"""In-memory key → KeyIndex ordered map (analog of
server/storage/mvcc/index.go treeIndex over google/btree; here a
SortedDict, the same O(log n) ordered-map contract)."""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

try:
    from sortedcontainers import SortedDict
except ImportError:  # gated dep: images without it use the fallback
    from ...pkg.sorteddict import SortedDict  # type: ignore[assignment]

from .key_index import KeyIndex, RevisionNotFound
from .revision import Revision


class TreeIndex:
    def __init__(self) -> None:
        self._tree: SortedDict = SortedDict()
        self._lock = threading.RLock()

    def put(self, key: bytes, rev: Revision) -> None:
        with self._lock:
            ki = self._tree.get(key)
            if ki is None:
                ki = KeyIndex(key=key)
                self._tree[key] = ki
            ki.put(rev.main, rev.sub)

    def restore_key(self, key: bytes, rev: Revision, created: Revision,
                    version: int) -> None:
        """Rebuild path: first sighting of a key seeds a keyIndex with
        the stored created/version; later sightings append normally."""
        with self._lock:
            ki = self._tree.get(key)
            if ki is None:
                ki = KeyIndex(key=key)
                ki.restore(created, rev, version)
                self._tree[key] = ki
            else:
                ki.put(rev.main, rev.sub)

    def tombstone(self, key: bytes, rev: Revision) -> None:
        with self._lock:
            ki = self._tree.get(key)
            if ki is None:
                raise RevisionNotFound()
            ki.tombstone(rev.main, rev.sub)

    def get(self, key: bytes, at_rev: int
            ) -> Tuple[Revision, Revision, int]:
        """(mod, created, version); raises RevisionNotFound."""
        with self._lock:
            ki = self._tree.get(key)
            if ki is None:
                raise RevisionNotFound()
            return ki.get(at_rev)

    def revisions(self, start: bytes, end: Optional[bytes], at_rev: int,
                  limit: int = 0) -> Tuple[List[Revision], int]:
        """Mod-revisions of keys in [start, end) visible at at_rev,
        plus the total count (limit applies to the list only).
        end=None → the single key `start`; end=b"" → open end, every
        key ≥ start (the \\x00 range sentinel resolves to this;
        ref: index.go Revisions)."""
        with self._lock:
            if end is None:
                try:
                    rev, _, _ = self.get(start, at_rev)
                    return [rev], 1
                except RevisionNotFound:
                    return [], 0
            revs: List[Revision] = []
            total = 0
            for key in self._tree.irange(start, end if end else None,
                                         inclusive=(True, False)):
                ki: KeyIndex = self._tree[key]
                try:
                    rev, _, _ = ki.get(at_rev)
                except RevisionNotFound:
                    continue
                total += 1
                if limit <= 0 or len(revs) < limit:
                    revs.append(rev)
            return revs, total

    def count_revisions(self, start: bytes, end: Optional[bytes],
                        at_rev: int) -> int:
        return self.revisions(start, end, at_rev)[1]

    def count_all(self, at_rev: int) -> int:
        """Live keys at at_rev over the WHOLE key space (no end bound —
        arbitrary bytes are legal keys)."""
        with self._lock:
            total = 0
            for ki in self._tree.values():
                try:
                    ki.get(at_rev)
                    total += 1
                except RevisionNotFound:
                    continue
            return total

    def range_since(self, start: bytes, end: Optional[bytes],
                    rev: int) -> List[Revision]:
        """All revisions ≥ rev touching keys in the range, ascending by
        revision — the watcher-replay scan (ref: index.go RangeSince)."""
        with self._lock:
            keys = (
                [start] if end is None
                else list(self._tree.irange(start, end if end else None,
                                            inclusive=(True, False)))
            )
            revs: List[Revision] = []
            for key in keys:
                ki = self._tree.get(key)
                if ki is None:
                    continue
                revs.extend(ki.since(rev))
            revs.sort()
            return revs

    def compact(self, at_rev: int) -> Dict[Revision, bool]:
        """Compact every keyIndex; returns the revisions that remain
        live in the backend (ref: index.go Compact)."""
        available: Dict[Revision, bool] = {}
        with self._lock:
            doomed: List[bytes] = []
            for key, ki in self._tree.items():
                ki.compact(at_rev, available)
                if ki.is_empty():
                    doomed.append(key)
            for key in doomed:
                del self._tree[key]
        return available

    def keep(self, at_rev: int) -> Dict[Revision, bool]:
        """The revisions a compaction at at_rev would keep, without
        mutating (ref: index.go Keep — used for HashKV)."""
        available: Dict[Revision, bool] = {}
        with self._lock:
            for _key, ki in self._tree.items():
                probe: Dict[Revision, bool] = {}
                ki._doompoint(at_rev, probe)
                available.update(probe)
        return available

    # -- txn rollback support -------------------------------------------------

    def snapshot_ki(self, key: bytes):
        """Deep copy of a keyIndex (or None) for write-txn rollback."""
        import copy

        with self._lock:
            ki = self._tree.get(key)
            return copy.deepcopy(ki) if ki is not None else None

    def restore_saved(self, key: bytes, saved) -> None:
        with self._lock:
            if saved is None:
                self._tree.pop(key, None)
            else:
                self._tree[key] = saved

    def __len__(self) -> int:
        with self._lock:
            return len(self._tree)
