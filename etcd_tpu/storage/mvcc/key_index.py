"""Per-key revision history: generations separated by tombstones.

A keyIndex tracks every revision that ever touched one key. A
*generation* is one create→…→delete lifespan; a tombstone ends a
generation and opens a fresh empty one. ``get(at_rev)`` walks the
newest generation not past at_rev; ``compact`` drops revisions ≤ the
compaction point while preserving the one revision still visible at it
(ref: server/storage/mvcc/key_index.go:70-137,204 — the behaviour
matrix in its doc comment is the spec this reimplements).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .revision import Revision


class RevisionNotFound(Exception):
    pass


@dataclass
class Generation:
    version: int = 0  # number of revisions in this generation
    created: Revision = field(default_factory=Revision)
    revs: List[Revision] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.revs

    def walk(self, fn) -> int:
        """Walk revs newest→oldest; return index of first rev where fn
        is False, or -1."""
        for i in range(len(self.revs) - 1, -1, -1):
            if not fn(self.revs[i]):
                return i
        return -1


@dataclass
class KeyIndex:
    key: bytes
    modified: Revision = field(default_factory=Revision)
    generations: List[Generation] = field(default_factory=list)

    def put(self, main: int, sub: int) -> None:
        rev = Revision(main, sub)
        if rev <= self.modified:
            raise ValueError(
                f"'put' with unexpected smaller revision {rev} <= {self.modified}"
            )
        if not self.generations:
            self.generations.append(Generation())
        g = self.generations[-1]
        if g.is_empty():
            g.created = rev
        g.revs.append(rev)
        g.version += 1
        self.modified = rev

    def restore(self, created: Revision, modified: Revision,
                version: int) -> None:
        """Seed a freshly-rebuilt keyIndex from a stored KeyValue row —
        compaction may have erased earlier revisions, so created/version
        come from the row, not from counting (ref: key_index.go restore)."""
        if self.generations:
            raise ValueError("restore on non-empty keyIndex")
        self.modified = modified
        self.generations.append(
            Generation(version=version, created=created, revs=[modified])
        )

    def tombstone(self, main: int, sub: int) -> None:
        if self.is_empty() or self.generations[-1].is_empty():
            raise RevisionNotFound()
        self.put(main, sub)
        self.generations.append(Generation())

    def get(self, at_rev: int) -> Tuple[Revision, Revision, int]:
        """(modified, created, version) of the key visible at at_rev.
        Raises RevisionNotFound if none (never created yet, deleted
        before at_rev, or compacted away)."""
        g = self._find_generation(at_rev)
        if g is None:
            raise RevisionNotFound()
        n = g.walk(lambda rev: rev.main > at_rev)
        if n != -1:
            return g.revs[n], g.created, g.version - (len(g.revs) - n - 1)
        raise RevisionNotFound()

    def since(self, rev: int) -> List[Revision]:
        """All revisions with main >= rev (ascending), at most one per
        main (the last sub wins) — feeds watcher replay
        (ref: key_index.go since)."""
        if self.is_empty():
            return []
        out: List[Revision] = []
        for g in self.generations:
            for r in g.revs:
                if r.main < rev:
                    continue
                if out and out[-1].main == r.main:
                    out[-1] = r
                else:
                    out.append(r)
        return out

    def is_empty(self) -> bool:
        return not self.generations or (
            len(self.generations) == 1 and self.generations[0].is_empty()
        )

    def _find_generation(self, rev: int) -> Optional[Generation]:
        """Newest generation containing rev (created ≤ rev and not ended
        before it)."""
        last = len(self.generations) - 1
        cg = last
        while cg >= 0:
            g = self.generations[cg]
            if g.is_empty():
                cg -= 1
                continue
            if cg != last:
                # tombstone of g is its final rev; if rev is at/after the
                # tombstone, the key was deleted at rev.
                if rev >= g.revs[-1].main:
                    return None
            if g.revs[0].main <= rev:
                return g
            cg -= 1
        return None

    def compact(self, at_rev: int,
                available: Dict[Revision, bool]) -> None:
        """Remove revisions with main <= at_rev except the newest one
        still visible at at_rev. Finished generations whose tombstone is
        ≤ at_rev disappear entirely (a compacted delete leaves nothing).
        `available` collects revisions that must stay in the backend.
        After compaction `is_empty()` may become True — the caller then
        drops the whole KeyIndex (ref: key_index.go compact doc table).
        """
        gen_idx, rev_idx = self._doompoint(at_rev, available)
        g = self.generations[gen_idx]
        if rev_idx != -1:
            g.revs = g.revs[rev_idx:]
        self.generations = self.generations[gen_idx:]
        if not self.generations:
            self.generations.append(Generation())

    def _doompoint(self, at_rev: int,
                   available: Dict[Revision, bool]) -> Tuple[int, int]:
        """(generation idx, rev idx) where compaction cuts: generations
        before gen_idx are dropped; within it, revs before rev_idx are
        dropped (rev_idx=-1 keeps it whole). Marks the surviving
        revision, if any, in `available`."""
        last = len(self.generations) - 1
        for gi, g in enumerate(self.generations):
            if g.is_empty():
                if gi == last:
                    return gi, -1
                continue
            # A finished generation ends in its tombstone; if that is at
            # or before at_rev the whole lifespan is invisible at at_rev.
            if gi != last and g.revs[-1].main <= at_rev:
                continue
            keep = -1
            for i, r in enumerate(g.revs):
                if r.main <= at_rev:
                    keep = i
                else:
                    break
            if keep == -1:
                return gi, -1  # generation starts after at_rev
            available[g.revs[keep]] = True
            return gi, keep
        return last, -1

    def __repr__(self) -> str:
        return (f"KeyIndex(key={self.key!r}, modified={self.modified}, "
                f"generations={self.generations})")
