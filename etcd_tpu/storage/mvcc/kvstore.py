"""The MVCC store: revisioned KV over (backend, treeIndex).

Layout and lifecycle mirror the reference store
(ref: server/storage/mvcc/kvstore.go:59-419):

* every write txn bumps ``current_rev``; each change writes the key
  bucket at the 17-byte revision key with a marshaled KeyValue (delete
  writes a tombstone-marked revision key with just the key);
* the in-memory TreeIndex maps user keys → revision history and is
  rebuilt from the backend on restore (kvstore.go:323-419);
* reads resolve (key range, at_rev) → revisions via the index, then
  point-read the backend at those revision keys
  (kvstore_txn.go:65 rangeKeys);
* ``compact(rev)`` drops index history and deletes dead revision keys,
  recording scheduled/finished compact revisions in the meta bucket so
  an interrupted compaction resumes on restore (kvstore.go:279,
  kvstore_compaction.go);
* ``hash_kv(rev)`` hashes live revision keys for corruption checks
  (kvstore.go HashStorage).
"""

from __future__ import annotations

import hashlib
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

from .. import backend as bk
from . import metrics as mmet
from .index import TreeIndex
from .key_index import RevisionNotFound
from .kv import Event, EventType, KeyValue, RangeOptions, RangeResult
from .revision import (
    Revision, bytes_to_rev, is_tombstone_key, rev_to_bytes, tombstone_key,
)

SCHEDULED_COMPACT_KEY = b"scheduledCompactRev"
FINISHED_COMPACT_KEY = b"finishedCompactRev"


class CompactedError(Exception):
    """Requested revision has been compacted (ref: ErrCompacted)."""


class FutureRevError(Exception):
    """Requested revision is in the future (ref: ErrFutureRev)."""


class KVStore:
    def __init__(self, backend: bk.Backend,
                 lessor: Optional[object] = None) -> None:
        self.b = backend
        self.lessor = lessor
        self.index = TreeIndex()
        self._lock = threading.RLock()
        self.current_rev = 1  # rev of the last completed write txn
        self.compact_rev = 0
        self._fifo_restore()

    # -- restore --------------------------------------------------------------

    def _fifo_restore(self) -> None:
        """Rebuild index + revision counters from the backend
        (ref: kvstore.go:323 restore)."""
        rt = self.b.read_tx()
        fin = rt.get(bk.META, FINISHED_COMPACT_KEY)
        if fin is not None:
            self.compact_rev = struct.unpack("<q", fin)[0]
        rows = rt.range(bk.KEY, b"", b"\xff" * 32)
        # Lease attachments reflect only each key's LATEST state: later
        # revisions override, tombstones clear (ref: kvstore.go restore
        # builds keyToLease the same way). Attaching per historical row
        # would resurrect stale attachments and delete live keys on
        # lease expiry.
        key_lease: Dict[bytes, int] = {}
        for rkey, rval in rows:
            rev = bytes_to_rev(rkey)
            self.current_rev = rev.main
            if is_tombstone_key(rkey):
                try:
                    self.index.tombstone(rval, rev)
                except RevisionNotFound:
                    pass  # creation compacted away; tombstone is stale
                key_lease.pop(rval, None)
                continue
            kv = KeyValue.unmarshal(rval)
            self.index.restore_key(
                kv.key, rev, Revision(kv.create_revision, 0), kv.version
            )
            key_lease[kv.key] = kv.lease
        if self.lessor is not None:
            from ...lease.lessor import LeaseNotFoundError

            for key, lease_id in key_lease.items():
                if not lease_id:
                    continue
                try:
                    self.lessor.attach(lease_id, key)
                except LeaseNotFoundError:
                    pass  # revoked after the final put; nothing to attach
        # A fully-compacted store can have ZERO revision rows; the
        # revision counter must still resume at the compaction point
        # (ref: kvstore.go restore: currentRev = max(currentRev,
        # compactMainRev)).
        self.current_rev = max(self.current_rev, self.compact_rev)
        sched = rt.get(bk.META, SCHEDULED_COMPACT_KEY)
        if sched is not None:
            srev = struct.unpack("<q", sched)[0]
            if srev > self.compact_rev:
                self.compact(srev)  # resume interrupted compaction
        mmet.keys_total.set(self.index.count_all(self.current_rev))

    # -- read path ------------------------------------------------------------

    def rev(self) -> int:
        with self._lock:
            return self.current_rev

    def first_rev(self) -> int:
        with self._lock:
            return self.compact_rev + 1

    def range(self, key: bytes, end: Optional[bytes],
              opts: Optional[RangeOptions] = None) -> RangeResult:
        opts = opts or RangeOptions()
        with self._lock:
            cur = self.current_rev
            at_rev = opts.rev if opts.rev > 0 else cur
            if at_rev < self.compact_rev:
                raise CompactedError()
            if at_rev > cur:
                raise FutureRevError()
            if opts.count_only:
                total = self.index.count_revisions(key, end, at_rev)
                return RangeResult(kvs=[], rev=cur, count=total)
            revs, total = self.index.revisions(key, end, at_rev, opts.limit)
            # Read rows while still holding the store lock so a
            # concurrent compact() cannot delete a resolved revision
            # between index lookup and backend read (the reference pins
            # a bolt read tx for the same reason, backend.go:249).
            rt = self.b.read_tx()
            kvs: List[KeyValue] = []
            for r in revs:
                rows = rt.range(bk.KEY, rev_to_bytes(r), None)
                if not rows:
                    raise RuntimeError(
                        f"revision {r} in index but missing from backend"
                    )
                kvs.append(KeyValue.unmarshal(rows[0][1]))
            return RangeResult(kvs=kvs, rev=cur, count=total)

    # -- write path -----------------------------------------------------------

    def write(self) -> "WriteTxn":
        return WriteTxn(self)

    def put(self, key: bytes, value: bytes, lease: int = 0) -> int:
        with self.write() as tx:
            tx.put(key, value, lease)
        return tx.rev  # read after __exit__ bumps it

    def delete_range(self, key: bytes,
                     end: Optional[bytes]) -> Tuple[int, int]:
        """(deleted_count, rev)."""
        with self.write() as tx:
            n = tx.delete_range(key, end)
        return n, tx.rev

    # -- compaction -----------------------------------------------------------

    def compact(self, at_rev: int) -> int:
        """Synchronous compaction (the reference schedules chunks; our
        backend scan is one pass). Returns the compacted revision."""
        with self._lock:
            if at_rev <= self.compact_rev:
                raise CompactedError()
            if at_rev > self.current_rev:
                raise FutureRevError()
            self.compact_rev = at_rev
            with self.b.batch_tx.lock:
                self.b.batch_tx.put(
                    bk.META, SCHEDULED_COMPACT_KEY, struct.pack("<q", at_rev)
                )
            keep = self.index.compact(at_rev)
            # Delete revision keys ≤ at_rev not in the keep set — still
            # under the store lock so readers never see the index and
            # backend disagree.
            end = rev_to_bytes(Revision(at_rev + 1, 0))
            rt = self.b.read_tx()
            with self.b.batch_tx.lock:
                for rkey, _ in rt.range(bk.KEY, b"", end):
                    base = rkey[:17]
                    rev = bytes_to_rev(base)
                    if rev.main > at_rev:
                        continue
                    if keep.get(rev) and not is_tombstone_key(rkey):
                        continue
                    self.b.batch_tx.delete(bk.KEY, rkey)
                self.b.batch_tx.put(
                    bk.META, FINISHED_COMPACT_KEY, struct.pack("<q", at_rev)
                )
        return at_rev

    # -- integrity ------------------------------------------------------------

    def hash_kv(self, rev: int = 0) -> Tuple[int, int, int]:
        """(hash, current_rev, compact_rev): crc-style digest over live
        revision keys ≤ rev (ref: kvstore.go HashByRev)."""
        with self._lock:
            cur = self.current_rev
            if rev == 0 or rev > cur:
                rev = cur
            if rev < self.compact_rev:
                raise CompactedError()
            keep = self.index.keep(rev)
        h = hashlib.sha256()
        rt = self.b.concurrent_read_tx()
        upper = rev_to_bytes(Revision(rev + 1, 0))
        for rkey, rval in rt.range(bk.KEY, b"", upper):
            kv_rev = bytes_to_rev(rkey[:17])
            if kv_rev.main <= self.compact_rev and kv_rev not in keep:
                continue
            h.update(rkey)
            h.update(rval)
        digest = int.from_bytes(h.digest()[:8], "big")
        return digest, cur, self.compact_rev


class WriteTxn:
    """One write transaction: all changes share main revision
    current_rev+1, sub revisions order them; commit bumps current_rev
    (ref: kvstore_txn.go:133 storeTxnWrite).

    Mutations apply eagerly to index+backend; an exception inside the
    ``with`` block rolls them back (saved KeyIndex copies are restored
    and written revision rows deleted), so an aborted txn leaves no
    trace and the next txn reuses the revision."""

    def __init__(self, store: KVStore,
                 on_end: Optional[Callable[["WriteTxn"], None]] = None
                 ) -> None:
        self.s = store
        self.changes: List[Event] = []
        self._on_end = on_end
        self._saved_ki: Dict[bytes, object] = {}  # key -> KeyIndex copy|None
        self._written_rows: List[bytes] = []
        self._keys_delta = 0  # live-key gauge delta, applied on commit

    def __enter__(self) -> "WriteTxn":
        self.s._lock.acquire()
        self.s.b.batch_tx.lock.acquire()
        self.rev = self.s.current_rev  # updated on first change
        return self

    def __exit__(self, exc_type, *rest) -> None:
        committed = exc_type is None and bool(self.changes)
        try:
            if committed:
                self.s.current_rev += 1
                self.rev = self.s.current_rev
                if self._keys_delta > 0:
                    mmet.keys_total.inc(self._keys_delta)
                elif self._keys_delta < 0:
                    mmet.keys_total.dec(-self._keys_delta)
                # Notify while both locks are held so watchers observe
                # revisions in commit order (the reference notifies in
                # txn End under the store mutex).
                if self._on_end is not None:
                    self._on_end(self)
            elif exc_type is not None and (
                    self.changes or self._written_rows):
                self._rollback()
        finally:
            self.s.b.batch_tx.lock.release()
            self.s._lock.release()

    def _rollback(self) -> None:
        for rkey in self._written_rows:
            self.s.b.batch_tx.delete(bk.KEY, rkey)
        for key, saved in self._saved_ki.items():
            self.s.index.restore_saved(key, saved)
        self.changes.clear()

    def _save_ki(self, key: bytes) -> None:
        if key not in self._saved_ki:
            self._saved_ki[key] = self.s.index.snapshot_ki(key)

    def _next_rev(self) -> Revision:
        return Revision(self.s.current_rev + 1, len(self.changes))

    def put(self, key: bytes, value: bytes, lease: int = 0) -> None:
        rev = self._next_rev()
        created = rev.main
        version = 1
        prev_lease = 0
        try:
            mod, c, ver = self.s.index.get(key, rev.main)
            created = c.main
            version = ver + 1
            prev = self._read_at(mod)
            prev_lease = prev.lease if prev else 0
        except RevisionNotFound:
            pass
        kv = KeyValue(
            key=key, create_revision=created, mod_revision=rev.main,
            version=version, value=value, lease=lease,
        )
        self._save_ki(key)
        rkey = rev_to_bytes(rev)
        self.s.b.batch_tx.put(bk.KEY, rkey, kv.marshal())
        self._written_rows.append(rkey)
        self.s.index.put(key, rev)
        if version == 1:
            self._keys_delta += 1  # new live key (ref: kvstore_txn.go put)
        self.changes.append(Event(type=EventType.PUT, kv=kv))
        les = self.s.lessor
        if les is not None:
            if prev_lease:
                les.detach(prev_lease, key)
            if lease:
                les.attach(lease, key)

    def delete_range(self, key: bytes, end: Optional[bytes]) -> int:
        # Resolve at current_rev+1 so deletes see this txn's own puts.
        revs, _ = self.s.index.revisions(key, end, self.s.current_rev + 1)
        if not revs:
            return 0
        keys = []
        rt = self.s.b.read_tx()
        for r in revs:
            rows = rt.range(bk.KEY, rev_to_bytes(r), None)
            keys.append(KeyValue.unmarshal(rows[0][1]))
        for prev_kv in keys:
            rev = self._next_rev()
            rkey = tombstone_key(rev_to_bytes(rev))
            # tombstone rows store just the user key (enough to rebuild
            # the index on restore)
            self._save_ki(prev_kv.key)
            self.s.b.batch_tx.put(bk.KEY, rkey, prev_kv.key)
            self._written_rows.append(rkey)
            self.s.index.tombstone(prev_kv.key, rev)
            self._keys_delta -= 1
            self.changes.append(Event(
                type=EventType.DELETE,
                kv=KeyValue(key=prev_kv.key, mod_revision=rev.main),
                prev_kv=prev_kv,
            ))
            if self.s.lessor is not None and prev_kv.lease:
                self.s.lessor.detach(prev_kv.lease, prev_kv.key)
        return len(keys)

    def range(self, key: bytes, end: Optional[bytes],
              opts: Optional[RangeOptions] = None) -> RangeResult:
        """Read inside the write txn (sees txn's own writes since the
        index/backend are updated eagerly)."""
        opts = opts or RangeOptions()
        at_rev = opts.rev if opts.rev > 0 else self.s.current_rev + (
            1 if self.changes else 0
        )
        # Same revision bounds as the store-level read path (ref:
        # kvstore_txn.go rangeKeys checks both on every txn read).
        if opts.rev > 0:
            if at_rev < self.s.compact_rev:
                raise CompactedError()
            if at_rev > self.s.current_rev + (1 if self.changes else 0):
                raise FutureRevError()
        revs, total = self.s.index.revisions(key, end, at_rev, opts.limit)
        if opts.count_only:
            return RangeResult(kvs=[], rev=self.s.current_rev, count=total)
        rt = self.s.b.read_tx()
        kvs = []
        for r in revs:
            rows = rt.range(bk.KEY, rev_to_bytes(r), None)
            kvs.append(KeyValue.unmarshal(rows[0][1]))
        return RangeResult(kvs=kvs, rev=self.s.current_rev, count=total)

    def _read_at(self, rev: Revision) -> Optional[KeyValue]:
        rows = self.s.b.read_tx().range(bk.KEY, rev_to_bytes(rev), None)
        return KeyValue.unmarshal(rows[0][1]) if rows else None
