"""Revision: the (main, sub) logical clock of the mvcc store.

``main`` increments once per transaction; ``sub`` orders changes within
one transaction. On-disk keys in the "key" bucket are the 17-byte
big-endian encoding [8B main]['_'][8B sub], optionally followed by 't'
to mark a tombstone — byte order equals revision order, so backend
range scans iterate history in revision order
(ref: server/storage/mvcc/revision.go).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class Revision:
    main: int = 0
    sub: int = 0


REV_BYTES_LEN = 17
_MARK_TOMBSTONE = b"t"


def rev_to_bytes(rev: Revision) -> bytes:
    return struct.pack(">Q", rev.main) + b"_" + struct.pack(">Q", rev.sub)


def bytes_to_rev(b: bytes) -> Revision:
    main = struct.unpack_from(">Q", b, 0)[0]
    sub = struct.unpack_from(">Q", b, 9)[0]
    return Revision(main, sub)


def tombstone_key(b: bytes) -> bytes:
    return b + _MARK_TOMBSTONE


def is_tombstone_key(b: bytes) -> bool:
    return len(b) == REV_BYTES_LEN + 1 and b.endswith(_MARK_TOMBSTONE)
