"""Multi-version KV store (analog of server/storage/mvcc)."""

from .revision import Revision, rev_to_bytes, bytes_to_rev, tombstone_key
from .kv import KeyValue, Event, EventType, RangeOptions, RangeResult
from .key_index import KeyIndex
from .index import TreeIndex
from .kvstore import KVStore, CompactedError, FutureRevError
from .watchable import WatchableStore, WatchStream

__all__ = [
    "Revision", "rev_to_bytes", "bytes_to_rev", "tombstone_key",
    "KeyValue", "Event", "EventType", "RangeOptions", "RangeResult",
    "KeyIndex", "TreeIndex", "KVStore", "CompactedError", "FutureRevError",
    "WatchableStore", "WatchStream",
]
