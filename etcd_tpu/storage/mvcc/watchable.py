"""Watchable store: event fanout over the MVCC store.

Same machinery as the reference (ref:
server/storage/mvcc/watchable_store.go:47-510, watcher_group.go):

* watchers hold a key or [key, end) range and a start revision;
* the **synced** group gets events inline as write txns end
  (``notify``); watchers whose start revision is behind go to the
  **unsynced** group and are caught up by a background ``sync_watchers``
  pass that replays history out of the store index/backend
  (watchable_store.go:331-408);
* a watcher whose channel is full becomes a **victim** and is retried
  asynchronously with the events it missed (watchable_store.go victim
  loop) — here the channel is an unbounded deque, so victimhood is
  modeled with an explicit per-watcher cap to preserve the slow-watcher
  semantics;
* watcher groups index range watchers in an interval tree
  (watcher_group.go uses pkg/adt) for O(log n + matches) fanout.

The WatchStream facade matches mvcc/watcher.go: watch/cancel/progress
over a shared event queue.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple

from ...pkg.adt import INF, Interval, IntervalTree, point_interval
from . import metrics as mmet
from .kv import Event, EventType, KeyValue
from .kvstore import KVStore
from .revision import rev_to_bytes

# How many buffered events mark a watcher as slow (victim); the
# reference uses chanBufLen 128 on the watch channel.
DEFAULT_BUFFER_CAP = 1024

# Open-ended watch ranges (end = the "\x00" sentinel) use a true +inf
# endpoint in the interval tree — any finite byte string would miss
# events for keys sorting above it.
WATCH_OPEN_MAX = INF


@dataclass
class WatchResponse:
    watch_id: int
    events: List[Event]
    revision: int  # store revision when sent
    compact_revision: int = 0  # nonzero → watcher cancelled at compaction


class Watcher:
    def __init__(self, wid: int, key: bytes, end: Optional[bytes],
                 start_rev: int, fcs: List[Callable[[Event], bool]],
                 sink: "WatchStream") -> None:
        self.id = wid
        self.key = key
        self.end = end
        self.min_rev = start_rev  # next revision this watcher needs
        self.filters = fcs
        self.sink = sink
        self.compacted = False
        self.victim = False

    def interval(self) -> Interval:
        if self.end is None:
            return point_interval(self.key)
        return Interval(self.key, self.end if self.end else WATCH_OPEN_MAX)

    def send(self, resp: WatchResponse) -> bool:
        if self.filters:
            resp.events = [
                e for e in resp.events
                if not any(f(e) for f in self.filters)
            ]
            if not resp.events and resp.compact_revision == 0:
                return True
        return self.sink._deliver(resp)


class WatcherGroup:
    """Point watchers by key + range watchers in an interval tree."""

    def __init__(self) -> None:
        self.keys: Dict[bytes, Set[Watcher]] = {}
        self.ranges = IntervalTree()
        self.watchers: Set[Watcher] = set()

    def add(self, w: Watcher) -> None:
        self.watchers.add(w)
        if w.end is None:
            self.keys.setdefault(w.key, set()).add(w)
            return
        ivl = w.interval()
        ws = self.ranges.find(ivl)
        if ws is None:
            self.ranges.insert(ivl, {w})
        else:
            ws.add(w)

    def remove(self, w: Watcher) -> bool:
        if w not in self.watchers:
            return False
        self.watchers.discard(w)
        if w.end is None:
            s = self.keys.get(w.key)
            if s is not None:
                s.discard(w)
                if not s:
                    del self.keys[w.key]
            return True
        ivl = w.interval()
        ws = self.ranges.find(ivl)
        if ws is not None:
            ws.discard(w)
            if not ws:
                self.ranges.delete(ivl)
        return True

    def matching(self, key: bytes) -> List[Watcher]:
        out = list(self.keys.get(key, ()))
        for ws in self.ranges.stab(key):
            out.extend(ws)
        return out

    def choose_min_rev(self, max_watchers: int, cur_rev: int,
                       compact_rev: int) -> Tuple[List[Watcher], int]:
        """Pick ≤ max_watchers unsynced watchers and the min revision to
        replay from; watchers behind the compaction point are marked
        compacted (ref: watcher_group.go chooseAll)."""
        chosen: List[Watcher] = []
        min_rev = cur_rev + 1
        for w in list(self.watchers)[:max_watchers]:
            if w.min_rev < compact_rev + 1:
                w.compacted = True
            chosen.append(w)
            if not w.compacted and w.min_rev < min_rev:
                min_rev = w.min_rev
        return chosen, min_rev

    def __len__(self) -> int:
        return len(self.watchers)


class WatchableStore(KVStore):
    def __init__(self, backend, lessor=None,
                 buffer_cap: int = DEFAULT_BUFFER_CAP) -> None:
        self._wlock = threading.RLock()
        self.synced = WatcherGroup()
        self.unsynced = WatcherGroup()
        self._victims: List[Tuple[Watcher, List[Event]]] = []
        self._buffer_cap = buffer_cap
        self._next_watch_id = 0
        # Max distinct revisions per unsynced replay response
        # (ref: watchable_store.go watchBatchMaxRevs = 1000).
        self.watch_batch_max_revs = 1000
        super().__init__(backend, lessor)

    # -- KVStore write hook ----------------------------------------------------

    def write(self):
        from .kvstore import WriteTxn

        return WriteTxn(
            self, on_end=lambda tx: self.notify(tx.rev, tx.changes)
        )

    # -- watch API -------------------------------------------------------------

    def new_watch_stream(self) -> "WatchStream":
        return WatchStream(self)

    def watch(self, key: bytes, end: Optional[bytes], start_rev: int,
              sink: "WatchStream", wid: Optional[int] = None,
              fcs: Optional[List[Callable[[Event], bool]]] = None) -> Watcher:
        # Lock order everywhere: store _lock → watch _wlock (notify runs
        # inside the write txn with _lock held).
        with self._lock, self._wlock:
            if wid is None:
                wid = self._next_watch_id
                self._next_watch_id += 1
            w = Watcher(wid, key, end, start_rev, fcs or [], sink)
            cur = self.rev()
            if start_rev == 0 or start_rev > cur:
                # A future-rev watcher is synced but keeps its start
                # revision: notify must not hand it events below it
                # (ref: watchable_store.go:128-136).
                w.min_rev = max(cur + 1, start_rev)
                self.synced.add(w)
            else:
                self.unsynced.add(w)
            mmet.watcher_total.inc()
            self._update_slow_gauge()
            return w

    def cancel_watcher(self, w: Watcher) -> bool:
        with self._wlock:
            found = self.synced.remove(w) or self.unsynced.remove(w)
            if not found:
                for i, (vw, _) in enumerate(self._victims):
                    if vw is w:
                        del self._victims[i]
                        found = True
                        break
            if found:
                mmet.watcher_total.dec()
            self._update_slow_gauge()
            return found

    # -- fanout ----------------------------------------------------------------

    def notify(self, rev: int, events: List[Event]) -> None:
        """Send events to synced watchers; slow ones become victims
        (ref: watchable_store.go:434 notify)."""
        with self._wlock:
            per_w: Dict[Watcher, List[Event]] = {}
            for ev in events:
                for w in self.synced.matching(ev.kv.key):
                    # Future-rev watchers wait for their start revision
                    # (ref: watcher_group.go newWatcherBatch minRev gate).
                    if ev.kv.mod_revision < w.min_rev:
                        continue
                    per_w.setdefault(w, []).append(ev)
            for w, evs in per_w.items():
                ok = w.send(WatchResponse(w.id, evs, rev))
                if ok:
                    mmet.events_total.inc(len(evs))
                if not ok:
                    # victim: move out of synced, retry async
                    self.synced.remove(w)
                    w.victim = True
                    w.min_rev = rev + 1
                    self._victims.append((w, evs))
            self._update_slow_gauge()

    def sync_watchers(self, max_watchers: int = 512) -> int:
        """One pass of the unsynced catch-up loop; returns watchers
        still unsynced (ref: watchable_store.go:331 syncWatchers)."""
        with self._lock, self._wlock:
            if len(self.unsynced) == 0 and not self._victims:
                return 0
            self._retry_victims()
            if len(self.unsynced) == 0:
                self._update_slow_gauge()
                return len(self.unsynced)
            cur = self.rev()
            compact = self.compact_rev
            chosen, min_rev = self.unsynced.choose_min_rev(
                max_watchers, cur, compact
            )
            revs = self.index.range_since(b"", b"", min_rev)
            evs = self._events_from_revs(revs)
            for w in chosen:
                if w.compacted:
                    w.send(WatchResponse(w.id, [], cur,
                                         compact_revision=compact))
                    self.unsynced.remove(w)
                    mmet.watcher_total.dec()  # cancelled at compaction
                    continue
                mine = [
                    e for e in evs
                    if e.kv.mod_revision >= w.min_rev and self._match(w, e)
                ]
                # Cap one replay response to WATCH_BATCH_MAX_REVS
                # distinct revisions; a capped watcher stays unsynced
                # with min_rev at the first undelivered revision
                # (ref: watchable_store.go watchBatchMaxRevs +
                # watcher_group.go newWatcherBatch moreRev).
                more_rev = 0
                if mine:
                    distinct, last, cut = 0, -1, len(mine)
                    for i, e in enumerate(mine):
                        r = e.kv.mod_revision
                        if r != last:
                            distinct += 1
                            last = r
                            if distinct > self.watch_batch_max_revs:
                                cut, more_rev = i, r
                                break
                    mine = mine[:cut]
                if mine and not w.send(
                        WatchResponse(w.id, mine, cur)):
                    w.victim = True
                    w.min_rev = more_rev or cur + 1
                    self.unsynced.remove(w)
                    self._victims.append((w, mine))
                    continue
                if mine:
                    mmet.events_total.inc(len(mine))
                if more_rev:
                    w.min_rev = more_rev  # stay unsynced; next pass
                    continue
                w.min_rev = cur + 1
                self.unsynced.remove(w)
                self.synced.add(w)
            self._update_slow_gauge()
            return len(self.unsynced)

    def start_sync_loop(self, interval: float = 0.1) -> None:
        """The unsynced catch-up + victim retry loop
        (ref: watchable_store.go:211 syncWatchersLoop, every 100ms)."""
        if getattr(self, "_sync_stop", None) is not None:
            return
        stop = threading.Event()
        self._sync_stop = stop

        def loop() -> None:
            while not stop.wait(interval):
                try:
                    self.sync_watchers()
                except Exception:  # noqa: BLE001 — keep the loop alive
                    pass

        self._sync_thread = threading.Thread(target=loop, daemon=True)
        self._sync_thread.start()

    def stop_sync_loop(self) -> None:
        stop = getattr(self, "_sync_stop", None)
        if stop is not None:
            stop.set()
            self._sync_stop = None
            t = getattr(self, "_sync_thread", None)
            if t is not None and t.is_alive():
                t.join(timeout=5)

    def _retry_victims(self) -> None:
        still: List[Tuple[Watcher, List[Event]]] = []
        for w, evs in self._victims:
            if w.send(WatchResponse(w.id, evs,
                                    evs[-1].kv.mod_revision if evs else
                                    self.rev())):
                mmet.events_total.inc(len(evs))
                w.victim = False
                # Writes may have happened while victimized; if so the
                # watcher needs history replay before going live again
                # (ref: watchable_store.go moveVictims).
                if w.min_rev <= self.rev():
                    self.unsynced.add(w)
                else:
                    self.synced.add(w)
            else:
                still.append((w, evs))
        self._victims = still

    def _update_slow_gauge(self) -> None:
        mmet.slow_watcher_total.set(len(self.unsynced) + len(self._victims))
        mmet.pending_events_total.set(
            sum(len(evs) for _, evs in self._victims)
        )

    @staticmethod
    def _match(w: Watcher, ev: Event) -> bool:
        if w.end is None:
            return ev.kv.key == w.key
        if not w.end:  # open end (the \x00 sentinel)
            return ev.kv.key >= w.key
        return w.key <= ev.kv.key < w.end

    def _events_from_revs(self, revs) -> List[Event]:
        from .. import backend as bk
        rt = self.b.read_tx()
        evs: List[Event] = []
        for r in revs:
            base = rev_to_bytes(r)
            rows = rt.range(bk.KEY, base, base + b"\xff")
            for rkey, rval in rows:
                if len(rkey) == 18:  # tombstone row
                    evs.append(Event(
                        type=EventType.DELETE,
                        kv=KeyValue(key=rval, mod_revision=r.main),
                    ))
                else:
                    evs.append(Event(type=EventType.PUT,
                                     kv=KeyValue.unmarshal(rval)))
        return evs


class WatcherDuplicateIDError(Exception):
    """ref: mvcc.ErrWatcherDuplicateID."""


class EmptyWatcherRangeError(Exception):
    """ref: mvcc.ErrEmptyWatcherRange — key >= end describes no keys."""


class WatchStream:
    """Client-facing handle multiplexing many watchers onto one queue
    (ref: mvcc/watcher.go:108 watchStream)."""

    def __init__(self, store: WatchableStore) -> None:
        self._s = store
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._q: Deque[WatchResponse] = deque()
        self._watchers: Dict[int, Watcher] = {}
        self._next_id = 0  # per-stream auto ids (ref: watcher.go Watch)
        self._closed = False
        mmet.watch_stream_total.inc()

    # watchers call this; False → would exceed cap (victim path)
    def _deliver(self, resp: WatchResponse) -> bool:
        with self._lock:
            if self._closed:
                return True  # drop silently after close
            if len(self._q) >= self._s._buffer_cap:
                return False
            self._q.append(resp)
            self._cond.notify_all()
            return True

    def watch(self, key: bytes, end: Optional[bytes] = None,
              start_rev: int = 0, wid: Optional[int] = None,
              fcs=None) -> int:
        """end semantics: None = single key; b"" = open-ended (every
        key >= key); otherwise end must sort above key
        (ref: watcher.go:108-136 Watch)."""
        if end is not None and end != b"" and end <= key:
            raise EmptyWatcherRangeError()
        with self._lock:
            if wid is not None:
                if wid in self._watchers:
                    raise WatcherDuplicateIDError()
            else:
                # Per-stream auto assignment skips manually-taken ids.
                while self._next_id in self._watchers:
                    self._next_id += 1
                wid = self._next_id
                self._next_id += 1
        w = self._s.watch(key, end, start_rev, self, wid=wid, fcs=fcs)
        with self._lock:
            self._watchers[w.id] = w
        return w.id

    def cancel(self, wid: int) -> bool:
        with self._lock:
            w = self._watchers.pop(wid, None)
        return self._s.cancel_watcher(w) if w is not None else False

    def request_progress(self, wid: int) -> None:
        with self._lock:
            w = self._watchers.get(wid)
        if w is None:
            return
        # Only a synced watcher may advertise the current revision: an
        # unsynced/victim watcher has not delivered everything below it
        # (ref: watchable_store.go progress()).
        with self._s._lock, self._s._wlock:
            if w not in self._s.synced.watchers:
                return
            rev = self._s.rev()
        self._deliver(WatchResponse(wid, [], rev))

    def poll(self, timeout: Optional[float] = None) -> Optional[WatchResponse]:
        with self._lock:
            if not self._q:
                self._cond.wait(timeout)
            return self._q.popleft() if self._q else None

    def pending(self) -> int:
        with self._lock:
            return len(self._q)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            wids = list(self._watchers)
            self._closed = True
            self._cond.notify_all()
        mmet.watch_stream_total.dec()
        for wid in wids:
            self.cancel(wid)
