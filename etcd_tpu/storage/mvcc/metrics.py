"""mvcc metric set (ref: server/storage/mvcc/metrics.go).

Process-global like the reference's prometheus registry (one member per
process is the deployment model); in-proc multi-member test clusters
share these, so gauges mix members — assert on per-store state, not
gauges, in such harnesses."""

from __future__ import annotations

from ...pkg import metrics as m

db_total_size = m.gauge(
    "etcd_mvcc_db_total_size_in_bytes", "Total size of the underlying database physically allocated in bytes."
)
db_in_use_size = m.gauge(
    "etcd_mvcc_db_total_size_in_use_in_bytes", "Total size of the underlying database logically in use in bytes."
)
keys_total = m.gauge(
    "etcd_debugging_mvcc_keys_total", "Total number of keys."
)
range_total = m.counter(
    "etcd_mvcc_range_total", "Total number of ranges seen by this member."
)
put_total = m.counter(
    "etcd_mvcc_put_total", "Total number of puts seen by this member."
)
delete_total = m.counter(
    "etcd_mvcc_delete_total", "Total number of deletes seen by this member."
)
txn_total = m.counter(
    "etcd_mvcc_txn_total", "Total number of txns seen by this member."
)
watch_stream_total = m.gauge(
    "etcd_debugging_mvcc_watch_stream_total", "Total number of watch streams."
)
watcher_total = m.gauge(
    "etcd_debugging_mvcc_watcher_total", "Total number of watchers."
)
slow_watcher_total = m.gauge(
    "etcd_debugging_mvcc_slow_watcher_total", "Total number of unsynced slow watchers."
)
events_total = m.counter(
    "etcd_debugging_mvcc_events_total", "Total number of events sent by this member."
)
pending_events_total = m.gauge(
    "etcd_debugging_mvcc_pending_events_total", "Total number of pending events to be sent."
)
compact_revision = m.gauge(
    "etcd_debugging_mvcc_compact_revision", "The revision of the last compaction in store."
)
current_revision = m.gauge(
    "etcd_debugging_mvcc_current_revision", "The current revision of store."
)
