"""Transactional bucketed KV backend — the bbolt analog.

The reference stores everything durable-but-queryable (mvcc revisions,
membership, leases, auth, alarms, meta) in one bbolt B+tree file with
batched commits (ref: server/storage/backend/backend.go:47-160). This
backend keeps the same shape and contract over sqlite3 — a native
B-tree engine baked into CPython:

* **buckets** → one two-column table per bucket (key BLOB PRIMARY KEY,
  value BLOB) so range scans ride the B-tree index;
* **batch_tx** → a single long-lived write transaction on the writer
  connection, auto-committed every ``batch_interval`` (100 ms) or
  ``batch_limit`` (10k) pending ops — the reference's batchTxBuffered
  cadence (backend.go:131-160);
* **read_tx** → reads on the writer connection, which see the open
  batch transaction (committed + buffered writes, like the reference's
  txReadBuffer merge);
* **concurrent_read_tx** → reads on a separate connection (WAL mode:
  sees only committed state) merged with a snapshot of the write
  buffer taken at creation — long scans never block the writer
  (backend.go:249 ConcurrentReadTx);
* **commit hooks** run inside every batch commit (ref:
  server/storage/hooks.go — the consistent-index persister);
* **defrag** → VACUUM (backend.go:447); size/size_in_use and commit
  counters feed the metrics surface.

Thread model: mutators serialize on ``batch_tx.lock`` exactly like the
reference's batchTx mutex; sqlite3 runs in serialized threading mode.
"""

from __future__ import annotations

import os
import sqlite3
import threading
import time

from . import metrics as dmet
from typing import Callable, Dict, List, Optional, Tuple

DEFAULT_BATCH_INTERVAL = 0.1  # seconds (ref: defaultBatchInterval 100ms)
DEFAULT_BATCH_LIMIT = 10000  # ops (ref: defaultBatchLimit)

_MAX_KEY = b"\xff" * 128


class Bucket:
    """A named keyspace. Instances are cheap views; identity is the name."""

    __slots__ = ("name", "table")

    def __init__(self, name: str) -> None:
        self.name = name
        if not name.replace("_", "").isalnum():
            raise ValueError(f"bad bucket name {name!r}")
        self.table = f"bucket_{name}"


# The reference's schema buckets (server/storage/schema/bucket.go).
KEY = Bucket("key")
META = Bucket("meta")
LEASE = Bucket("lease")
ALARM = Bucket("alarm")
CLUSTER = Bucket("cluster")
MEMBERS = Bucket("members")
MEMBERS_REMOVED = Bucket("members_removed")
AUTH = Bucket("auth")
AUTH_USERS = Bucket("authUsers")
AUTH_ROLES = Bucket("authRoles")
TEST = Bucket("test")

ALL_BUCKETS = [KEY, META, LEASE, ALARM, CLUSTER, MEMBERS, MEMBERS_REMOVED,
               AUTH, AUTH_USERS, AUTH_ROLES, TEST]


class BatchTx:
    """The single buffered write transaction (writer connection)."""

    def __init__(self, backend: "Backend") -> None:
        self._b = backend
        self.lock = threading.RLock()
        self._pending = 0
        # Overlay mirror of uncommitted writes, only consumed by
        # concurrent_read_tx snapshots: bucket -> {key: value|None}.
        self._buf: Dict[str, Dict[bytes, Optional[bytes]]] = {}

    # -- mutations (callers hold .lock) --------------------------------------

    def put(self, bucket: Bucket, key: bytes, value: bytes) -> None:
        self._b._exec(
            f"INSERT INTO {bucket.table}(k, v) VALUES(?, ?) "
            f"ON CONFLICT(k) DO UPDATE SET v=excluded.v",
            (key, value),
        )
        self._buf.setdefault(bucket.name, {})[bytes(key)] = bytes(value)
        self._pending += 1
        if self._pending >= self._b.batch_limit:
            self.commit()

    def delete(self, bucket: Bucket, key: bytes) -> None:
        self._b._exec(f"DELETE FROM {bucket.table} WHERE k=?", (key,))
        self._buf.setdefault(bucket.name, {})[bytes(key)] = None
        self._pending += 1

    def delete_range(self, bucket: Bucket, start: bytes,
                     end: Optional[bytes]) -> int:
        """Delete [start, end); end=None deletes just `start`."""
        if end is None:
            self.delete(bucket, start)
            return 1
        doomed = [
            k for k, _ in self._b._query_writer(bucket, start, end)
        ]
        cur = self._b._exec(
            f"DELETE FROM {bucket.table} WHERE k>=? AND k<?", (start, end)
        )
        buf = self._buf.setdefault(bucket.name, {})
        for k in doomed:
            buf[k] = None
        self._pending += 1
        return cur.rowcount

    def unsafe_create_bucket(self, bucket: Bucket) -> None:
        self._b._exec(
            f"CREATE TABLE IF NOT EXISTS {bucket.table} "
            f"(k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID"
        )

    def pending(self) -> int:
        return self._pending

    def commit(self) -> None:
        with self.lock:
            self._b._run_hooks(self)
            self._b._commit_locked()
            self._buf.clear()
            self._pending = 0


class ReadTx:
    """Read view; `overlay` (if any) patches uncommitted writes over a
    committed-state connection."""

    def __init__(self, backend: "Backend", use_writer: bool,
                 overlay: Optional[Dict[str, Dict[bytes, Optional[bytes]]]]
                 ) -> None:
        self._b = backend
        self._use_writer = use_writer
        self._overlay = overlay

    def _rows(self, bucket: Bucket, start: bytes,
              end: bytes) -> List[Tuple[bytes, bytes]]:
        if self._use_writer:
            return self._b._query_writer(bucket, start, end)
        return self._b._query_reader(bucket, start, end)

    def get(self, bucket: Bucket, key: bytes) -> Optional[bytes]:
        key = bytes(key)
        if self._overlay is not None:
            ov = self._overlay.get(bucket.name)
            if ov is not None and key in ov:
                return ov[key]
        rows = self._rows(bucket, key, key + b"\x00")
        return rows[0][1] if rows else None

    def range(self, bucket: Bucket, start: bytes, end: Optional[bytes],
              limit: int = 0) -> List[Tuple[bytes, bytes]]:
        """Sorted [start, end); end=None means the single key `start`;
        limit 0 = unlimited."""
        if end is None:
            v = self.get(bucket, start)
            return [(bytes(start), v)] if v is not None else []
        rows = dict(self._rows(bucket, start, end))
        if self._overlay is not None:
            ov = self._overlay.get(bucket.name)
            if ov:
                for k, v in ov.items():
                    if start <= k < end:
                        if v is None:
                            rows.pop(k, None)
                        else:
                            rows[k] = v
        out = sorted(rows.items())
        if limit > 0:
            out = out[:limit]
        return out

    def count(self, bucket: Bucket) -> int:
        return len(self.range(bucket, b"", _MAX_KEY))

    def for_each(self, bucket: Bucket,
                 fn: Callable[[bytes, bytes], bool]) -> None:
        for k, v in self.range(bucket, b"", _MAX_KEY):
            if not fn(k, v):
                return


class Backend:
    def __init__(self, path: str,
                 batch_interval: float = DEFAULT_BATCH_INTERVAL,
                 batch_limit: int = DEFAULT_BATCH_LIMIT) -> None:
        self.path = path
        self.batch_interval = batch_interval
        self.batch_limit = batch_limit
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._w = sqlite3.connect(
            path, isolation_level=None, check_same_thread=False
        )
        self._wlock = threading.RLock()
        self._w.execute("PRAGMA journal_mode=WAL")
        self._w.execute("PRAGMA synchronous=NORMAL")
        self._in_txn = False
        self.batch_tx = BatchTx(self)
        self._hooks: List[Callable[[BatchTx], None]] = []
        self.commits = 0
        self._stopped = threading.Event()
        with self.batch_tx.lock:
            for b in ALL_BUCKETS:
                self.batch_tx.unsafe_create_bucket(b)
            self._commit_locked()
        # Reader connection: WAL mode gives it the last-committed
        # snapshot without blocking the writer.
        self._r = sqlite3.connect(path, check_same_thread=False)
        self._rlock = threading.RLock()
        self._runner = threading.Thread(
            target=self._run, name=f"backend-{os.path.basename(path)}",
            daemon=True,
        )
        self._runner.start()

    # -- low-level ------------------------------------------------------------

    def _exec(self, sql: str, params: tuple = ()) -> sqlite3.Cursor:
        with self._wlock:
            if not self._in_txn:
                self._w.execute("BEGIN")
                self._in_txn = True
            return self._w.execute(sql, params)

    def _commit_locked(self) -> None:
        with self._wlock:
            if self._in_txn:
                t0 = time.monotonic()
                self._w.execute("COMMIT")
                dmet.backend_commit_duration.observe(time.monotonic() - t0)
                self._in_txn = False
                self.commits += 1

    def _query_writer(self, bucket: Bucket, start: bytes,
                      end: bytes) -> List[Tuple[bytes, bytes]]:
        with self._wlock:
            return self._w.execute(
                f"SELECT k, v FROM {bucket.table} WHERE k>=? AND k<? "
                f"ORDER BY k", (start, end),
            ).fetchall()

    def _query_reader(self, bucket: Bucket, start: bytes,
                      end: bytes) -> List[Tuple[bytes, bytes]]:
        with self._rlock:
            return self._r.execute(
                f"SELECT k, v FROM {bucket.table} WHERE k>=? AND k<? "
                f"ORDER BY k", (start, end),
            ).fetchall()

    def _run_hooks(self, tx: BatchTx) -> None:
        for h in self._hooks:
            h(tx)

    # -- public ---------------------------------------------------------------

    def read_tx(self) -> ReadTx:
        """Sees committed state + the open batch transaction."""
        return ReadTx(self, use_writer=True, overlay=None)

    def concurrent_read_tx(self) -> ReadTx:
        """Committed snapshot + buffer overlay frozen at creation; never
        contends with the writer connection."""
        with self.batch_tx.lock:
            snap = {b: dict(kv) for b, kv in self.batch_tx._buf.items()}
        return ReadTx(self, use_writer=False, overlay=snap)

    def add_hook(self, hook: Callable[[BatchTx], None]) -> None:
        self._hooks.append(hook)

    def force_commit(self) -> None:
        self.batch_tx.commit()

    def defrag(self) -> None:
        with self.batch_tx.lock:
            self.batch_tx.commit()
            with self._wlock:
                self._w.execute("PRAGMA wal_checkpoint(TRUNCATE)")
                self._w.execute("VACUUM")

    def size(self) -> int:
        """On-disk footprint incl. the not-yet-checkpointed WAL journal
        (bbolt's size is the whole mmap'd file; counting the sqlite -wal
        keeps quota checks honest before checkpoints)."""
        total = 0
        for p in (self.path, self.path + "-wal"):
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return total

    def size_in_use(self) -> int:
        with self._wlock:
            pages = self._w.execute("PRAGMA page_count").fetchone()[0]
            free = self._w.execute("PRAGMA freelist_count").fetchone()[0]
            psize = self._w.execute("PRAGMA page_size").fetchone()[0]
        return (pages - free) * psize

    def snapshot_to(self, dest_path: str) -> None:
        """Consistent online copy (the reference streams the bbolt file;
        sqlite3's backup API gives the same guarantee)."""
        with self.batch_tx.lock:
            self.batch_tx.commit()
            with self._wlock:
                dst = sqlite3.connect(dest_path)
                try:
                    self._w.backup(dst)
                finally:
                    dst.close()

    def close(self) -> None:
        self._stopped.set()
        self._runner.join(timeout=5)
        with self.batch_tx.lock:
            # Through the hook-running commit: the consistent index must
            # land in the same final txn as the buffered applies.
            self.batch_tx.commit()
            with self._wlock:
                self._w.close()
            with self._rlock:
                self._r.close()

    # -- background commit loop ----------------------------------------------

    def _run(self) -> None:
        while not self._stopped.wait(self.batch_interval):
            with self.batch_tx.lock:
                if self.batch_tx.pending() > 0:
                    self.batch_tx.commit()


def open_backend(path: str, **kw) -> Backend:
    return Backend(path, **kw)
