"""Write-ahead log for raft state: the durable half of the Ready contract.

Facade over the native segmented record log (etcd_tpu/native/walog.py;
C++ core in native/src/walog.cc). Maps raft records onto log record
types and enforces the reference WAL's contract
(ref: server/storage/wal/wal.go:73-99):

* ``create(dir, metadata)`` — new WAL, first record is metadata
  (wal.go:101 Create);
* ``WAL.read_all(snap)`` — replay: returns (metadata, HardState,
  entries after snap.index), dropping entry versions superseded by
  later appends at the same index (wal.go:437-558 ReadAll);
* ``save(hs, entries, must_sync)`` — append entries + HardState, fsync
  when the raft MustSync rule says so (wal.go:920-953 Save), cut to a
  new segment past the size limit (wal.go:710 cut);
* ``save_snapshot(idx, term)`` — record a snapshot marker so replay can
  start there (wal.go:955 SaveSnapshot);
* ``release_to(index)`` — drop segments wholly before index
  (wal.go ReleaseLockTo).

Record payloads use a compact fixed struct encoding — our own wire
format, not the reference's protobufs.
"""

from __future__ import annotations

import os
import struct
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..native import walog as nwalog
from ..raft.types import Entry, EntryType, HardState, is_empty_hard_state
from . import metrics as dmet

# Record types (native type 0 is reserved for the CRC chain seed).
REC_METADATA = 1
REC_ENTRY = 2
REC_STATE = 3
REC_SNAPSHOT = 4

_STATE = struct.Struct("<QQQ")  # term, vote, commit
_ENTRY_HDR = struct.Struct("<QQI")  # term, index, type
_SNAP = struct.Struct("<QQ")  # index, term

SEGMENT_BYTES = 64 << 20  # ref: wal.go SegmentSizeBytes (64 MiB)


@dataclass
class WalSnapshot:
    """Snapshot marker in the WAL (ref: walpb.Snapshot)."""

    index: int = 0
    term: int = 0


class WALError(Exception):
    pass


class WAL:
    def __init__(self, w: nwalog.Walog, metadata: bytes) -> None:
        self._w = w
        self.metadata = metadata
        self._last_index = 0  # highest entry index appended
        self._segment_bytes = SEGMENT_BYTES

    # -- lifecycle ------------------------------------------------------------

    @staticmethod
    def create(dirpath: str, metadata: bytes = b"",
               segment_bytes: int = SEGMENT_BYTES) -> "WAL":
        w = nwalog.Walog(dirpath, segment_bytes=segment_bytes, create=True)
        wal = WAL(w, metadata)
        wal._segment_bytes = segment_bytes
        w.append(REC_METADATA, metadata)
        # An empty snapshot record marks "replay from the start"
        # (ref: wal.go:130 Create writes an empty walpb.Snapshot).
        w.append(REC_SNAPSHOT, _SNAP.pack(0, 0))
        w.flush(sync=True)
        return wal

    @staticmethod
    def exists(dirpath: str) -> bool:
        return os.path.isdir(dirpath) and any(
            f.endswith(".wal") for f in os.listdir(dirpath)
        )

    @staticmethod
    def open(dirpath: str,
             segment_bytes: int = SEGMENT_BYTES) -> "WAL":
        """Open for appending; run read_all() before the first save."""
        w = nwalog.Walog(dirpath, segment_bytes=segment_bytes, create=False)
        wal = WAL(w, b"")
        wal._segment_bytes = segment_bytes
        return wal

    def close(self) -> None:
        self._w.close()

    def __enter__(self) -> "WAL":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- replay ---------------------------------------------------------------

    def read_all(
        self, snap: Optional[WalSnapshot] = None
    ) -> Tuple[bytes, HardState, List[Entry]]:
        """Replay records; return (metadata, last HardState, entries with
        index > snap.index). Raises WALError if the requested snapshot
        marker never appears in the log (ref: ReadAll's match check)."""
        start = snap.index if snap is not None else 0
        records = nwalog.read_all(self._w.dirpath, repair=True)
        metadata = b""
        hs = HardState()
        ents: List[Entry] = []
        snap_matched = snap is None or (snap.index == 0 and snap.term == 0)
        for rtype, payload, _seq, _meta in records:
            if rtype == REC_METADATA:
                metadata = payload
            elif rtype == REC_STATE:
                term, vote, commit = _STATE.unpack(payload)
                hs = HardState(term=term, vote=vote, commit=commit)
            elif rtype == REC_ENTRY:
                term, index, etype = _ENTRY_HDR.unpack_from(payload)
                e = Entry(term=term, index=index,
                          type=EntryType(etype),
                          data=payload[_ENTRY_HDR.size:])
                if e.index > start:
                    # Later append at index i supersedes any previously
                    # replayed entries at >= i (leader-change rewrite).
                    pos = e.index - start - 1
                    if pos > len(ents):
                        # Gap: the WAL is missing entries between the
                        # snapshot point and this record (ref: ReadAll's
                        # ErrSliceOutOfRange guard).
                        raise WALError(
                            f"entry index {e.index} leaves a gap after "
                            f"{start + len(ents)}"
                        )
                    del ents[pos:]
                    ents.append(e)
                self._last_index = index
            elif rtype == REC_SNAPSHOT:
                idx, term = _SNAP.unpack(payload)
                if snap is not None and idx == snap.index:
                    if term != snap.term and snap.index != 0:
                        raise WALError(
                            f"snapshot marker term mismatch at index {idx}: "
                            f"wal {term} != requested {snap.term}"
                        )
                    snap_matched = True
        if not snap_matched:
            raise WALError(
                f"requested snapshot (index={snap.index}) not found in wal"
            )
        self.metadata = metadata
        return metadata, hs, ents

    # -- append ---------------------------------------------------------------

    def save(self, hs: HardState, entries: List[Entry],
             must_sync: Optional[bool] = None) -> None:
        """Append entries then HardState; fsync iff must_sync (default:
        the raft MustSync rule — any entries or a changed HardState)."""
        if is_empty_hard_state(hs) and not entries:
            return
        for e in entries:
            self._w.append(
                REC_ENTRY,
                _ENTRY_HDR.pack(e.term, e.index, int(e.type)) + e.data,
            )
            self._last_index = e.index
        if not is_empty_hard_state(hs):
            self._w.append(REC_STATE, _STATE.pack(hs.term, hs.vote, hs.commit))
        sync = must_sync if must_sync is not None else True
        if sync:
            t0 = time.monotonic()
            self._w.flush(sync=True)
            dmet.wal_fsync_duration.observe(time.monotonic() - t0)
        else:
            self._w.flush(sync=False)
        if self._w.tail_offset() > self._segment_bytes:
            self._cut()

    def save_snapshot(self, snap: WalSnapshot, sync: bool = True) -> None:
        self._w.append(REC_SNAPSHOT, _SNAP.pack(snap.index, snap.term))
        self._w.flush(sync=sync)

    def _cut(self) -> None:
        """Roll to a new segment named for the next entry index, carrying
        metadata + latest state forward via the crc chain (the chain is
        global, so no re-write is needed — the seed record links it)."""
        self._w.cut(self._last_index + 1)

    def release_to(self, index: int) -> int:
        """Delete segments that only contain data below `index`."""
        return self._w.release_before(index)

    # -- introspection --------------------------------------------------------

    def sync_stats(self) -> Tuple[int, int]:
        return self._w.sync_stats()

    def last_sync_ns(self) -> int:
        return self._w.last_sync_ns()


def verify(dirpath: str) -> bool:
    """Offline chain validation (ref: wal.go:629 Verify)."""
    return nwalog.verify(dirpath)
