"""Durable storage: WAL, backend, snapshots, MVCC (analog of the
reference's ``server/storage``)."""
