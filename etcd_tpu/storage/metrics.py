"""Disk metric set (ref: server/storage/wal/metrics.go,
server/storage/backend metrics in backend.go)."""

from __future__ import annotations

from ..pkg import metrics as m

wal_fsync_duration = m.histogram(
    "etcd_disk_wal_fsync_duration_seconds", "The latency distributions of fsync called by WAL.",
    buckets=[0.001 * (2 ** i) for i in range(14)],
)
wal_write_bytes = m.gauge(
    "etcd_disk_wal_write_bytes_total", "Total number of bytes written in WAL."
)
backend_commit_duration = m.histogram(
    "etcd_disk_backend_commit_duration_seconds", "The latency distributions of commit called by backend.",
    buckets=[0.001 * (2 ** i) for i in range(14)],
)
backend_snapshot_duration = m.histogram(
    "etcd_disk_backend_snapshot_duration_seconds", "The latency distribution of backend snapshots.",
    buckets=[0.01 * (2 ** i) for i in range(10)],
)
