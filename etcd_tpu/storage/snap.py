"""Snapshotter: durable raft snapshot files
(ref: server/etcdserver/api/snap/snapshotter.go:52-139).

Each snapshot is one file named ``%016x-%016x.snap`` (term-index, same
naming as the reference) containing a CRC32-guarded record:

    [u32 crc over payload][u32 payload_len][payload]

where payload = fixed header (index, term, conf-state counts) + conf
state ids + opaque application data. ``load()`` walks snapshots newest
first and skips corrupt/partial files, renaming them ``.broken`` the
way snapshotter.go:204-243 does.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import List, Optional

from ..raft.types import ConfState, Snapshot, SnapshotMetadata

SNAP_SUFFIX = ".snap"
_HDR = struct.Struct("<QQIIII")  # index, term, nv, nl, nvo, nln + auto_leave flag packed in nln high bit


class SnapError(Exception):
    pass


class NoSnapshotError(SnapError):
    """ref: snap.ErrNoSnapshot."""


def _encode(snap: Snapshot) -> bytes:
    md = snap.metadata
    cs = md.conf_state
    ids = cs.voters + cs.learners + cs.voters_outgoing + cs.learners_next
    nln = len(cs.learners_next) | (1 << 31 if cs.auto_leave else 0)
    hdr = _HDR.pack(
        md.index,
        md.term,
        len(cs.voters),
        len(cs.learners),
        len(cs.voters_outgoing),
        nln,
    )
    return hdr + struct.pack(f"<{len(ids)}Q", *ids) + snap.data


def _decode(payload: bytes) -> Snapshot:
    index, term, nv, nl, nvo, nln_raw = _HDR.unpack_from(payload)
    auto_leave = bool(nln_raw >> 31)
    nln = nln_raw & 0x7FFFFFFF
    n = nv + nl + nvo + nln
    off = _HDR.size
    ids = list(struct.unpack_from(f"<{n}Q", payload, off))
    off += 8 * n
    cs = ConfState(
        voters=ids[:nv],
        learners=ids[nv : nv + nl],
        voters_outgoing=ids[nv + nl : nv + nl + nvo],
        learners_next=ids[nv + nl + nvo :],
        auto_leave=auto_leave,
    )
    return Snapshot(
        data=payload[off:],
        metadata=SnapshotMetadata(conf_state=cs, index=index, term=term),
    )


class Snapshotter:
    """``fault_hook(op, nbytes)`` is the storage fault plane's seam
    (batched/faults.DiskFaultPlan — same contract as native Walog's
    hook): called BEFORE each file-affecting step with op in
    {"snap_write", "snap_fsync", "snap_rename"}, so a raise guarantees
    that step never started (the write-atomicity save_snap's tmp+rename
    already provides makes any abort loss-free: the previous snapshot
    file is untouched). The hook may sleep (latency injection) or
    raise: ENOSPC/write errors fire on snap_write/snap_rename, fsync
    errors on snap_fsync — exercised directly by
    tests/batched/test_diskfaults.py's Snapshotter seam tests."""

    def __init__(self, dirpath: str, *, fault_hook=None) -> None:
        self.dir = dirpath
        self.fault_hook = fault_hook
        os.makedirs(dirpath, exist_ok=True)

    def _hook(self, op: str, nbytes: int = 0) -> None:
        if self.fault_hook is not None:
            self.fault_hook(op, nbytes)

    def save_snap(self, snapshot: Snapshot) -> None:
        """ref: snapshotter.go:82-139 SaveSnap/save."""
        if snapshot.metadata.index == 0:
            return
        fname = "%016x-%016x%s" % (
            snapshot.metadata.term,
            snapshot.metadata.index,
            SNAP_SUFFIX,
        )
        payload = _encode(snapshot)
        blob = struct.pack("<II", zlib.crc32(payload), len(payload)) + payload
        tmp = os.path.join(self.dir, fname + ".tmp")
        self._hook("snap_write", len(blob))
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            self._hook("snap_fsync")
            os.fsync(f.fileno())
        self._hook("snap_rename")
        os.replace(tmp, os.path.join(self.dir, fname))
        # Crash-durability: fsync the parent directory after the
        # rename, or a crash can lose the DIRECTORY ENTRY of a fully
        # fsync'd snapshot file (the rename lives in the dir's pages,
        # not the file's — ref: fileutil.Fsync after rename in the
        # reference's snap/wal paths; ATC'19's fsync-failure study
        # calls out exactly this class).
        self._fsync_dir()

    def _fsync_dir(self) -> None:
        dfd = os.open(self.dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def snap_names(self) -> List[str]:
        """Snapshot filenames, newest (highest term-index) first."""
        names = [
            n
            for n in os.listdir(self.dir)
            if n.endswith(SNAP_SUFFIX)
        ]
        names.sort(reverse=True)
        return names

    def load(self) -> Snapshot:
        """Newest valid snapshot (ref: snapshotter.go:141-172 Load)."""
        return self.load_matching(lambda s: True)

    def load_newest_available(self, wal_snaps: List[tuple]) -> Snapshot:
        """Newest snapshot also recorded in the WAL's snapshot markers
        (ref: snapshotter.go:160-172): wal_snaps is [(index, term), ...]."""
        want = {(i, t) for i, t in wal_snaps}
        return self.load_matching(
            lambda s: (s.metadata.index, s.metadata.term) in want
        )

    def load_matching(self, matchfn) -> Snapshot:
        for name in self.snap_names():
            path = os.path.join(self.dir, name)
            try:
                snap = self._read(path)
            except SnapError:
                os.replace(path, path + ".broken")
                continue
            if matchfn(snap):
                return snap
        raise NoSnapshotError()

    @staticmethod
    def _read(path: str) -> Snapshot:
        with open(path, "rb") as f:
            blob = f.read()
        if len(blob) < 8:
            raise SnapError(f"snap file {path} too short")
        crc, ln = struct.unpack_from("<II", blob)
        payload = blob[8 : 8 + ln]
        if len(payload) != ln or zlib.crc32(payload) != crc:
            raise SnapError(f"snap file {path} crc mismatch")
        return _decode(payload)

    def release_snap_dbs(self, index: int) -> None:
        """Delete snapshot files older than index (purge path,
        ref: snapshotter.go ReleaseSnapDBs). The unlink runs through
        the fault seam (``snap_unlink``), and the directory is fsync'd
        after pruning — the rename-fsync above makes CREATION durable,
        but an unlink lives in the same directory pages: without this,
        a crash can resurrect a pruned file and a later replay may pick
        a stale snapshot that the retention contract promised was gone."""
        removed = 0
        for name in self.snap_names():
            try:
                idx = int(name[17:33], 16)
            except ValueError:
                continue
            if idx < index:
                self._hook("snap_unlink")
                os.remove(os.path.join(self.dir, name))
                removed += 1
        if removed:
            self._hook("snap_fsync")
            self._fsync_dir()

    def retain(self, keep: int) -> int:
        """Keep the ``keep`` newest snapshot files, unlink the rest
        (fault seam + dir fsync like release_snap_dbs). Returns the
        number pruned. keep < 1 is clamped to 1 — retention must never
        delete the only recoverable snapshot."""
        keep = max(1, int(keep))
        victims = self.snap_names()[keep:]
        for name in victims:
            self._hook("snap_unlink")
            os.remove(os.path.join(self.dir, name))
        if victims:
            self._hook("snap_fsync")
            self._fsync_dir()
        return len(victims)
