"""serverstorage.Storage facade: one object owning WAL + Snapshotter,
enforcing the durability ordering the Ready loop depends on
(ref: server/storage/storage.go NewStorage/storage).

Contract (storage.go:27-45):
* ``save(hs, entries, must_sync)`` — WAL append (+fsync per MustSync);
* ``save_snap(snap)`` — snapshot file is written *before* the WAL
  marker so a crash between the two still replays into a state the
  snapshot file can satisfy (storage.go:66-88 SaveSnap);
* ``release(snap)`` — drop WAL segments and snap files made obsolete
  by a persisted snapshot (storage.go:90-109 Release).
"""

from __future__ import annotations

from typing import List

from ..raft.types import Entry, HardState, Snapshot
from .snap import Snapshotter
from .wal import WAL, WalSnapshot


class ServerStorage:
    def __init__(self, wal: WAL, snapshotter: Snapshotter) -> None:
        self.wal = wal
        self.snapshotter = snapshotter

    def save(
        self, hs: HardState, entries: List[Entry], must_sync: bool = True
    ) -> None:
        self.wal.save(hs, entries, must_sync)

    def save_snap(self, snap: Snapshot) -> None:
        walsnap = WalSnapshot(index=snap.metadata.index, term=snap.metadata.term)
        # File first, marker second (ref: storage.go:73-87).
        self.snapshotter.save_snap(snap)
        self.wal.save_snapshot(walsnap)

    def release(self, snap: Snapshot) -> None:
        self.wal.release_to(snap.metadata.index)
        self.snapshotter.release_snap_dbs(snap.metadata.index)

    def sync(self) -> None:
        self.wal.save(HardState(), [], must_sync=True)

    def close(self) -> None:
        self.wal.close()
