"""JWT token provider (ref: server/auth/jwt.go:31-120 tokenJWT).

Standard JWT wire format — ``base64url(header).base64url(claims).
base64url(sig)`` with an ``{"alg","typ"}`` header — carrying the
reference's claim set ``{username, revision, exp}`` (jwt.go:71-83
assign). Signing is HS256/HS384/HS512 from the standard library; the
reference additionally supports RSA/ECDSA, which is a key-material
deployment concern, not a protocol difference — the validation
pipeline (alg allow-list, signature check, exp check, revision
extraction) matches jwt.go:41-69 info/parse.

Option string parity with --auth-token
(jwt.go:85-120 NewTokenProviderJWT / prepareOpts):
``jwt,sign-method=HS256,sign-key=<secret>,ttl=5m``.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Dict, Optional, Tuple

DEFAULT_JWT_TTL = 300.0

_ALGS = {
    "HS256": hashlib.sha256,
    "HS384": hashlib.sha384,
    "HS512": hashlib.sha512,
}


def _b64e(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def _b64d(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def parse_ttl(spec: str) -> float:
    """'5m' / '30s' / '1h' / plain seconds (jwt.go ttl option)."""
    spec = spec.strip()
    mult = {"s": 1, "m": 60, "h": 3600}.get(spec[-1:], None)
    if mult is not None:
        return float(spec[:-1]) * mult
    return float(spec)


class JWTTokenProvider:
    """ref: jwt.go tokenJWT — stateless signed tokens; every member
    can validate without shared state, so auth survives leader moves."""

    def __init__(self, sign_key: bytes, sign_method: str = "HS256",
                 ttl: float = DEFAULT_JWT_TTL) -> None:
        if sign_method not in _ALGS:
            raise ValueError(
                f"unsupported sign method {sign_method!r} "
                f"(supported: {sorted(_ALGS)})")
        self._key = sign_key
        self._alg = sign_method
        self._ttl = ttl
        self._enabled = False

    @classmethod
    def from_opts(cls, opts: str) -> "JWTTokenProvider":
        """``sign-method=HS256,sign-key=k,ttl=5m`` (jwt.go prepareOpts)."""
        kv: Dict[str, str] = {}
        for part in opts.split(","):
            if not part:
                continue
            k, _, v = part.partition("=")
            kv[k.strip()] = v.strip()
        key = kv.get("sign-key", "")
        if not key:
            raise ValueError("jwt: sign-key option is required")
        return cls(
            key.encode(),
            sign_method=kv.get("sign-method", "HS256"),
            ttl=parse_ttl(kv["ttl"]) if "ttl" in kv else DEFAULT_JWT_TTL,
        )

    # -- TokenProvider surface (same as simple/hmac providers) -----------------

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def _sign(self, signing_input: bytes) -> bytes:
        return hmac.new(self._key, signing_input, _ALGS[self._alg]).digest()

    def assign(self, username: str, revision: int = 0) -> str:
        """jwt.go:71-83 assign — mint {username, revision, exp}."""
        if not self._enabled:
            raise RuntimeError("jwt token provider disabled")
        header = {"alg": self._alg, "typ": "JWT"}
        claims = {
            "username": username,
            "revision": revision,
            # NumericDate; RFC 7519 §2 allows a fractional part.
            "exp": time.time() + self._ttl,
        }
        signing_input = (
            _b64e(json.dumps(header, separators=(",", ":")).encode())
            + "."
            + _b64e(json.dumps(claims, separators=(",", ":")).encode())
        ).encode()
        return signing_input.decode() + "." + _b64e(self._sign(signing_input))

    def info(self, token: str) -> Optional[str]:
        ur = self.info_with_revision(token)
        return ur[0] if ur is not None else None

    def info_with_revision(self, token: str) -> Optional[Tuple[str, int]]:
        """jwt.go:41-69 info — None on any validation failure."""
        if not self._enabled:
            return None
        try:
            header_b64, claims_b64, sig_b64 = token.split(".")
            header = json.loads(_b64d(header_b64))
            # alg allow-list: reject alg-confusion tokens ("none" etc.).
            if header.get("alg") != self._alg:
                return None
            signing_input = (header_b64 + "." + claims_b64).encode()
            if not hmac.compare_digest(
                    _b64d(sig_b64), self._sign(signing_input)):
                return None
            claims = json.loads(_b64d(claims_b64))
            if float(claims.get("exp", 0)) < time.time():
                return None
            return str(claims["username"]), int(claims.get("revision", 0))
        except (ValueError, KeyError, TypeError):
            return None

    # Stateless: nothing to invalidate per-user, same as the reference
    # (jwt.go invalidateUser is a no-op).
    def invalidate_user(self, username: str) -> None:
        pass
