"""Per-user unified range permissions
(ref: server/auth/range_perm_cache.go).

Each user's granted role permissions are merged into two interval trees
(read, write); permission checks are containment queries. The cache is
rebuilt wholesale on any auth mutation (rangePermCache invalidation,
store.go refreshRangePermCache).
"""

from __future__ import annotations

from typing import Dict, List

from ..pkg.adt import Interval, IntervalTree

# The end-of-table sentinel: a range whose end is "" in the API means
# "just the key"; end == b"\x00" means "from key to everything after".
_MAX = b"\xff" * 64


def _ivl(key: bytes, range_end: bytes) -> Interval:
    if not range_end:
        return Interval(key, key + b"\x00")
    if range_end == b"\x00":
        return Interval(key, _MAX)
    return Interval(key, range_end)


class UnifiedRangePermissions:
    def __init__(self) -> None:
        self.read = IntervalTree()
        self.write = IntervalTree()

    def add(self, key: bytes, range_end: bytes, perm_type: int) -> None:
        from .store import PermissionType

        ivl = _ivl(key, range_end)
        if perm_type in (PermissionType.READ, PermissionType.READWRITE):
            self.read.insert(ivl, True)
        if perm_type in (PermissionType.WRITE, PermissionType.READWRITE):
            self.write.insert(ivl, True)

    def _check(self, tree: IntervalTree, key: bytes, range_end: bytes) -> bool:
        want = _ivl(key, range_end)
        if not range_end:
            # Point check: any covering interval grants it
            # (checkKeyPoint range_perm_cache.go:129-141).
            for iv, _v in tree.visit_items(want):
                if iv.begin <= key and (key < iv.end):
                    return True
            return False
        # Interval check: one granted interval must contain the whole
        # request (checkKeyInterval range_perm_cache.go:113-127).
        for iv, _v in tree.visit_items(want):
            if iv.begin <= want.begin and want.end <= iv.end:
                return True
        return False

    def check_read(self, key: bytes, range_end: bytes) -> bool:
        return self._check(self.read, key, range_end)

    def check_write(self, key: bytes, range_end: bytes) -> bool:
        return self._check(self.write, key, range_end)
