"""AuthN/Z (ref: server/auth/).

Users, roles, key-range permissions backed by an interval tree, token
providers (simple TTL tokens + HMAC-signed stateless tokens, the JWT
analog), and a revision-checked store so stale-auth requests are
rejected the way the reference does.
"""

from .store import (  # noqa: F401
    AuthStore,
    AuthInfo,
    AuthDisabledError,
    AuthNotEnabledError,
    AuthFailedError,
    AuthOldRevisionError,
    InvalidAuthTokenError,
    PermissionDeniedError,
    RoleAlreadyExistError,
    RoleNotFoundError,
    RoleNotGrantedError,
    RootUserNotExistError,
    RootRoleNotGrantedError,
    UserAlreadyExistError,
    UserEmptyError,
    UserNotFoundError,
    Permission,
    PermissionType,
    ROOT_USER,
    ROOT_ROLE,
)
from .simple_token import SimpleTokenProvider  # noqa: F401
from .hmac_token import HMACTokenProvider  # noqa: F401
