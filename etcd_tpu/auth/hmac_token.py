"""Stateless signed tokens: the JWT analog (ref: server/auth/jwt.go).

Same shape as the reference's JWT provider — a signed claim set of
``{username, revision, exp}`` — but signed with HMAC-SHA256 from the
standard library instead of RSA/ECDSA, since key material handling is a
deployment concern, not a protocol one. Token format:

    base64url(json claims) "." base64url(hmac)
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
from typing import Optional, Tuple

DEFAULT_HMAC_TOKEN_TTL = 300.0


class HMACTokenProvider:
    def __init__(self, sign_key: bytes, ttl: float = DEFAULT_HMAC_TOKEN_TTL) -> None:
        self._key = sign_key
        self._ttl = ttl
        self._enabled = False

    def enable(self) -> None:
        self._enabled = True

    def disable(self) -> None:
        self._enabled = False

    def _sign(self, payload: bytes) -> bytes:
        return hmac.new(self._key, payload, hashlib.sha256).digest()

    def assign(self, username: str, revision: int = 0) -> str:
        """ref: jwt.go assign — claims {username, revision, exp}."""
        if not self._enabled:
            raise RuntimeError("hmac token provider disabled")
        claims = {
            "username": username,
            "revision": revision,
            "exp": time.time() + self._ttl,
        }
        payload = base64.urlsafe_b64encode(json.dumps(claims).encode())
        sig = base64.urlsafe_b64encode(self._sign(payload))
        return payload.decode() + "." + sig.decode()

    def info(self, token: str) -> Optional[str]:
        user_rev = self.info_with_revision(token)
        return user_rev[0] if user_rev is not None else None

    def info_with_revision(self, token: str) -> Optional[Tuple[str, int]]:
        try:
            payload_b64, sig_b64 = token.split(".", 1)
            payload = payload_b64.encode()
            sig = base64.urlsafe_b64decode(sig_b64.encode())
            if not hmac.compare_digest(sig, self._sign(payload)):
                return None
            claims = json.loads(base64.urlsafe_b64decode(payload))
            if time.time() > float(claims["exp"]):
                return None
            return str(claims["username"]), int(claims["revision"])
        except Exception:  # noqa: BLE001 — any malformed token is invalid
            return None

    def invalidate_user(self, username: str) -> None:
        """Stateless tokens can't be revoked individually; revision checks
        cover invalidation (ref: jwt.go — same limitation)."""
