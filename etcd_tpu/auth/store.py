"""The auth store (ref: server/auth/store.go).

State lives in three backend buckets — the enable flag + auth revision,
users, roles — exactly the reference's schema split. Every mutation
bumps the **auth revision**; requests carry the revision their token was
minted at and are rejected with AuthOldRevisionError when stale
(store.go isValidPermission/isOpPermitted revision gate). Permission
checks resolve through the per-user unified interval-tree cache
(range_perm_cache.py), rebuilt on every mutation.

Passwords are salted PBKDF2-HMAC-SHA256 (the stdlib stand-in for the
reference's bcrypt; same contract: cost-parameterized, per-user salt,
constant-time compare).
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import threading
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Optional

from ..storage import backend as bk
from .range_perm_cache import UnifiedRangePermissions

ROOT_USER = "root"
ROOT_ROLE = "root"

AUTH_BUCKET = bk.Bucket("auth")
USERS_BUCKET = bk.Bucket("authUsers")
ROLES_BUCKET = bk.Bucket("authRoles")

ENABLED_KEY = b"authEnabled"
REVISION_KEY = b"authRevision"

DEFAULT_PBKDF2_ITERS = 10_000  # host-side cost knob (bcrypt-cost analog)


class PermissionType(IntEnum):
    """ref: authpb.Permission_Type."""

    READ = 0
    WRITE = 1
    READWRITE = 2


@dataclass
class Permission:
    perm_type: PermissionType = PermissionType.READ
    key: bytes = b""
    range_end: bytes = b""


@dataclass
class User:
    name: str = ""
    password: str = ""  # "salt$iters$hexhash", empty for no-password users
    roles: List[str] = field(default_factory=list)
    no_password: bool = False


@dataclass
class Role:
    name: str = ""
    key_permissions: List[Permission] = field(default_factory=list)


@dataclass
class AuthInfo:
    """ref: auth.AuthInfo — identity + the auth revision it was minted at."""

    username: str = ""
    revision: int = 0


class AuthError(Exception):
    pass


class AuthDisabledError(AuthError):
    """ref: ErrAuthNotEnabled (op requires enabled auth)."""


class AuthNotEnabledError(AuthError):
    pass


class AuthFailedError(AuthError):
    """ref: ErrAuthFailed."""


class AuthOldRevisionError(AuthError):
    """ref: ErrAuthOldRevision."""


class InvalidAuthTokenError(AuthError):
    """ref: ErrInvalidAuthToken."""


class PermissionDeniedError(AuthError):
    """ref: ErrPermissionDenied."""


class UserAlreadyExistError(AuthError):
    pass


class UserEmptyError(AuthError):
    pass


class UserNotFoundError(AuthError):
    pass


class RoleAlreadyExistError(AuthError):
    pass


class RoleNotFoundError(AuthError):
    pass


class RoleNotGrantedError(AuthError):
    pass


class RootUserNotExistError(AuthError):
    """ref: ErrRootUserNotExist."""


class RootRoleNotGrantedError(AuthError):
    """ref: ErrRootRoleNotExist."""


def hash_password(password: str, iters: int = DEFAULT_PBKDF2_ITERS) -> str:
    salt = os.urandom(16)
    dk = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, iters)
    return f"{salt.hex()}${iters}${dk.hex()}"


def verify_password(stored: str, password: str) -> bool:
    if not stored:
        return False
    try:
        salt_hex, iters_s, hash_hex = stored.split("$")
        dk = hashlib.pbkdf2_hmac(
            "sha256", password.encode(), bytes.fromhex(salt_hex), int(iters_s)
        )
        return hmac.compare_digest(dk.hex(), hash_hex)
    except ValueError:
        return False


def _user_to_bytes(u: User) -> bytes:
    return json.dumps(
        {
            "name": u.name,
            "password": u.password,
            "roles": u.roles,
            "no_password": u.no_password,
        }
    ).encode()


def _user_from_bytes(b: bytes) -> User:
    d = json.loads(b.decode())
    return User(
        name=d["name"],
        password=d["password"],
        roles=list(d["roles"]),
        no_password=d.get("no_password", False),
    )


def _role_to_bytes(r: Role) -> bytes:
    return json.dumps(
        {
            "name": r.name,
            "perms": [
                {
                    "type": int(p.perm_type),
                    "key": p.key.hex(),
                    "range_end": p.range_end.hex(),
                }
                for p in r.key_permissions
            ],
        }
    ).encode()


def _role_from_bytes(b: bytes) -> Role:
    d = json.loads(b.decode())
    return Role(
        name=d["name"],
        key_permissions=[
            Permission(
                perm_type=PermissionType(p["type"]),
                key=bytes.fromhex(p["key"]),
                range_end=bytes.fromhex(p["range_end"]),
            )
            for p in d["perms"]
        ],
    )


class AuthStore:
    """ref: server/auth/store.go authStore."""

    def __init__(
        self,
        backend: bk.Backend,
        token_provider=None,
        pbkdf2_iters: int = DEFAULT_PBKDF2_ITERS,
    ) -> None:
        self._lock = threading.RLock()
        self.b = backend
        self.tp = token_provider
        self.iters = pbkdf2_iters
        self._enabled = False
        self._revision = 0
        self._range_perm_cache: Dict[str, UnifiedRangePermissions] = {}

        tx = self.b.batch_tx
        with tx.lock:
            tx.unsafe_create_bucket(AUTH_BUCKET)
            tx.unsafe_create_bucket(USERS_BUCKET)
            tx.unsafe_create_bucket(ROLES_BUCKET)
        rt = self.b.read_tx()
        enabled = rt.get(AUTH_BUCKET, ENABLED_KEY)
        rev = rt.get(AUTH_BUCKET, REVISION_KEY)
        self._revision = int.from_bytes(rev, "big") if rev else 0
        if enabled == b"\x01":
            self._enabled = True
            if self.tp is not None:
                self.tp.enable()
            self._refresh_range_perm_cache()

    # -- helpers ---------------------------------------------------------------

    def _commit_revision(self) -> None:
        """Bump + persist auth revision (ref: store.go commitRevision)."""
        self._revision += 1
        tx = self.b.batch_tx
        with tx.lock:
            tx.put(AUTH_BUCKET, REVISION_KEY, self._revision.to_bytes(8, "big"))

    def revision(self) -> int:
        with self._lock:
            return self._revision

    def _get_user(self, name: str) -> Optional[User]:
        v = self.b.read_tx().get(USERS_BUCKET, name.encode())
        return _user_from_bytes(v) if v is not None else None

    def _put_user(self, u: User) -> None:
        tx = self.b.batch_tx
        with tx.lock:
            tx.put(USERS_BUCKET, u.name.encode(), _user_to_bytes(u))

    def _get_role(self, name: str) -> Optional[Role]:
        v = self.b.read_tx().get(ROLES_BUCKET, name.encode())
        return _role_from_bytes(v) if v is not None else None

    def _put_role(self, r: Role) -> None:
        tx = self.b.batch_tx
        with tx.lock:
            tx.put(ROLES_BUCKET, r.name.encode(), _role_to_bytes(r))

    def _all_users(self) -> List[User]:
        rows = self.b.read_tx().range(USERS_BUCKET, b"", b"\xff" * 64, 0)
        return [_user_from_bytes(v) for _k, v in rows]

    def _all_roles(self) -> List[Role]:
        rows = self.b.read_tx().range(ROLES_BUCKET, b"", b"\xff" * 64, 0)
        return [_role_from_bytes(v) for _k, v in rows]

    def _refresh_range_perm_cache(self) -> None:
        """Rebuild every user's merged permission trees
        (ref: range_perm_cache.go refreshRangePermCache)."""
        cache: Dict[str, UnifiedRangePermissions] = {}
        roles = {r.name: r for r in self._all_roles()}
        for user in self._all_users():
            perms = UnifiedRangePermissions()
            for rname in user.roles:
                role = roles.get(rname)
                if role is None:
                    continue
                for p in role.key_permissions:
                    perms.add(p.key, p.range_end, p.perm_type)
            cache[user.name] = perms
        self._range_perm_cache = cache

    # -- enable / disable ------------------------------------------------------

    def is_auth_enabled(self) -> bool:
        with self._lock:
            return self._enabled

    def auth_enable(self) -> None:
        """ref: store.go AuthEnable — requires root user with root role."""
        with self._lock:
            if self._enabled:
                return
            root = self._get_user(ROOT_USER)
            if root is None:
                raise RootUserNotExistError()
            if ROOT_ROLE not in root.roles:
                raise RootRoleNotGrantedError()
            tx = self.b.batch_tx
            with tx.lock:
                tx.put(AUTH_BUCKET, ENABLED_KEY, b"\x01")
            self._enabled = True
            if self.tp is not None:
                self.tp.enable()
            self._refresh_range_perm_cache()
            self._commit_revision()

    def auth_disable(self) -> None:
        with self._lock:
            if not self._enabled:
                return
            tx = self.b.batch_tx
            with tx.lock:
                tx.put(AUTH_BUCKET, ENABLED_KEY, b"\x00")
            self._enabled = False
            if self.tp is not None:
                self.tp.disable()
            self._commit_revision()

    # -- authentication --------------------------------------------------------

    def check_password(self, username: str, password: str) -> int:
        """Verify credentials; returns current auth revision
        (ref: store.go CheckPassword)."""
        with self._lock:
            if not self._enabled:
                raise AuthNotEnabledError()
            user = self._get_user(username)
            if user is None or user.no_password:
                raise AuthFailedError()
        if not verify_password(user.password, password):
            raise AuthFailedError()
        with self._lock:
            return self._revision

    def authenticate(self, username: str, password: str) -> str:
        """Credentials → token (ref: store.go Authenticate + api layer)."""
        rev = self.check_password(username, password)
        if self.tp is None:
            raise AuthError("no token provider configured")
        return self.tp.assign(username, rev)

    def auth_info_from_token(self, token: str) -> AuthInfo:
        """ref: store.go AuthInfoFromCtx token resolution."""
        with self._lock:
            if not self._enabled:
                return AuthInfo()
            if self.tp is None:
                raise InvalidAuthTokenError()
            user = self.tp.info(token)
            if user is None:
                raise InvalidAuthTokenError()
            return AuthInfo(username=user, revision=self._revision)

    # -- user management -------------------------------------------------------

    def user_add(
        self, name: str, password: str = "", no_password: bool = False
    ) -> None:
        """ref: store.go UserAdd."""
        if not name:
            raise UserEmptyError()
        with self._lock:
            if self._get_user(name) is not None:
                raise UserAlreadyExistError(name)
            hashed = "" if no_password else hash_password(password, self.iters)
            self._put_user(User(name=name, password=hashed, no_password=no_password))
            self._commit_revision()
            self._refresh_range_perm_cache()

    def user_delete(self, name: str) -> None:
        with self._lock:
            if self._enabled and name == ROOT_USER:
                raise AuthError("cannot delete root user while auth is enabled")
            if self._get_user(name) is None:
                raise UserNotFoundError(name)
            tx = self.b.batch_tx
            with tx.lock:
                tx.delete(USERS_BUCKET, name.encode())
            if self.tp is not None:
                self.tp.invalidate_user(name)
            self._commit_revision()
            self._refresh_range_perm_cache()

    def user_change_password(self, name: str, password: str) -> None:
        with self._lock:
            user = self._get_user(name)
            if user is None:
                raise UserNotFoundError(name)
            user.password = hash_password(password, self.iters)
            self._put_user(user)
            if self.tp is not None:
                self.tp.invalidate_user(name)
            self._commit_revision()

    def user_grant_role(self, user: str, role: str) -> None:
        with self._lock:
            u = self._get_user(user)
            if u is None:
                raise UserNotFoundError(user)
            if role != ROOT_ROLE and self._get_role(role) is None:
                raise RoleNotFoundError(role)
            if role in u.roles:
                return
            u.roles = sorted(u.roles + [role])
            self._put_user(u)
            self._commit_revision()
            self._refresh_range_perm_cache()

    def user_revoke_role(self, user: str, role: str) -> None:
        with self._lock:
            u = self._get_user(user)
            if u is None:
                raise UserNotFoundError(user)
            if role not in u.roles:
                raise RoleNotGrantedError(role)
            u.roles = [r for r in u.roles if r != role]
            self._put_user(u)
            self._commit_revision()
            self._refresh_range_perm_cache()

    def user_get(self, name: str) -> User:
        with self._lock:
            u = self._get_user(name)
            if u is None:
                raise UserNotFoundError(name)
            return u

    def user_list(self) -> List[str]:
        with self._lock:
            return sorted(u.name for u in self._all_users())

    # -- role management -------------------------------------------------------

    def role_add(self, name: str) -> None:
        if not name:
            raise AuthError("role name empty")
        with self._lock:
            if self._get_role(name) is not None:
                raise RoleAlreadyExistError(name)
            self._put_role(Role(name=name))
            self._commit_revision()

    def role_delete(self, name: str) -> None:
        """Deletes the role and revokes it from every user
        (ref: store.go RoleDelete)."""
        with self._lock:
            if self._enabled and name == ROOT_ROLE:
                raise AuthError("cannot delete root role while auth is enabled")
            if self._get_role(name) is None:
                raise RoleNotFoundError(name)
            tx = self.b.batch_tx
            with tx.lock:
                tx.delete(ROLES_BUCKET, name.encode())
            for u in self._all_users():
                if name in u.roles:
                    u.roles = [r for r in u.roles if r != name]
                    self._put_user(u)
            self._commit_revision()
            self._refresh_range_perm_cache()

    def role_grant_permission(self, role: str, perm: Permission) -> None:
        with self._lock:
            r = self._get_role(role)
            if r is None:
                raise RoleNotFoundError(role)
            r.key_permissions = [
                p
                for p in r.key_permissions
                if not (p.key == perm.key and p.range_end == perm.range_end)
            ] + [perm]
            r.key_permissions.sort(key=lambda p: (p.key, p.range_end))
            self._put_role(r)
            self._commit_revision()
            self._refresh_range_perm_cache()

    def role_revoke_permission(
        self, role: str, key: bytes, range_end: bytes = b""
    ) -> None:
        with self._lock:
            r = self._get_role(role)
            if r is None:
                raise RoleNotFoundError(role)
            before = len(r.key_permissions)
            r.key_permissions = [
                p
                for p in r.key_permissions
                if not (p.key == key and p.range_end == range_end)
            ]
            if len(r.key_permissions) == before:
                raise AuthError("permission not granted to the role")
            self._put_role(r)
            self._commit_revision()
            self._refresh_range_perm_cache()

    def role_get(self, name: str) -> Role:
        with self._lock:
            r = self._get_role(name)
            if r is None:
                raise RoleNotFoundError(name)
            return r

    def role_list(self) -> List[str]:
        with self._lock:
            return sorted(r.name for r in self._all_roles())

    # -- permission checks -----------------------------------------------------

    def _is_op_permitted(
        self, info: Optional[AuthInfo], key: bytes, range_end: bytes, write: bool
    ) -> None:
        """ref: store.go isOpPermitted."""
        with self._lock:
            if not self._enabled:
                return
            if info is None or not info.username:
                raise UserEmptyError()
            if info.revision == 0:
                raise InvalidAuthTokenError()
            if info.revision < self._revision:
                raise AuthOldRevisionError()
            user = self._get_user(info.username)
            if user is None:
                raise UserNotFoundError(info.username)
            if ROOT_ROLE in user.roles:
                return
            perms = self._range_perm_cache.get(info.username)
            ok = (
                perms is not None
                and (
                    perms.check_write(key, range_end)
                    if write
                    else perms.check_read(key, range_end)
                )
            )
            if not ok:
                raise PermissionDeniedError()

    def is_put_permitted(self, info: Optional[AuthInfo], key: bytes) -> None:
        self._is_op_permitted(info, key, b"", write=True)

    def is_range_permitted(
        self, info: Optional[AuthInfo], key: bytes, range_end: bytes = b""
    ) -> None:
        self._is_op_permitted(info, key, range_end, write=False)

    def is_delete_range_permitted(
        self, info: Optional[AuthInfo], key: bytes, range_end: bytes = b""
    ) -> None:
        self._is_op_permitted(info, key, range_end, write=True)

    def is_admin_permitted(self, info: Optional[AuthInfo]) -> None:
        """ref: store.go IsAdminPermitted — root role required."""
        with self._lock:
            if not self._enabled:
                return
            if info is None or not info.username:
                raise UserEmptyError()
            if info.revision < self._revision:
                raise AuthOldRevisionError()
            user = self._get_user(info.username)
            if user is None:
                raise UserNotFoundError(info.username)
            if ROOT_ROLE not in user.roles:
                raise PermissionDeniedError()

    def recover(self, backend: bk.Backend) -> None:
        """Reload state after a backend swap (ref: store.go Recover)."""
        with self._lock:
            self.b = backend
            rt = self.b.read_tx()
            enabled = rt.get(AUTH_BUCKET, ENABLED_KEY)
            rev = rt.get(AUTH_BUCKET, REVISION_KEY)
            self._enabled = enabled == b"\x01"
            self._revision = int.from_bytes(rev, "big") if rev else 0
            if self._enabled and self.tp is not None:
                self.tp.enable()
            self._refresh_range_perm_cache()
