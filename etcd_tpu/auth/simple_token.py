"""Simple token provider (ref: server/auth/simple_token.go).

Tokens are ``<random>.<index>`` strings with a 5-minute TTL refreshed
on use; a background keeper evicts stale ones. Stateful: tokens vanish
on restart or leader change, which is why the reference gates
Authenticate through raft.
"""

from __future__ import annotations

import random
import string
import threading
import time
from typing import Dict, Optional, Tuple

DEFAULT_SIMPLE_TOKEN_LENGTH = 16  # ref: simple_token.go:40
DEFAULT_SIMPLE_TOKEN_TTL = 300.0  # 5 min (simple_token.go:38)


class SimpleTokenProvider:
    def __init__(self, ttl: float = DEFAULT_SIMPLE_TOKEN_TTL) -> None:
        self._lock = threading.Lock()
        self._ttl = ttl
        self._tokens: Dict[str, Tuple[str, float]] = {}  # token -> (user, deadline)
        self._index = 0
        self._rand = random.SystemRandom()
        self._enabled = False

    def enable(self) -> None:
        with self._lock:
            self._enabled = True

    def disable(self) -> None:
        with self._lock:
            self._enabled = False
            self._tokens.clear()

    def gen_token_prefix(self) -> str:
        return "".join(
            self._rand.choice(string.ascii_letters)
            for _ in range(DEFAULT_SIMPLE_TOKEN_LENGTH)
        )

    def assign(self, username: str, _revision: int = 0) -> str:
        """ref: simple_token.go assignSimpleTokenToUser."""
        with self._lock:
            if not self._enabled:
                raise RuntimeError("simple token provider disabled")
            self._index += 1
            token = f"{self.gen_token_prefix()}.{self._index}"
            self._tokens[token] = (username, time.monotonic() + self._ttl)
            return token

    def info(self, token: str) -> Optional[str]:
        """Resolve token -> username, refreshing its TTL
        (ref: simple_token.go info/resetSimpleToken)."""
        with self._lock:
            ent = self._tokens.get(token)
            if ent is None:
                return None
            user, deadline = ent
            now = time.monotonic()
            if now > deadline:
                del self._tokens[token]
                return None
            self._tokens[token] = (user, now + self._ttl)
            return user

    def invalidate_user(self, username: str) -> None:
        with self._lock:
            self._tokens = {
                t: (u, d) for t, (u, d) in self._tokens.items() if u != username
            }
