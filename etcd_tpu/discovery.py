"""Cluster bootstrap discovery via an existing v3 cluster
(ref: server/etcdserver/api/v3discovery/discovery.go — members
self-register under a token prefix on the discovery cluster, wait for
cluster-size registrations, then derive --initial-cluster).

Keyspace on the discovery cluster:
``/_etcd/registry/<token>/_config/size`` (expected member count) and
``/_etcd/registry/<token>/members/<name>`` → peer URL.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from .client.client import Client
from .client.util import prefix_end


class DiscoveryError(Exception):
    pass


def _registry(token: str) -> bytes:
    return f"/_etcd/registry/{token}".encode()


def setup_token(endpoints: List[Tuple[str, int]], token: str,
                size: int) -> None:
    """Operator step: create the token with the expected cluster size
    (discovery.go expects size pre-set by `etcdctl put`)."""
    c = Client(endpoints)
    try:
        c.put(_registry(token) + b"/_config/size", str(size).encode())
    finally:
        c.close()


def join_cluster(endpoints: List[Tuple[str, int]], token: str,
                 name: str, peer_url: str,
                 timeout: float = 60.0) -> str:
    """Register and wait for the full roster; returns the
    initial-cluster string (discovery.go JoinCluster →
    checkCluster/registerSelf/waitNodes)."""
    c = Client(endpoints)
    try:
        reg = _registry(token)
        size_resp = c.get(reg + b"/_config/size")
        if not size_resp.kvs:
            raise DiscoveryError(
                f"discovery token {token!r} not set up (no _config/size)"
            )
        size = int(size_resp.kvs[0].value)

        members_pfx = reg + b"/members/"
        # First-come registration: create-if-absent so a re-joining
        # member keeps its slot and latecomers beyond size are rejected.
        from .server import api as sapi

        my_key = members_pfx + name.encode()
        c.txn(sapi.TxnRequest(
            compare=[sapi.Compare(
                target=sapi.CompareTarget.CREATE,
                result=sapi.CompareResult.EQUAL,
                key=my_key, create_revision=0,
            )],
            success=[sapi.RequestOp(request_put=sapi.PutRequest(
                key=my_key, value=peer_url.encode(),
            ))],
        ))

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            resp = c.get(members_pfx, prefix_end(members_pfx),
                         sort_order=sapi.SortOrder.ASCEND)
            roster: Dict[str, str] = {}
            for kv in resp.kvs[:size]:  # first `size` registrants win
                roster[kv.key[len(members_pfx):].decode()] = kv.value.decode()
            if name not in roster and len(resp.kvs) >= size:
                raise DiscoveryError(
                    f"cluster is full ({size} members registered first)"
                )
            if len(roster) >= size:
                return ",".join(
                    f"{nm}={url}" for nm, url in sorted(roster.items())
                )
            time.sleep(0.2)
        raise DiscoveryError("timed out waiting for cluster roster")
    finally:
        c.close()
