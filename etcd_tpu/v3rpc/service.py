"""The RPC server: six service surfaces over one listener
(ref: api/v3rpc/grpc.go:39-93 service registration; key.go, watch.go,
lease.go, maintenance.go, member.go, auth.go).

Connection model: one read loop per client conn; unary methods run on
worker threads (gRPC handler goroutines); each conn owns one
WatchStream whose poller pushes ``{"stream": wid, "event": ...}``
frames (watch.go's sendLoop/recvLoop pair).
"""

from __future__ import annotations

import socket
import ssl
import struct
import threading
from typing import Any, Dict, Optional

from ..server import api as sapi
from ..server import metrics as smet
from ..server.membership import Member
from . import wire
from .connbase import FramedServerConn


class V3RPCServer:
    def __init__(self, server, bind=("127.0.0.1", 0), tls_info=None) -> None:
        self.s = server
        self._stopped = threading.Event()
        # Client-channel TLS (ref: embed/etcd.go serveClients over
        # transport.NewTLSListener, listener.go:79).
        self._ssl = None
        if tls_info is not None and not tls_info.empty():
            self._ssl = tls_info.server_context()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(bind)
        self._listener.listen(128)
        self.addr = self._listener.getsockname()
        self._conns: set = set()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        for c in list(self._conns):
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._ssl is not None:
                # Handshake off the accept thread: a half-open dialer
                # must not block other clients.
                threading.Thread(target=self._tls_accept, args=(conn,),
                                 daemon=True).start()
            else:
                self._conns.add(conn)
                _Conn(self, conn)

    def _tls_accept(self, conn: socket.socket) -> None:
        try:
            conn = self._ssl.wrap_socket(conn, server_side=True)
        except OSError:  # covers ssl.SSLError
            try:
                conn.close()
            except OSError:
                pass
            return
        self._conns.add(conn)
        if self._stopped.is_set():
            # stop() may have drained _conns while we were handshaking.
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass
            return
        _Conn(self, conn)


class _Conn(FramedServerConn):
    recv_counter = staticmethod(smet.client_grpc_received_bytes.inc)
    sent_counter = staticmethod(smet.client_grpc_sent_bytes.inc)

    def __init__(self, srv: V3RPCServer, sock: socket.socket) -> None:
        self.srv = srv
        self.watch_stream = None
        self._watch_poller: Optional[threading.Thread] = None
        self._observers: Dict[int, threading.Event] = {}
        self._next_observe_id = 0
        self._obs_lock = threading.Lock()
        super().__init__(sock, srv._stopped)

    def _send(self, obj: Dict[str, Any]) -> bool:
        return self.send_frame(obj)

    def encode_result(self, result: Any) -> Any:
        return wire.enc(result)

    def on_close(self) -> None:
        if self.watch_stream is not None:
            self.watch_stream.close()
        with self._obs_lock:
            observers = list(self._observers.values())
            self._observers.clear()
        for stop in observers:
            stop.set()
        self.srv._conns.discard(self.sock)

    # -- dispatch --------------------------------------------------------------

    def dispatch(self, method: str, params: Dict, token: Optional[str]):
        return self._dispatch(method, params, token)

    def _dispatch(self, method: str, params: Dict, token: Optional[str]):
        s = self.srv.s
        if method in ("Range", "Put", "DeleteRange", "Txn", "Compact"):
            req = wire.dec_request(method, params)
            fn = {
                "Range": s.range,
                "Put": s.put,
                "DeleteRange": s.delete_range,
                "Txn": s.txn,
                "Compact": s.compact,
            }[method]
            return fn(req, token=token)

        if method == "Alarm":
            req = wire.dec_request("Alarm", params)
            return s.alarm(req, token=token)

        if method == "WatchCreate":
            return self._watch_create(params)
        if method == "WatchCancel":
            if self.watch_stream is not None:
                self.watch_stream.cancel(params["watch_id"])
            return {"canceled": True}

        if method == "LeaseGrant":
            return s.lease_grant(
                ttl=params["ttl"], lease_id=params.get("id", 0), token=token
            )
        if method == "LeaseRevoke":
            return s.lease_revoke(params["id"], token=token)
        if method == "LeaseKeepAlive":
            ttl = s.lease_renew(
                params["id"], local_only=params.get("local_only", False))
            return {"id": params["id"], "ttl": ttl}
        if method == "LeaseTimeToLive":
            out = s.lease_time_to_live(params["id"], keys=params.get("keys", False))
            if out is None:
                return {"id": params["id"], "ttl": -1}
            return out
        if method == "LeaseLeases":
            return {"leases": s.lease_leases()}

        if method == "MemberAdd":
            m = Member(
                id=params["id"],
                name=params.get("name", ""),
                peer_urls=params.get("peer_urls", []),
                is_learner=params.get("is_learner", False),
            )
            s.add_member(m)
            return {"members": [wire.enc(x.__dict__) for x in s.cluster.member_list()]}
        if method == "MemberRemove":
            s.remove_member(params["id"])
            return {"members": [wire.enc(x.__dict__) for x in s.cluster.member_list()]}
        if method == "MemberPromote":
            s.promote_member(params["id"])
            return {"members": [wire.enc(x.__dict__) for x in s.cluster.member_list()]}
        if method == "MemberList":
            return {"members": [wire.enc(x.__dict__) for x in s.cluster.member_list()]}

        if method == "Status":
            return {
                "member_id": s.id,
                "leader": s.leader(),
                "is_leader": s.is_leader(),
                "raft_term": s._term,
                "applied_index": s.applied_index(),
                "committed_index": s.committed_index(),
                "db_size": s.be.size(),
                "db_size_in_use": s.be.size_in_use(),
                "revision": s.kv.rev(),
            }
        if method == "HashKV":
            h, rev, crev = s.hash_kv(params.get("revision", 0))
            return {"hash": h, "compact_revision": crev, "revision": rev}
        if method == "Defragment":
            s.defrag()
            return {}
        if method == "MoveLeader":
            s.node.transfer_leadership(s.leader(), params["target_id"])
            return {}
        if method == "Snapshot":
            import os
            import tempfile

            fd, tmp = tempfile.mkstemp(suffix=".snap.db")
            os.close(fd)
            s.be.snapshot_to(tmp)
            with open(tmp, "rb") as f:
                data = f.read()
            os.remove(tmp)
            return {"blob": data.hex()}

        if method in ("Campaign", "Proclaim", "Leader", "Resign",
                      "Observe", "ObserveCancel"):
            return self._election(method, params, token)
        if method == "Lock":
            # Bounded even for "wait forever" callers so an abandoned
            # conn can't pin a handler thread indefinitely.
            timeout = params.get("timeout") or 24 * 3600.0
            key = s.lock_server.lock(
                bytes.fromhex(params["name"]), params["lease"],
                timeout=timeout, token=token)
            return {"key": key.hex(), "revision": s.kv.rev()}
        if method == "Unlock":
            s.lock_server.unlock(bytes.fromhex(params["key"]), token=token)
            return {"revision": s.kv.rev()}

        if method == "Authenticate":
            token_out = s.authenticate(params["name"], params["password"])
            return {"token": token_out}
        if method == "Auth":
            req = wire.dec_request("Auth", params)
            resp = s.auth_op(req, token=token)
            return resp or {}
        if method == "AuthStatus":
            return {
                "enabled": s.auth_store.is_auth_enabled(),
                "auth_revision": s.auth_store.revision(),
            }
        if method == "UserGet":
            u = s.auth_store.user_get(params["name"])
            return {"name": u.name, "roles": u.roles}
        if method == "UserList":
            return {"users": s.auth_store.user_list()}
        if method == "RoleGet":
            r = s.auth_store.role_get(params["role"])
            return {
                "name": r.name,
                "perms": [
                    {
                        "type": int(p.perm_type),
                        "key": p.key.hex(),
                        "range_end": p.range_end.hex(),
                    }
                    for p in r.key_permissions
                ],
            }
        if method == "RoleList":
            return {"roles": s.auth_store.role_list()}

        raise ValueError(f"unknown method {method!r}")

    # -- election/lock (v3election.go / v3lock.go) ----------------------------

    def _election(self, method: str, params: Dict, token: Optional[str]):
        from ..server.v3election import LeaderKey

        s = self.srv.s
        es = s.election_server

        def dec_leader(d: Dict) -> LeaderKey:
            return LeaderKey(
                name=bytes.fromhex(d["name"]), key=bytes.fromhex(d["key"]),
                rev=d["rev"], lease=d["lease"])

        def enc_leader(lk: LeaderKey) -> Dict:
            return {"name": lk.name.hex(), "key": lk.key.hex(),
                    "rev": lk.rev, "lease": lk.lease}

        if method == "Campaign":
            lk = es.campaign(
                bytes.fromhex(params["name"]), params["lease"],
                bytes.fromhex(params.get("value", "")),
                timeout=params.get("timeout") or 24 * 3600.0, token=token)
            return {"leader": enc_leader(lk), "revision": s.kv.rev()}
        if method == "Proclaim":
            es.proclaim(dec_leader(params["leader"]),
                        bytes.fromhex(params.get("value", "")), token=token)
            return {"revision": s.kv.rev()}
        if method == "Resign":
            es.resign(dec_leader(params["leader"]), token=token)
            return {"revision": s.kv.rev()}
        if method == "Leader":
            kv = es.leader(bytes.fromhex(params["name"]), token=token)
            return {"kv": wire.enc(kv), "revision": s.kv.rev()}
        if method == "Observe":
            with self._obs_lock:
                oid = self._next_observe_id
                self._next_observe_id += 1
                stop = threading.Event()
                self._observers[oid] = stop
            name = bytes.fromhex(params["name"])

            def pump() -> None:
                def push(kv) -> bool:
                    return self._send({"ostream": oid, "kv": wire.enc(kv)})

                try:
                    es.observe(name, push, stop, token=token)
                finally:
                    with self._obs_lock:
                        self._observers.pop(oid, None)

            threading.Thread(target=pump, daemon=True,
                             name=f"observe-{oid}").start()
            return {"observe_id": oid}
        if method == "ObserveCancel":
            with self._obs_lock:
                stop = self._observers.pop(params["observe_id"], None)
            if stop is not None:
                stop.set()
            return {}
        raise ValueError(f"unknown election method {method!r}")

    # -- watch (watch.go stream loops) ----------------------------------------

    def _watch_create(self, params: Dict) -> Dict:
        s = self.srv.s
        if self.watch_stream is None:
            self.watch_stream = s.kv.new_watch_stream()
            self._watch_poller = threading.Thread(
                target=self._watch_push_loop, daemon=True
            )
            self._watch_poller.start()
        key = bytes.fromhex(params["key"])
        end_hex = params.get("range_end", "")
        end = bytes.fromhex(end_hex) if end_hex else None
        if end == b"\x00":
            end = b""  # open end: every key ≥ key (the \x00 sentinel)
        wid = self.watch_stream.watch(
            key, end, start_rev=params.get("start_revision", 0)
        )
        return {"watch_id": wid, "revision": s.kv.rev()}

    def _watch_push_loop(self) -> None:
        ws = self.watch_stream
        while not self.srv._stopped.is_set():
            resp = ws.poll(timeout=0.2)
            if resp is None:
                continue
            ok = self._send(
                {
                    "stream": resp.watch_id,
                    "event": {
                        "revision": resp.revision,
                        "events": [wire.enc_event(ev) for ev in resp.events],
                    },
                }
            )
            if not ok:
                return
