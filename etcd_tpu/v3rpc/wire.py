"""Framing + dataclass (de)serialization for the client RPC surface.

Frames: u32 little-endian length + JSON body. Requests:
``{"id": n, "method": str, "params": {...}, "token": str?}``;
responses: ``{"id": n, "result": ...}`` or
``{"id": n, "error": {"type": str, "msg": str}}``; server-push stream
events carry ``{"stream": watch_id, "event": {...}}`` instead of "id".
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, Optional

from ..server import api as sapi
from ..storage.mvcc.kv import Event, EventType, KeyValue

MAX_FRAME = 512 << 20


def write_frame(sock: socket.socket, obj: Dict[str, Any]) -> int:
    body = json.dumps(obj, separators=(",", ":")).encode()
    sock.sendall(struct.pack("<I", len(body)) + body)
    return 4 + len(body)


def read_frame(sock: socket.socket, counter=None) -> Optional[Dict[str, Any]]:
    """`counter`, if given, is called with the frame size in bytes
    (server-side traffic metrics)."""
    hdr = _read_exact(sock, 4)
    if hdr is None:
        return None
    (ln,) = struct.unpack("<I", hdr)
    if ln > MAX_FRAME:
        return None
    body = _read_exact(sock, ln)
    if body is None:
        return None
    if counter is not None:
        counter(4 + ln)
    return json.loads(body.decode())


def _read_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


# -- dataclass <-> json dict ---------------------------------------------------

enc = sapi._enc  # generic dataclass/bytes/enum encoder


def dec_kv(d: Optional[Dict]) -> Optional[KeyValue]:
    if d is None:
        return None
    return KeyValue(
        key=bytes.fromhex(d.get("key", "")),
        create_revision=d.get("create_revision", 0),
        mod_revision=d.get("mod_revision", 0),
        version=d.get("version", 0),
        value=bytes.fromhex(d.get("value", "")),
        lease=d.get("lease", 0),
    )


def dec_header(d: Optional[Dict]) -> sapi.ResponseHeader:
    d = d or {}
    return sapi.ResponseHeader(
        cluster_id=d.get("cluster_id", 0),
        member_id=d.get("member_id", 0),
        revision=d.get("revision", 0),
        raft_term=d.get("raft_term", 0),
    )


def dec_event(d: Dict) -> Event:
    return Event(
        type=EventType(d.get("type", 0)),
        kv=dec_kv(d.get("kv")) or KeyValue(),
        prev_kv=dec_kv(d.get("prev_kv")),
    )


def enc_event(ev: Event) -> Dict:
    out: Dict[str, Any] = {"type": int(ev.type), "kv": enc(ev.kv)}
    if ev.prev_kv is not None:
        out["prev_kv"] = enc(ev.prev_kv)
    return out


def dec_response(method: str, d: Dict):
    """Rehydrate a response dataclass for the client."""
    if method in ("Range",):
        return sapi.RangeResponse(
            header=dec_header(d.get("header")),
            kvs=[dec_kv(x) for x in d.get("kvs", [])],
            more=d.get("more", False),
            count=d.get("count", 0),
        )
    if method == "Put":
        return sapi.PutResponse(
            header=dec_header(d.get("header")), prev_kv=dec_kv(d.get("prev_kv"))
        )
    if method == "DeleteRange":
        return sapi.DeleteRangeResponse(
            header=dec_header(d.get("header")),
            deleted=d.get("deleted", 0),
            prev_kvs=[dec_kv(x) for x in d.get("prev_kvs", [])],
        )
    if method == "Txn":
        return dec_txn_response(d)
    if method == "Compact":
        return sapi.CompactionResponse(header=dec_header(d.get("header")))
    if method == "LeaseGrant":
        return sapi.LeaseGrantResponse(
            header=dec_header(d.get("header")),
            id=d.get("id", 0),
            ttl=d.get("ttl", 0),
            error=d.get("error", ""),
        )
    if method == "LeaseRevoke":
        return sapi.LeaseRevokeResponse(header=dec_header(d.get("header")))
    if method == "Alarm":
        return sapi.AlarmResponse(
            header=dec_header(d.get("header")),
            alarms=[
                sapi.AlarmMember(
                    member_id=a.get("member_id", 0),
                    alarm=sapi.AlarmType(a.get("alarm", 0)),
                )
                for a in d.get("alarms", [])
            ],
        )
    return d  # generic dict result


def dec_txn_response(d: Dict) -> sapi.TxnResponse:
    resps = []
    for r in d.get("responses", []):
        op = sapi.ResponseOp()
        if "response_range" in r:
            op.response_range = dec_response("Range", r["response_range"])
        if "response_put" in r:
            op.response_put = dec_response("Put", r["response_put"])
        if "response_delete_range" in r:
            op.response_delete_range = dec_response(
                "DeleteRange", r["response_delete_range"]
            )
        if "response_txn" in r:
            op.response_txn = dec_txn_response(r["response_txn"])
        resps.append(op)
    return sapi.TxnResponse(
        header=dec_header(d.get("header")),
        succeeded=d.get("succeeded", False),
        responses=resps,
    )


def dec_request(method: str, params: Dict):
    """Rehydrate a request dataclass server-side."""
    b = sapi._build
    if method == "Range":
        return b(sapi.RangeRequest, params)
    if method == "Put":
        return b(sapi.PutRequest, params)
    if method == "DeleteRange":
        return b(sapi.DeleteRangeRequest, params)
    if method == "Txn":
        return b(sapi.TxnRequest, params)
    if method == "Compact":
        return b(sapi.CompactionRequest, params)
    if method == "Alarm":
        return b(sapi.AlarmRequest, params)
    if method == "Auth":
        return b(sapi.AuthRequest, params)
    return params
