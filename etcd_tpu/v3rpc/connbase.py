"""Shared framed-RPC server connection scaffolding, used by both the
member RPC server (service.py) and the grpcproxy — one copy of the
frame pump, per-request threading, and error-frame shaping."""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Dict, Optional

from . import wire
from ..pkg import rpctypes


class FramedServerConn:
    """One downstream connection: read loop spawning a handler thread
    per request frame; writes serialized under a lock.

    Subclasses implement ``dispatch(method, params, token) -> result``
    and may override ``on_close`` / ``on_sent`` / byte counters."""

    recv_counter: Optional[Callable[[int], None]] = None
    sent_counter: Optional[Callable[[int], None]] = None

    def __init__(self, sock: socket.socket,
                 stopped: "threading.Event") -> None:
        self.sock = sock
        self.wlock = threading.Lock()
        self._stopped = stopped
        threading.Thread(target=self._read_loop, daemon=True).start()

    # -- override points -------------------------------------------------------

    def dispatch(self, method: str, params: Dict, token: Optional[str]) -> Any:
        raise NotImplementedError

    def encode_result(self, result: Any) -> Any:
        return result

    def encode_error(self, e: Exception) -> Dict[str, Any]:
        """Typed error frame. Canonical-table errors carry a stable
        symbolic code + gRPC status code (ref: api/v3rpc/rpctypes/
        error.go); the class name rides along as ``type`` for older
        peers."""
        out: Dict[str, Any] = {"type": type(e).__name__, "msg": str(e)}
        entry = rpctypes.entry_for_exception(e)
        if entry is not None:
            sym, code, _canonical = entry
            out["code"] = sym
            out["grpcCode"] = int(code)
        return out

    def on_close(self) -> None:
        pass

    def after_send(self, method: str, params: Dict, result: Any) -> None:
        """Runs after the response frame went out (ordering hook: e.g.
        start watch event pumps only once the create response is on the
        wire)."""

    # -- machinery -------------------------------------------------------------

    def send_frame(self, obj: Dict[str, Any]) -> bool:
        try:
            with self.wlock:
                n = wire.write_frame(self.sock, obj)
            if self.sent_counter is not None:
                self.sent_counter(n)
            return True
        except OSError:
            return False

    def _read_loop(self) -> None:
        try:
            while not self._stopped.is_set():
                req = wire.read_frame(self.sock, counter=self.recv_counter)
                if req is None:
                    return
                threading.Thread(
                    target=self._handle, args=(req,), daemon=True
                ).start()
        except OSError:
            return  # peer went away; on_close in finally
        except Exception:  # noqa: BLE001 — a framing crash must be loud
            import sys
            import traceback
            print("v3rpc conn read loop crashed:", file=sys.stderr)
            traceback.print_exc()
        finally:
            self.on_close()
            try:
                self.sock.close()
            except OSError:
                pass

    def _handle(self, req: Dict[str, Any]) -> None:
        rid = req.get("id")
        method = req.get("method", "")
        params = req.get("params", {}) or {}
        token = req.get("token")
        try:
            result = self.dispatch(method, params, token)
            self.send_frame({"id": rid, "result": self.encode_result(result)})
            self.after_send(method, params, result)
        except Exception as e:  # noqa: BLE001 — typed error to the client
            self.send_frame({"id": rid, "error": self.encode_error(e)})
