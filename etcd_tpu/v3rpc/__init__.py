"""Client-facing RPC surface (ref: server/etcdserver/api/v3rpc/ — the
gRPC services KV/Watch/Lease/Cluster/Maintenance/Auth).

The reference serves protobuf over gRPC/HTTP2; this serves the same six
service surfaces over length-prefixed JSON frames on TCP — unary
request/response plus server-push streams for watch events and lease
keepalives. Interceptor duties (auth token resolution, leader checks)
live in the method handlers.
"""

from .service import V3RPCServer  # noqa: F401
from .wire import read_frame, write_frame  # noqa: F401
