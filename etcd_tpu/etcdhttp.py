"""HTTP observability endpoints: /health, /readyz, /livez, /metrics,
/version (ref: server/etcdserver/api/etcdhttp/{metrics,base}.go,
embed/etcd.go:731 serveMetrics).

Health semantics follow etcdhttp/metrics.go:34-121:

* ``/health`` — unhealthy if a NOSPACE/CORRUPT alarm is raised (unless
  excluded via ``?exclude=NOSPACE``), if there is no leader (unless
  ``?serializable=true``), and optionally proves linearizable progress
  with a ReadIndex barrier.
* ``/readyz`` / ``/livez`` — aggregate check endpoints with per-check
  listing via ``?verbose``.
* ``/metrics`` — the pkg.metrics registry in Prometheus text format.
* ``/version`` — {"etcdserver", "etcdcluster"}.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from . import version as ver
from .pkg import metrics as pmet


class EtcdHTTP:
    """Serves health/metrics for one EtcdServer. `server` may be None
    (metrics-only listener)."""

    def __init__(
        self,
        server=None,
        bind: Tuple[str, int] = ("127.0.0.1", 0),
        registry: Optional[pmet.Registry] = None,
        serve_gateway: bool = False,
    ) -> None:
        """`serve_gateway` mounts the JSON write surface (/v3/...) on
        this listener — keep it OFF for metrics/health listeners."""
        self.server = server
        self.serve_gateway = serve_gateway
        self.registry = registry or pmet.DEFAULT
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                outer._route(self)

            def do_POST(self):
                outer._gateway(self)

        self.httpd = ThreadingHTTPServer(bind, Handler)
        self.addr = self.httpd.server_address
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True,
        )
        self._thread.start()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)

    # -- routing ---------------------------------------------------------------

    def _route(self, h: BaseHTTPRequestHandler) -> None:
        u = urlparse(h.path)
        q = parse_qs(u.query, keep_blank_values=True)
        if u.path == "/metrics":
            self._refresh_gauges()
            body = self.registry.expose().encode()
            self._reply(h, 200, body, "text/plain; version=0.0.4")
        elif u.path == "/version":
            body = json.dumps(
                {
                    "etcdserver": ver.SERVER_VERSION,
                    "etcdcluster": ver.CLUSTER_VERSION,
                }
            ).encode()
            self._reply(h, 200, body, "application/json")
        elif u.path == "/health":
            self._health(h, q)
        elif u.path in ("/readyz", "/livez"):
            self._checkz(h, u.path, q)
        else:
            self._reply(h, 404, b"404 page not found\n")

    def _gateway(self, h: BaseHTTPRequestHandler) -> None:
        """The grpc-gateway JSON interop surface: POST /v3/... with a
        JSON body, bytes base64 (ref: embed/serve.go grpc-gateway mux;
        gatewayjson.py carries the route table)."""
        u = urlparse(h.path)
        not_found = json.dumps({
            "error": "Not Found", "code": 5, "message": "Not Found",
        }).encode()
        if (not self.serve_gateway or not u.path.startswith("/v3/")
                or self.server is None):
            self._reply(h, 404, not_found, "application/json")
            return
        from . import gatewayjson

        try:
            ln = int(h.headers.get("Content-Length") or 0)
            body = json.loads(h.rfile.read(ln) or b"{}")
            token = h.headers.get("Authorization") or None
            out = gatewayjson.handle(self.server, u.path, body, token=token)
            self._reply(h, 200, json.dumps(out).encode(),
                        "application/json")
        except KeyError:
            self._reply(h, 404, not_found, "application/json")
        except Exception as e:  # noqa: BLE001 — gateway error body
            err = {"error": str(e), "code": 2,
                   "message": str(e)}
            self._reply(h, 400, json.dumps(err).encode(),
                        "application/json")

    def _reply(
        self, h: BaseHTTPRequestHandler, code: int, body: bytes,
        ctype: str = "text/plain; charset=utf-8",
    ) -> None:
        try:
            h.send_response(code)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(body)))
            h.end_headers()
            h.wfile.write(body)
        except OSError:
            pass

    def _refresh_gauges(self) -> None:
        """Point-in-time store gauges, refreshed per scrape (the
        reference refreshes them on backend commit hooks)."""
        s = self.server
        if s is None:
            return
        from .storage.mvcc import metrics as mmet

        try:
            mmet.db_total_size.set(s.be.size())
            mmet.db_in_use_size.set(s.be.size_in_use())
            mmet.current_revision.set(s.kv.rev())
            mmet.compact_revision.set(s.kv.compact_rev)
        except Exception:  # noqa: BLE001 — scrape must not 500
            pass

    # -- health (etcdhttp/metrics.go checkHealth) ------------------------------

    def _health(self, h, q) -> None:
        s = self.server
        if s is None:
            self._reply(h, 200, json.dumps({"health": "true"}).encode(),
                        "application/json")
            return
        excluded = {a for vals in q.get("exclude", []) for a in vals.split(",")}
        serializable = q.get("serializable", ["false"])[0] == "true"

        reason = ""
        healthy = True
        # Alarm check (checkAlarms).
        for am in s.alarms.get():
            short = am.alarm.name  # "NOSPACE" / "CORRUPT"
            if short in excluded:
                continue
            healthy, reason = False, f"alarm activated: {short}"
            break
        # Leader check (checkLeader) — skipped for serializable probes.
        if healthy and not serializable:
            from .raft.raft import NONE

            if s.leader() == NONE:
                healthy, reason = False, "web server has no leader"
        if healthy and not serializable:
            try:
                s.linearizable_read_notify(timeout=2.0)
            except Exception as e:  # noqa: BLE001
                healthy, reason = False, f"QGET ERROR:{type(e).__name__}"
        body = json.dumps(
            {"health": "true" if healthy else "false", "reason": reason}
        ).encode()
        self._reply(h, 200 if healthy else 503, body, "application/json")

    def _checkz(self, h, path: str, q) -> None:
        s = self.server
        checks = {}
        if s is not None:
            if path == "/readyz":
                from .raft.raft import NONE
                from .server.api import AlarmType

                checks["data_corruption"] = not any(
                    am.alarm == AlarmType.CORRUPT for am in s.alarms.get()
                )
                checks["leader"] = s.leader() != NONE
            # A real serializable read proves the local read path is alive
            # (etcdhttp/health.go serializable_read check).
            from .server.api import RangeRequest

            try:
                s.range(RangeRequest(key=b"\x00", serializable=True))
                checks["serializable_read"] = True
            except Exception:  # noqa: BLE001
                checks["serializable_read"] = False
        ok = all(checks.values())
        if "verbose" in q:
            lines = [
                f"[{'+' if v else '-'}]{k} ok" for k, v in checks.items()
            ]
            lines.append("ok" if ok else "failed")
            body = ("\n".join(lines) + "\n").encode()
        else:
            body = b"ok\n" if ok else b"failed\n"
        self._reply(h, 200 if ok else 503, body)
