"""Lock-order recorder: lockdep for the hosting path's thread soup.

The batched hosting layer runs a member round thread, a WAL drain
worker, a chaos delayed-delivery pump, and per-peer TCP sender lanes —
55 files in this tree spawn threads and nothing checks acquisition
discipline. This module instruments ``threading.Lock``/``RLock``
creation inside a scope, aggregates acquisitions by *creation site*
(lockdep-style lock classes), builds the cross-thread acquisition graph
(an edge A->B means some thread acquired B while holding A), and fails
on cycles — the statistical signature of an eventual deadlock, caught
even on runs where the interleaving never actually deadlocks.

Usage (chaos/hosting tests)::

    with LockOrderRecorder() as rec:
        ...build members/routers/harness...   # their locks get wrapped
        ...run the episode...
    rec.check()        # raises LockOrderViolation on any cycle

Locks created outside the ``with`` block are untouched; instances
created inside keep recording after the block exits (the run phase),
until ``rec.disable()``. Same-site self-edges (two *instances* of one
lock class nested, e.g. member A's _lock inside member B's during a
cross-member call) are recorded but excluded from cycle detection by
default — they are one abstraction level finer than class-granular
ordering can judge; ``check(strict=True)`` includes them.
"""

from __future__ import annotations

import sys
import threading
from typing import Dict, List, Optional, Set, Tuple


class LockOrderViolation(AssertionError):
    pass


def _creation_frame(skip_files: Tuple[str, ...]) -> Tuple[str, int]:
    """(full path, lineno) of the first non-infrastructure frame."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(skip_files) and "threading" not in fn:
            return fn, f.f_lineno
        f = f.f_back
    return "<unknown>", 0


class _RecordedLock:
    """Proxy around a real Lock/RLock; records acquisition order into
    the owning recorder. Supports the stdlib lock protocol including
    what threading.Condition needs from a raw lock."""

    __slots__ = ("_real", "_rec", "site")

    def __init__(self, real, rec: "LockOrderRecorder", site: str):
        self._real = real
        self._rec = rec
        self.site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._rec._on_acquire(self)
        return got

    def release(self):
        self._rec._on_release(self)
        self._real.release()

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._real.locked()

    # RLock introspection Condition uses when available.
    def _is_owned(self):
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        # Plain Lock fallback (mirrors Condition's own heuristic).
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __getattr__(self, name):
        # Condition PROBES lock._release_save/_acquire_restore and falls
        # back to single release()/acquire() when the probe raises
        # AttributeError. Forward the probe to the real lock: a wrapped
        # RLock must expose them (else a recursively-held Condition
        # wait() releases ONE level and the notifier deadlocks), and a
        # wrapped plain Lock must NOT (so the probe fails naturally and
        # the recorded release()/acquire() fallback runs).
        if name in ("_release_save", "_acquire_restore", "_at_fork_reinit"):
            return getattr(self._real, name)
        raise AttributeError(name)

    def __repr__(self):
        return f"<RecordedLock {self.site} wrapping {self._real!r}>"


class LockOrderRecorder:
    """Patch threading.Lock/RLock factories inside a scope; build the
    held->acquired graph across all threads; detect ordering cycles."""

    _SKIP_FILES = ("lockorder.py",)

    def __init__(self, name: Optional[str] = None, include=None):
        """`include`: optional predicate on the creating frame's FULL
        file path; locks created at non-matching sites stay plain
        (unrecorded). The chaos tests pass `lambda p: "etcd_tpu" in p`
        so the graph covers the drain/pump/sender-lane locks without
        jax/stdlib internals muddying cycle detection."""
        self.name = name or "lockorder"
        self.include = include
        self._orig_lock = None
        self._orig_rlock = None
        self._enabled = False
        self._patched = False
        # (held_site, acquired_site) -> sample (thread, count)
        self._mu = threading.Lock()  # real lock: created pre-patch
        self.edges: Dict[Tuple[str, str], Dict] = {}
        self._tls = threading.local()
        self.sites: Set[str] = set()

    # -- patching -------------------------------------------------------------

    def __enter__(self) -> "LockOrderRecorder":
        self.enable()
        return self

    def __exit__(self, *exc) -> None:
        self.unpatch()

    def enable(self) -> None:
        assert not self._patched, "recorder already active"
        self._orig_lock, self._orig_rlock = threading.Lock, threading.RLock
        rec = self

        def make_lock():
            path, line = _creation_frame(rec._SKIP_FILES)
            if rec.include is not None and not rec.include(path):
                return rec._orig_lock()
            return _RecordedLock(
                rec._orig_lock(), rec, f"{path.rsplit('/', 1)[-1]}:{line}")

        def make_rlock():
            path, line = _creation_frame(rec._SKIP_FILES)
            if rec.include is not None and not rec.include(path):
                return rec._orig_rlock()
            return _RecordedLock(
                rec._orig_rlock(), rec, f"{path.rsplit('/', 1)[-1]}:{line}")

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._patched = True
        self._enabled = True

    def unpatch(self) -> None:
        """Restore the factories; existing wrapped locks keep
        recording until disable()."""
        if self._patched:
            threading.Lock = self._orig_lock
            threading.RLock = self._orig_rlock
            self._patched = False

    def disable(self) -> None:
        self.unpatch()
        self._enabled = False

    # -- recording ------------------------------------------------------------

    def _held(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _on_acquire(self, lock: _RecordedLock) -> None:
        if not self._enabled:
            return
        stack = self._held()
        if stack:
            edge = (stack[-1], lock.site)
            with self._mu:
                info = self.edges.setdefault(
                    edge,
                    {"count": 0, "thread": threading.current_thread().name})
                info["count"] += 1
        with self._mu:
            self.sites.add(lock.site)
        stack.append(lock.site)

    def _on_release(self, lock: _RecordedLock) -> None:
        if not self._enabled:
            return
        stack = self._held()
        # Remove the most recent matching site (non-LIFO release legal).
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == lock.site:
                del stack[i]
                break

    # -- analysis -------------------------------------------------------------

    def graph(self, strict: bool = False) -> Dict[str, Set[str]]:
        with self._mu:
            g: Dict[str, Set[str]] = {}
            for (a, b) in self.edges:
                if a == b and not strict:
                    continue
                g.setdefault(a, set()).add(b)
            return g

    def cycles(self, strict: bool = False) -> List[List[str]]:
        """Elementary cycles in the acquisition graph (DFS with a
        recursion stack; one representative per back edge)."""
        g = self.graph(strict)
        out: List[List[str]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        visited: Set[str] = set()

        def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
            visited.add(node)
            on_path.add(node)
            path.append(node)
            for nxt in sorted(g.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = tuple(sorted(set(cyc)))
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(cyc)
                elif nxt not in visited:
                    dfs(nxt, path, on_path)
            path.pop()
            on_path.discard(node)

        for node in sorted(g):
            if node not in visited:
                dfs(node, [], set())
        return out

    def check(self, strict: bool = False) -> None:
        cyc = self.cycles(strict)
        if cyc:
            detail = []
            with self._mu:
                for c in cyc:
                    pairs = list(zip(c, c[1:]))
                    detail.append(" -> ".join(c) + "  (" + "; ".join(
                        f"{a}->{b} x{self.edges[(a, b)]['count']} on "
                        f"{self.edges[(a, b)]['thread']}"
                        for a, b in pairs if (a, b) in self.edges) + ")")
            raise LockOrderViolation(
                f"[{self.name}] lock acquisition-order cycle(s) — an "
                "eventual deadlock under the wrong interleaving:\n  "
                + "\n  ".join(detail))
