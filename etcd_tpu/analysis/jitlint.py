"""jitlint: AST lint passes for jax hot-path code (pure stdlib).

Every perf regression and measurement artifact this repo has shipped so
far was a *mechanically detectable* class of bug: a missing fence made a
675M/s record (BENCH r4), per-message host syncs burned 45% of each
hosted round (fixed by hand in PR 6), and an accidental Python branch on
a tracer silently forces a device->host transfer per round. These
passes encode those classes.

Jit-reachability
----------------
A function is a *jit root* when it is decorated with ``jax.jit`` /
``functools.partial(jax.jit, ...)`` or passed by name to
``jax.jit`` / ``vmap`` / ``pmap`` / ``lax.scan`` / ``lax.cond`` /
``lax.while_loop`` / ``lax.fori_loop`` / ``checkpoint`` anywhere in the
analyzed file set. Reachability then propagates through plain-name
calls, across files via ``from .mod import name`` within the set.

Device values (syntactic, conservative)
---------------------------------------
Inside jit-reachable code: parameters are tracers unless annotated with
a static type (``bool``/``int``/``float``/``str``/``*Config``) or named
``self``/``cfg``/``config``; results of ``jnp.*``/``jax.*`` calls are
device values; devness propagates through arithmetic, comparisons,
subscripts, attribute access (except the static ``.shape``/``.dtype``/
``.ndim``/``.size``) and assignment. Container literals are NOT device
(a Python list of tracers is legal to iterate).

Rules
-----
- ``tracer-branch``     Python control flow (if/while/assert/ternary/
                        and/or/iteration) on a device value in jit code.
- ``host-sync-in-jit``  ``.item()``/``.tolist()``/``bool()``/``int()``/
                        ``float()``/``np.*``/``block_until_ready``/
                        ``device_get`` on a device value in jit code.
- ``narrow-lane-arith`` arithmetic on a value narrowed to int8/int16,
                        or narrow-lane state-field access in a jit root
                        before its ``widen_state`` call.
- ``donated-use``       a buffer passed at a donated position of a
                        ``jax.jit(..., donate_argnums=...)`` callable is
                        read again before being rebound.
- ``impure-jit``        ``time.*``/``random.*``/``np.random.*``/
                        ``datetime.*``/``uuid.*``/``secrets.*``/
                        ``os.urandom`` inside jit code.
- ``dict-order-static`` a set literal/comprehension or unsorted
                        ``.keys()/.values()/.items()`` feeding a
                        ``jax.jit(...)`` call (static-arg hash order).
- ``sync-in-loop``      ``np.asarray``/``np.array``/``.item()``/
                        ``block_until_ready``/``device_get`` inside a
                        for/while loop of HOST code in a jax-importing
                        module — the per-item-sync class PR 6 spent a
                        whole tentpole deleting. One bulk gather per
                        round is the blessed idiom; loops are not.

Waivers
-------
Findings are suppressible ONLY via an inline pragma — a comment
reading ``jitlint: waive(<rule>) -- <reason>`` on the offending line
or the line directly above.

The reason after ``--`` is mandatory (``waiver-malformed`` otherwise);
a pragma that suppresses nothing is itself a finding
(``waiver-unused``), so stale waivers cannot accumulate.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "tracer-branch": (
        "Python control flow on a device value inside jit-reachable "
        "code (forces concretization: per-round device->host sync or "
        "TracerBoolConversionError)"),
    "host-sync-in-jit": (
        "host conversion (.item()/.tolist()/bool()/int()/float()/np.*/"
        "block_until_ready/device_get) on a device value inside "
        "jit-reachable code"),
    "narrow-lane-arith": (
        "arithmetic on an int8/int16-narrowed value (narrow lanes are "
        "storage-only: widen to i32 at kernel entry before any math, "
        "else the win silently becomes wrap-around bugs)"),
    "donated-use": (
        "use of a buffer after passing it at a donated position "
        "(donated buffers are freed by XLA; reading one is "
        "use-after-free at the runtime's mercy)"),
    "impure-jit": (
        "impure call (time/random/datetime/uuid/secrets/os.urandom) "
        "inside jit-reachable code (baked in at trace time, silently "
        "constant thereafter)"),
    "dict-order-static": (
        "dict/set iteration order feeding a jax.jit static argument "
        "(hash-order differences recompile per process and blow the "
        "compile budget)"),
    "sync-in-loop": (
        "device sync (np.asarray/np.array/.item()/block_until_ready/"
        "device_get) inside a host loop — sync once in bulk per round, "
        "not per item"),
    "waiver-malformed": (
        "jitlint waive pragma without a ' -- <reason>' justification"),
    "waiver-unused": (
        "jitlint waive pragma that suppresses no finding (stale — "
        "remove it)"),
    "syntax-error": (
        "file failed to parse — nothing else can be checked"),
}

_JIT_WRAPPERS = {
    "jit", "vmap", "pmap", "scan", "cond", "while_loop", "fori_loop",
    "checkpoint", "remat", "custom_vjp", "custom_jvp", "switch",
}
_STATIC_PARAM_NAMES = {"self", "cls", "cfg", "config"}
_STATIC_ANNOTATIONS = {"bool", "int", "float", "str", "bytes"}
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding"}
_NARROW_CASTS = {"int8", "int16", "I8", "I16", "i8", "i16"}
# Mirrors state.NARROW_DTYPES (kept literal: jitlint imports nothing
# from the package it lints).
NARROW_FIELDS = {
    "role", "vote", "lead", "transferee", "votes", "pr_state", "inflight",
}
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_IMPURE_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.", "datetime.",
    "uuid.", "secrets.",
)
_IMPURE_EXACT = {"os.urandom", "time", "input"}

_WAIVE_RE = re.compile(
    r"#\s*jitlint:\s*waive\(([^)]*)\)(?:\s*--\s*(\S.*))?")


@dataclass
class Finding:
    path: str
    line: int
    rule: str
    message: str
    waived: bool = False
    reason: str = ""

    def format(self) -> str:
        tag = f" [waived: {self.reason}]" if self.waived else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tag}"


@dataclass
class _Waiver:
    line: int  # line the pragma suppresses findings on
    rules: Set[str]
    reason: str
    pragma_line: int
    used: bool = False


@dataclass(eq=False)
class _FuncRec:
    module: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    jit_root: bool = False
    reachable: bool = False
    calls: Set[str] = field(default_factory=set)  # local names called


@dataclass(eq=False)
class _ModRec:
    name: str
    path: str
    tree: ast.Module
    source_lines: List[str]
    imports_jax: bool = False
    # local name -> (module, name) for `from .mod import name`
    import_map: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    funcs: Dict[str, _FuncRec] = field(default_factory=dict)  # by simple name
    waivers: List[_Waiver] = field(default_factory=list)
    findings: List[Finding] = field(default_factory=list)
    # callable-name -> donated positional indexes
    donators: Dict[str, Tuple[int, ...]] = field(default_factory=dict)


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _parse_waivers(source: str, path: str,
                   findings: List[Finding]) -> List[_Waiver]:
    """Scan COMMENT tokens (only — string literals that merely mention
    the pragma syntax, like this module's own docs, never match)."""
    import io
    import tokenize

    waivers: List[_Waiver] = []
    lines = source.splitlines()
    comments: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                comments.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError):
        pass
    for i, text in comments:
        m = _WAIVE_RE.search(text)
        if not m:
            if "jitlint:" in text and "waive(" in text:
                findings.append(Finding(
                    path, i, "waiver-malformed",
                    "unparseable jitlint pragma (expected "
                    "'# jitlint: waive(<rule>) -- <reason>')"))
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        unknown = rules - set(RULES)
        if unknown or not rules:
            findings.append(Finding(
                path, i, "waiver-malformed",
                f"unknown rule(s) in waive pragma: {sorted(unknown)}"))
            rules &= set(RULES)
            if not rules:
                # Nothing left to waive — don't also append an empty
                # waiver that waiver-unused would re-report as noise.
                continue
        if not reason:
            findings.append(Finding(
                path, i, "waiver-malformed",
                "waive pragma missing ' -- <reason>' justification"))
            continue
        # A standalone comment line waives the next non-comment line;
        # a trailing pragma waives its own line.
        target = i
        full_line = lines[i - 1] if i <= len(lines) else ""
        if full_line.lstrip().startswith("#"):
            target = i + 1
            while target <= len(lines) and (
                    not lines[target - 1].strip()
                    or lines[target - 1].lstrip().startswith("#")):
                target += 1
        waivers.append(_Waiver(target, rules, reason, i))
    return waivers


# -----------------------------------------------------------------------------
# Module collection
# -----------------------------------------------------------------------------


def _collect_module(path: str, source: str) -> _ModRec:
    tree = ast.parse(source, filename=path)
    name = os.path.splitext(os.path.basename(path))[0]
    rec = _ModRec(name, path, tree, source.splitlines())
    rec.waivers = _parse_waivers(source, path, rec.findings)

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    rec.imports_jax = True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod == "jax" or mod.startswith("jax."):
                rec.imports_jax = True
            base = mod.rsplit(".", 1)[-1] if mod else ""
            for a in node.names:
                rec.import_map[a.asname or a.name] = (base, a.name)

    def collect_funcs(body, prefix: str):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{node.name}"
                fr = _FuncRec(rec.name, qual, node)
                # Simple-name index: inner defs shadow outers only if
                # duplicate names collide, which is fine for our use.
                rec.funcs.setdefault(node.name, fr)
                for d in node.decorator_list:
                    dd = _dotted(d) or ""
                    if isinstance(d, ast.Call):
                        dd = _dotted(d.func) or ""
                        for sub in ast.walk(d):
                            sdd = _dotted(sub) if isinstance(
                                sub, (ast.Name, ast.Attribute)) else None
                            if sdd and sdd.split(".")[-1] in _JIT_WRAPPERS:
                                fr.jit_root = True
                    if dd.split(".")[-1] in _JIT_WRAPPERS:
                        fr.jit_root = True
                collect_funcs(node.body, f"{qual}.<locals>.")
            elif isinstance(node, (ast.ClassDef,)):
                collect_funcs(node.body, f"{prefix}{node.name}.")
            elif hasattr(node, "body") and isinstance(node.body, list):
                # Generic statement containers (if/try/with/for/while
                # and their else/finally/except blocks).
                collect_funcs(node.body, prefix)
                for attr in ("orelse", "finalbody", "handlers"):
                    for sub in getattr(node, attr, []) or []:
                        if hasattr(sub, "body"):
                            collect_funcs(sub.body, prefix)
                        else:
                            collect_funcs([sub], prefix)

    collect_funcs(tree.body, "")

    # Functions passed by name to jit wrappers are roots; assignments of
    # jax.jit(...) results record donation signatures.
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn_dd = _dotted(node.func) or ""
        leaf = fn_dd.split(".")[-1]
        if leaf in _JIT_WRAPPERS:
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in rec.funcs:
                    rec.funcs[arg.id].jit_root = True
        if leaf == "jit":
            donate: Tuple[int, ...] = ()
            for kw in node.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    try:
                        vals = ast.literal_eval(kw.value)
                    except ValueError:
                        vals = None
                    if isinstance(vals, int):
                        donate = (vals,)
                    elif isinstance(vals, (tuple, list)):
                        donate = tuple(v for v in vals if isinstance(v, int))
            if donate:
                parent_assigns = _assign_targets_of_call(tree, node)
                for tgt in parent_assigns:
                    rec.donators[tgt.split(".")[-1]] = donate

    # Call edges (simple names only).
    for fr in set(rec.funcs.values()):
        for sub in ast.walk(fr.node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                fr.calls.add(sub.func.id)
            elif isinstance(sub, ast.Call):
                dd = _dotted(sub.func)
                if dd:
                    fr.calls.add(dd.split(".")[-1])
    return rec


def _assign_targets_of_call(tree: ast.Module, call: ast.Call) -> List[str]:
    """Dotted names an expression is assigned to (scan for Assign whose
    value subtree contains `call`)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                sub is call for sub in ast.walk(node.value)):
            for t in node.targets:
                dd = _dotted(t)
                if dd:
                    out.append(dd)
    return out


def _propagate_reachability(mods: Dict[str, _ModRec]) -> None:
    # Import links resolve by module NAME; records are keyed by path
    # (stems can collide across directories — first record wins links).
    by_name: Dict[str, _ModRec] = {}
    owner: Dict[_FuncRec, _ModRec] = {}
    work: List[_FuncRec] = []
    for m in mods.values():
        by_name.setdefault(m.name, m)
        for fr in set(m.funcs.values()):
            owner[fr] = m
            if fr.jit_root and not fr.reachable:
                fr.reachable = True
                work.append(fr)
    while work:
        fr = work.pop()
        m = owner[fr]
        for callee in fr.calls:
            targets: List[_FuncRec] = []
            if callee in m.funcs:
                targets.append(m.funcs[callee])
            elif callee in m.import_map:
                im, iname = m.import_map[callee]
                if im in by_name and iname in by_name[im].funcs:
                    targets.append(by_name[im].funcs[iname])
            for t in targets:
                if not t.reachable:
                    t.reachable = True
                    work.append(t)


# -----------------------------------------------------------------------------
# Devness inference + per-function rule visitors
# -----------------------------------------------------------------------------


class _FuncLinter:
    def __init__(self, mod: _ModRec, fr: _FuncRec, jit: bool):
        self.mod = mod
        self.fr = fr
        self.jit = jit  # jit-reachable scope
        self.device: Set[str] = set()
        self.narrow: Set[str] = set()
        self.findings = mod.findings
        if jit:
            self._seed_params()

    def _emit(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(Finding(
            self.mod.path, getattr(node, "lineno", 0), rule, msg))

    def _seed_params(self) -> None:
        args = self.fr.node.args
        all_args = (args.posonlyargs + args.args + args.kwonlyargs
                    + ([args.vararg] if args.vararg else [])
                    + ([args.kwarg] if args.kwarg else []))
        for a in all_args:
            if a.arg in _STATIC_PARAM_NAMES:
                continue
            if a.annotation is not None:
                ann = ast.unparse(a.annotation)
                leaf = ann.split(".")[-1].split("[")[0]
                if leaf in _STATIC_ANNOTATIONS or leaf.endswith("Config"):
                    continue
            self.device.add(a.arg)

    # -- devness --------------------------------------------------------------

    def is_device(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.device
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.is_device(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_device(node.value) or self.is_device(node.slice)
        if isinstance(node, (ast.BinOp,)):
            return self.is_device(node.left) or self.is_device(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_device(node.operand)
        if isinstance(node, ast.Compare):
            return self.is_device(node.left) or any(
                self.is_device(c) for c in node.comparators)
        if isinstance(node, ast.BoolOp):
            return any(self.is_device(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return self.is_device(node.body) or self.is_device(node.orelse)
        if isinstance(node, ast.Starred):
            return self.is_device(node.value)
        if isinstance(node, ast.Call):
            dd = _dotted(node.func) or ""
            root = dd.split(".")[0]
            if root in ("jnp", "lax") or dd.startswith((
                    "jax.numpy.", "jax.lax.", "jax.nn.", "jax.tree")):
                return True
            if dd in ("jax.device_put",):
                return True
            # Method chains on device values (.astype/.at[..].set/...)
            # and calls forwarding device arguments stay device.
            if isinstance(node.func, ast.Attribute) and self.is_device(
                    node.func.value):
                return node.func.attr not in _SYNC_METHODS
            if root in ("np", "numpy", "bool", "int", "float", "len"):
                return False
            return any(self.is_device(a) for a in node.args) or any(
                self.is_device(k.value) for k in node.keywords)
        return False

    def _is_narrow(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.narrow
        if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "astype":
            if node.args:
                cast = ast.unparse(node.args[0]).split(".")[-1]
                if cast in _NARROW_CASTS:
                    return True
        if isinstance(node, ast.Call):
            dd = _dotted(node.func) or ""
            if dd.split(".")[-1] == "narrow_state":
                return True
        return False

    # -- the pass -------------------------------------------------------------

    def run(self) -> None:
        node = self.fr.node
        if self.jit:
            # Two passes: devness propagates through assignments that
            # lexically precede their uses on pass 1; pass 2 catches
            # the rest (closures over later defs are rare in jit code).
            for _ in range(2):
                for stmt in ast.walk(node):
                    if isinstance(stmt, ast.Assign):
                        dev = self.is_device(stmt.value)
                        nar = self._is_narrow(stmt.value)
                        for t in stmt.targets:
                            for n in ast.walk(t):
                                if isinstance(n, ast.Name):
                                    if dev:
                                        self.device.add(n.id)
                                    if nar:
                                        self.narrow.add(n.id)
                                    elif n.id in self.narrow:
                                        self.narrow.discard(n.id)
                    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                        tgt = stmt.target
                        if isinstance(tgt, ast.Name) and stmt.value is not None \
                                and self.is_device(stmt.value):
                            self.device.add(tgt.id)
            self._check_jit_rules(node)
            self._check_widen_discipline(node)
        else:
            self._check_host_rules(node)
        self._check_donated_use(node)

    def _check_jit_rules(self, fn_node: ast.AST) -> None:
        own_nested = {
            n for n in ast.walk(fn_node)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn_node
        }

        def in_nested(node):
            return any(node in ast.walk(f) for f in own_nested)

        for node in ast.walk(fn_node):
            # Nested defs are linted as their own (reachable) functions.
            if node is not fn_node and in_nested(node):
                continue
            if isinstance(node, (ast.If, ast.While)) and self.is_device(
                    node.test):
                self._emit(node, "tracer-branch",
                           f"`{ast.unparse(node.test)[:60]}` is a device "
                           "value; use jnp.where/lax.cond")
            elif isinstance(node, ast.IfExp) and self.is_device(node.test):
                self._emit(node, "tracer-branch",
                           "ternary on a device value; use jnp.where")
            elif isinstance(node, ast.Assert) and self.is_device(node.test):
                self._emit(node, "tracer-branch",
                           "assert on a device value (concretizes); use "
                           "checkify or a static shape check")
            elif isinstance(node, ast.BoolOp) and self.is_device(node):
                self._emit(node, "tracer-branch",
                           "and/or on device values calls bool(); "
                           "use & / | / jnp.logical_*")
            elif isinstance(node, ast.For) and self.is_device(node.iter):
                self._emit(node, "tracer-branch",
                           "iteration over a device value; use lax.scan "
                           "or index with a static range")
            elif isinstance(node, ast.comprehension) and self.is_device(
                    node.iter):
                self._emit(node, "tracer-branch",
                           "comprehension over a device value")
            elif isinstance(node, ast.Call):
                self._check_jit_call(node)
            elif isinstance(node, ast.BinOp):
                for side in (node.left, node.right):
                    if self._is_narrow(side):
                        self._emit(
                            node, "narrow-lane-arith",
                            "arithmetic on an int8/int16-narrowed value; "
                            "widen to i32 first (state.widen_state / "
                            ".astype(I32))")
                        break

    def _check_jit_call(self, node: ast.Call) -> None:
        dd = _dotted(node.func) or ""
        leaf = dd.split(".")[-1]
        if dd.startswith(_IMPURE_PREFIXES) or dd in _IMPURE_EXACT:
            self._emit(node, "impure-jit",
                       f"`{dd}(...)` inside jit-reachable code")
            return
        args_dev = any(self.is_device(a) for a in node.args)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SYNC_METHODS and \
                self.is_device(node.func.value):
            self._emit(node, "host-sync-in-jit",
                       f".{node.func.attr}() on a device value inside "
                       "jit-reachable code")
        elif dd in ("bool", "int", "float") and args_dev:
            self._emit(node, "host-sync-in-jit",
                       f"{dd}() on a device value inside jit-reachable "
                       "code (concretizes the tracer)")
        elif (dd.split(".")[0] in ("np", "numpy")
              and not dd.startswith(("np.random", "numpy.random"))
              and args_dev):
            self._emit(node, "host-sync-in-jit",
                       f"`{dd}(...)` on a device value inside "
                       "jit-reachable code (numpy pulls the tracer to "
                       "host); use jnp")
        elif leaf == "device_get" and args_dev:
            self._emit(node, "host-sync-in-jit",
                       "jax.device_get inside jit-reachable code")

    def _check_widen_discipline(self, fn_node: ast.AST) -> None:
        """In a jit ROOT with a BatchedState-annotated param, narrow
        state fields must not be touched before widen_state runs (the
        widen-at-entry contract that keeps cfg.narrow_lanes safe)."""
        if not self.fr.jit_root:
            return
        args = self.fr.node.args
        state_params = {
            a.arg for a in args.posonlyargs + args.args + args.kwonlyargs
            if a.annotation is not None
            and ast.unparse(a.annotation).split(".")[-1] == "BatchedState"
        }
        if not state_params:
            return
        widened = False
        for stmt in getattr(fn_node, "body", []):
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    dd = _dotted(sub.func) or ""
                    if dd.split(".")[-1] == "widen_state":
                        widened = True
                if (not widened and isinstance(sub, ast.Attribute)
                        and sub.attr in NARROW_FIELDS
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id in state_params):
                    self._emit(
                        sub, "narrow-lane-arith",
                        f"narrow lane `.{sub.attr}` read in a jit root "
                        "before widen_state (storage may be int8/int16 "
                        "under cfg.narrow_lanes)")
            if widened:
                break

    def _check_host_rules(self, fn_node: ast.AST) -> None:
        if not self.mod.imports_jax:
            return
        loops = [n for n in ast.walk(fn_node)
                 if isinstance(n, (ast.For, ast.While))]
        seen: Set[int] = set()
        for loop in loops:
            for node in ast.walk(loop):
                if id(node) in seen or not isinstance(node, ast.Call):
                    continue
                dd = _dotted(node.func) or ""
                hit = (
                    dd in ("np.asarray", "np.array", "numpy.asarray",
                           "numpy.array", "jax.device_get",
                           "jax.block_until_ready")
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("item", "block_until_ready"))
                )
                if hit:
                    seen.add(id(node))
                    self._emit(
                        node, "sync-in-loop",
                        f"`{(dd or node.func.attr)}` inside a host loop; "
                        "hoist to one bulk sync per round")

    def _check_donated_use(self, fn_node: ast.AST) -> None:
        donators = self.mod.donators
        if not donators:
            return
        stmts = list(ast.walk(fn_node))
        for node in stmts:
            if not isinstance(node, ast.Call):
                continue
            dd = _dotted(node.func) or ""
            leaf = dd.split(".")[-1]
            if leaf not in donators:
                continue
            for pos in donators[leaf]:
                if pos >= len(node.args):
                    continue
                arg_dd = _dotted(node.args[pos])
                if not arg_dd:
                    continue
                self._flag_use_after(fn_node, node, arg_dd)

    def _flag_use_after(self, fn_node: ast.AST, call: ast.Call,
                        name: str) -> None:
        call_line = call.lineno
        rebound_line = None
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign) and node.lineno >= call_line:
                for t in node.targets:
                    for sub in ast.walk(t):
                        dd = _dotted(sub) if isinstance(
                            sub, (ast.Name, ast.Attribute)) else None
                        if dd == name:
                            rebound_line = min(
                                rebound_line or node.lineno, node.lineno)
        for node in ast.walk(fn_node):
            dd = _dotted(node) if isinstance(
                node, (ast.Name, ast.Attribute)) else None
            if dd != name or not isinstance(
                    getattr(node, "ctx", None), ast.Load):
                continue
            line = node.lineno
            if line <= call_line:
                continue
            if rebound_line is not None and line >= rebound_line:
                continue
            self._emit(node, "donated-use",
                       f"`{name}` read after being donated at line "
                       f"{call_line} (buffer freed by XLA)")
            return


def _check_dict_order_static(mod: _ModRec) -> None:
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        dd = _dotted(node.func) or ""
        if dd.split(".")[-1] != "jit":
            continue
        for arg in list(node.args) + [k.value for k in node.keywords]:
            for sub in ast.walk(arg):
                bad = None
                if isinstance(sub, (ast.Set, ast.SetComp)):
                    bad = "set literal/comprehension"
                elif isinstance(sub, ast.Call):
                    sdd = _dotted(sub.func) or ""
                    if sdd == "set":
                        bad = "set(...)"
                    elif isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr in ("keys", "values", "items"):
                        bad = f".{sub.func.attr}()"
                if bad and not _sorted_wrapped(arg, sub):
                    mod.findings.append(Finding(
                        mod.path, sub.lineno, "dict-order-static",
                        f"{bad} feeding jax.jit — iteration order is "
                        "not canonical; wrap in sorted(...) or use a "
                        "tuple literal"))


def _sorted_wrapped(root: ast.AST, target: ast.AST) -> bool:
    for node in ast.walk(root):
        if isinstance(node, ast.Call):
            if (_dotted(node.func) or "") == "sorted":
                if any(sub is target for sub in ast.walk(node)):
                    return True
    return False


# -----------------------------------------------------------------------------
# Entry points
# -----------------------------------------------------------------------------


def _apply_waivers(mod: _ModRec) -> None:
    for f in mod.findings:
        if f.rule.startswith("waiver-"):
            continue
        for w in mod.waivers:
            if w.line == f.line and f.rule in w.rules:
                f.waived = True
                f.reason = w.reason
                w.used = True
                break
    for w in mod.waivers:
        if not w.used:
            mod.findings.append(Finding(
                mod.path, w.pragma_line, "waiver-unused",
                f"waive({', '.join(sorted(w.rules))}) suppresses "
                "nothing on its target line"))


def lint_modules(mods: Dict[str, _ModRec]) -> List[Finding]:
    _propagate_reachability(mods)
    for mod in mods.values():
        for fr in set(mod.funcs.values()):
            _FuncLinter(mod, fr, jit=fr.reachable).run()
        _check_dict_order_static(mod)
        _apply_waivers(mod)
    out: List[Finding] = []
    for mod in mods.values():
        out.extend(mod.findings)
    out.sort(key=lambda f: (f.path, f.line, f.rule))
    return out


def lint_source(source: str, path: str = "<string>",
                extra_modules: Optional[Dict[str, str]] = None
                ) -> List[Finding]:
    """Lint one source string (tests); `extra_modules` maps module name
    -> source for cross-file reachability."""
    mods = {}
    rec = _collect_module(path, source)
    mods[rec.path] = rec
    for name, src in (extra_modules or {}).items():
        extra = _collect_module(f"<{name}>", src)
        extra.name = name
        mods[extra.path] = extra
    return [f for f in lint_modules(mods) if f.path == path]


def lint_file(path: str) -> List[Finding]:
    return lint_paths([path])


def collect_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for base, _dirs, names in os.walk(p):
                files.extend(
                    os.path.join(base, n) for n in sorted(names)
                    if n.endswith(".py"))
        elif p.endswith(".py") and os.path.isfile(p):
            files.append(p)
        else:
            # A typo'd/renamed path must FAIL the gate, not lint zero
            # files and exit green — a vacuous gate is worse than a
            # broken one.
            raise FileNotFoundError(
                f"jitlint: not a directory or existing .py file: {p!r}")
    return files


def lint_paths(paths: Sequence[str]) -> List[Finding]:
    mods: Dict[str, _ModRec] = {}
    for path in collect_files(paths):
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        try:
            rec = _collect_module(path, source)
        except SyntaxError as e:
            rec = _ModRec(os.path.basename(path), path,
                          ast.Module(body=[], type_ignores=[]), [])
            rec.findings.append(Finding(
                path, e.lineno or 0, "syntax-error",
                f"syntax error: {e.msg}"))
        # Keyed by PATH (stems collide across dirs, e.g. tools/x.py vs
        # pkg/x.py); lint_modules builds its own name index for import
        # resolution.
        mods[rec.path] = rec
    return lint_modules(mods)
