"""Static analysis + runtime sentinels for the batched hot path (ISSUE 7).

Three planes, one goal — keep the jitted round pure, the dtypes
disciplined, and the compile budget bounded, mechanically:

* ``jitlint``   — AST lint passes over jit-reachable code (pure stdlib,
  no jax import): tracer control flow, host syncs inside jit, narrow-lane
  arithmetic before the mandated widen-at-entry, use-after-donation,
  banned impurities, dict-order-dependent static args, and per-item
  device syncs inside host loops. CLI: ``tools/jitlint.py``.
* ``sentinels`` — runtime guards: ``jax.transfer_guard("disallow")``
  around the warm round dispatch (ETCD_TPU_TRANSFER_GUARD=disallow) and
  a recompile sentinel counting distinct round-step programs per session
  against a declared shape budget (tests/batched/conftest.py).
* ``lockorder`` — an instrumented ``threading.Lock`` recorder that
  builds the cross-thread acquisition graph (drain/pump/sender lanes)
  and fails on cycles.

Everything here is import-light: ``jitlint``/``lockorder`` never import
jax; ``sentinels`` imports it lazily so the lint CLI runs anywhere.
"""

from .jitlint import (  # noqa: F401
    Finding,
    RULES,
    lint_file,
    lint_paths,
    lint_source,
)
from .lockorder import LockOrderRecorder, LockOrderViolation  # noqa: F401
from .sentinels import (  # noqa: F401
    CompileBudget,
    RecompileBudgetExceeded,
    distinct_shapes,
    note_compile_key,
    reset_compile_tracking,
    round_guard,
    transfer_guard_mode,
)
