"""Runtime sentinels for the batched hot path: transfer guard +
recompile budget.

Transfer guard
--------------
``round_guard()`` returns ``jax.transfer_guard(mode)`` when
``ETCD_TPU_TRANSFER_GUARD`` is set (tests/batched/conftest.py and the
benches set ``disallow``), else a no-op context. The engine/rawnode
wrap exactly the *warm* device dispatch of the round program in it, so
any implicit transfer sneaking into the steady-state loop — an eager
scalar op, a stray ``jnp.zeros``, a concretized tracer — is a hard
error instead of a silent per-round sync (the BENCH r4 675M/s artifact
class). Two deliberate scope limits, measured on this jax build:

* compilation itself transfers host constants, so a cold program must
  be dispatched once unguarded — callers use ``warm_guard(key)`` which
  guards every call after the first per program/static-arg key;
* on CPU, array transfers are zero-copy aliases and do NOT trip the
  guard (scalar transfers do) — the AST side (jitlint's sync-in-loop)
  covers the class the runtime guard can't see on CPU.

Recompile sentinel
------------------
``step._step_round_jit`` notes one key per distinct round-step config
via ``note_compile_key``; ``distinct_shapes("round_step")`` is then the
number of round programs built this session. tests/batched/conftest.py
declares the tier-1 shape budget and fails the session when new configs
exceed it — the ~15s tier-1 margin dies by one unnoticed compile at a
time. ``CompileBudget`` additionally watches live jit wrappers via
``_cache_size()`` for genuine cache-miss counting (new static args /
new input shapes on the same wrapper).
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Optional, Set

_TRANSFER_GUARD_ENV = "ETCD_TPU_TRANSFER_GUARD"

_lock = threading.Lock()
_compile_keys: Dict[str, Set[str]] = {}
_warm_keys: Set[str] = set()


def transfer_guard_mode() -> str:
    """'' (off) or a jax transfer-guard level ('disallow', 'log', ...)."""
    return os.environ.get(_TRANSFER_GUARD_ENV, "")


def round_guard():
    """Context manager for the round dispatch: jax.transfer_guard(mode)
    when enabled, no-op otherwise. Only wrap already-compiled dispatch
    with all-device args — compilation transfers host constants."""
    mode = transfer_guard_mode()
    if not mode:
        return contextlib.nullcontext()
    import jax

    return jax.transfer_guard(mode)


@contextlib.contextmanager
def warm_guard(key: str):
    """round_guard() for every call after the first with this `key`.

    The first dispatch of a (program, static-args) pair compiles, and
    compilation legitimately transfers host constants; keying warmth by
    (program, statics) keeps recompiles unguarded too, while the
    steady-state loop runs fully fenced."""
    mode = transfer_guard_mode()
    if not mode:
        yield
        return
    with _lock:
        warm = key in _warm_keys
    if warm:
        import jax

        with jax.transfer_guard(mode):
            yield
    else:
        yield
        with _lock:
            _warm_keys.add(key)


# -----------------------------------------------------------------------------
# Recompile sentinel
# -----------------------------------------------------------------------------


class RecompileBudgetExceeded(RuntimeError):
    pass


def note_compile_key(program: str, key: str) -> None:
    """Record that `program` built a trace for shape/config `key`
    (called from the build path, e.g. step._step_round_jit — once per
    distinct config thanks to its lru_cache)."""
    with _lock:
        _compile_keys.setdefault(program, set()).add(key)


def distinct_shapes(program: Optional[str] = None) -> int:
    with _lock:
        if program is not None:
            return len(_compile_keys.get(program, ()))
        return sum(len(v) for v in _compile_keys.values())


def compile_keys(program: str) -> Set[str]:
    with _lock:
        return set(_compile_keys.get(program, ()))


def reset_compile_tracking() -> None:
    with _lock:
        _compile_keys.clear()
        _warm_keys.clear()


def jit_cache_size(jitted) -> int:
    """Entries in a jax.jit wrapper's trace cache (one per distinct
    (shapes, dtypes, static args) signature); -1 when this jax build
    doesn't expose it."""
    try:
        return int(jitted._cache_size())
    except AttributeError:
        return -1


class CompileBudget:
    """Counts jit cache misses across tracked wrappers against a hard
    limit.

        budget = CompileBudget(limit=1)
        budget.track("closed_loop", eng._closed_loop)
        ...drive the engine...
        budget.check()   # raises RecompileBudgetExceeded when over

    A miss is a new entry in a tracked wrapper's trace cache: a new
    static-arg value (e.g. a new `rounds`) or a new input shape. The
    declared tier-1 budget lives in tests/batched/conftest.py.
    """

    def __init__(self, limit: int):
        self.limit = int(limit)
        self._baseline: Dict[str, int] = {}
        self._fns: Dict[str, object] = {}

    def track(self, name: str, jitted) -> "CompileBudget":
        self._fns[name] = jitted
        self._baseline[name] = max(jit_cache_size(jitted), 0)
        return self

    def misses(self) -> int:
        total = 0
        for name, fn in self._fns.items():
            size = jit_cache_size(fn)
            if size >= 0:
                total += max(size - self._baseline[name], 0)
        return total

    def report(self) -> Dict[str, int]:
        return {
            name: max(jit_cache_size(fn), 0) - self._baseline[name]
            for name, fn in self._fns.items()
        }

    def check(self) -> int:
        m = self.misses()
        if m > self.limit:
            raise RecompileBudgetExceeded(
                f"jit cache misses {m} > declared budget {self.limit} "
                f"(per-wrapper: {self.report()}); a new static arg or "
                "input shape recompiled the hot program — make it "
                "conscious (bump the budget) or make it go away")
        return m
