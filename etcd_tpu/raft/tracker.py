"""Leader-side replication tracking (ref: raft/tracker/).

Per-follower state (Match/Next/State/ProbeSent/RecentActive and the
inflight window) is exactly what becomes the ``[G, R]`` tensors of the
batched engine: states are small ints, the inflight ring degenerates to a
(count, last-index) pair per replica, and Committed()/TallyVotes() are the
replica-axis reductions in ``etcd_tpu.batched``.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, Dict, List, Optional, Set, Tuple

from .quorum import JointConfig, MajorityConfig, VoteResult


class ProgressStateType(IntEnum):
    """ref: raft/tracker/state.go."""

    StateProbe = 0
    StateReplicate = 1
    StateSnapshot = 2

    def __str__(self) -> str:
        return self.name


StateProbe = ProgressStateType.StateProbe
StateReplicate = ProgressStateType.StateReplicate
StateSnapshot = ProgressStateType.StateSnapshot


class Inflights:
    """Sliding window bounding un-acked MsgApp per follower
    (ref: raft/tracker/inflights.go).

    Entries are added in increasing index order and freed by "≤ index", so
    a deque suffices; on the TPU this is just a count plus last-added
    index per ``[G, R]`` slot.
    """

    def __init__(self, size: int):
        self.size = size
        self.buffer: List[int] = []

    def clone(self) -> "Inflights":
        c = Inflights(self.size)
        c.buffer = list(self.buffer)
        return c

    def add(self, inflight: int) -> None:
        if self.full():
            raise RuntimeError("cannot add into a Full inflights")
        self.buffer.append(inflight)

    def free_le(self, to: int) -> None:
        i = 0
        while i < len(self.buffer) and self.buffer[i] <= to:
            i += 1
        del self.buffer[:i]

    def free_first_one(self) -> None:
        if self.buffer:
            del self.buffer[0]

    def full(self) -> bool:
        return len(self.buffer) == self.size

    def count(self) -> int:
        return len(self.buffer)

    def reset(self) -> None:
        self.buffer.clear()


class Progress:
    """A follower's replication progress in the leader's view
    (ref: raft/tracker/progress.go:30-80)."""

    def __init__(
        self,
        match: int = 0,
        next: int = 0,
        inflights: Optional[Inflights] = None,
        is_learner: bool = False,
        recent_active: bool = False,
    ):
        self.match = match
        self.next = next
        self.state: ProgressStateType = StateProbe
        self.pending_snapshot = 0
        self.recent_active = recent_active
        self.probe_sent = False
        self.inflights = inflights if inflights is not None else Inflights(0)
        self.is_learner = is_learner

    def reset_state(self, state: ProgressStateType) -> None:
        self.probe_sent = False
        self.pending_snapshot = 0
        self.state = state
        self.inflights.reset()

    def probe_acked(self) -> None:
        self.probe_sent = False

    def become_probe(self) -> None:
        # Probing resumes after the pending snapshot, if one was sent.
        if self.state == StateSnapshot:
            pending = self.pending_snapshot
            self.reset_state(StateProbe)
            self.next = max(self.match + 1, pending + 1)
        else:
            self.reset_state(StateProbe)
            self.next = self.match + 1

    def become_replicate(self) -> None:
        self.reset_state(StateReplicate)
        self.next = self.match + 1

    def become_snapshot(self, snapshoti: int) -> None:
        self.reset_state(StateSnapshot)
        self.pending_snapshot = snapshoti

    def maybe_update(self, n: int) -> bool:
        """Ack up to index n; False if the ack is stale
        (ref: progress.go:144-153)."""
        updated = False
        if self.match < n:
            self.match = n
            updated = True
            self.probe_acked()
        self.next = max(self.next, n + 1)
        return updated

    def optimistic_update(self, n: int) -> None:
        self.next = n + 1

    def maybe_decr_to(self, rejected: int, match_hint: int) -> bool:
        """Handle a MsgApp rejection (ref: progress.go:170-193)."""
        if self.state == StateReplicate:
            if rejected <= self.match:
                return False
            self.next = self.match + 1
            return True
        if self.next - 1 != rejected:
            return False
        self.next = max(min(rejected, match_hint + 1), 1)
        self.probe_sent = False
        return True

    def is_paused(self) -> bool:
        if self.state == StateProbe:
            return self.probe_sent
        if self.state == StateReplicate:
            return self.inflights.full()
        if self.state == StateSnapshot:
            return True
        raise RuntimeError("unexpected state")

    def __str__(self) -> str:
        parts = [f"{self.state} match={self.match} next={self.next}"]
        if self.is_learner:
            parts.append(" learner")
        if self.is_paused():
            parts.append(" paused")
        if self.pending_snapshot > 0:
            parts.append(f" pendingSnap={self.pending_snapshot}")
        if not self.recent_active:
            parts.append(" inactive")
        n = self.inflights.count()
        if n > 0:
            parts.append(f" inflight={n}")
            if self.inflights.full():
                parts.append("[full]")
        return "".join(parts)

    def copy(self) -> "Progress":
        p = Progress(self.match, self.next, self.inflights.clone(), self.is_learner,
                     self.recent_active)
        p.state = self.state
        p.pending_snapshot = self.pending_snapshot
        p.probe_sent = self.probe_sent
        return p


def progress_map_str(progress: Dict[int, Progress]) -> str:
    return "".join(f"{vid}: {progress[vid]}\n" for vid in sorted(progress))


class TrackerConfig:
    """Active configuration (ref: raft/tracker/tracker.go:27-78).

    Empty learner sets are represented as None-equivalent empty sets; the
    printed form only includes non-empty segments, matching the Go nil-map
    conventions.
    """

    def __init__(self):
        self.voters = JointConfig()
        self.auto_leave = False
        self.learners: Set[int] = set()
        self.learners_next: Set[int] = set()

    def __str__(self) -> str:
        buf = f"voters={self.voters}"
        if self.learners:
            buf += f" learners={MajorityConfig(self.learners)}"
        if self.learners_next:
            buf += f" learners_next={MajorityConfig(self.learners_next)}"
        if self.auto_leave:
            buf += " autoleave"
        return buf

    def clone(self) -> "TrackerConfig":
        c = TrackerConfig()
        c.voters = self.voters.clone()
        c.auto_leave = self.auto_leave
        c.learners = set(self.learners)
        c.learners_next = set(self.learners_next)
        return c


class ProgressTracker:
    """Config + per-peer Progress + vote tally
    (ref: raft/tracker/tracker.go:117-125)."""

    def __init__(self, max_inflight: int):
        self.max_inflight = max_inflight
        self.config = TrackerConfig()
        self.progress: Dict[int, Progress] = {}
        self.votes: Dict[int, bool] = {}

    # -- config views ---------------------------------------------------------

    @property
    def voters(self) -> JointConfig:
        return self.config.voters

    @property
    def learners(self) -> Set[int]:
        return self.config.learners

    @property
    def learners_next(self) -> Set[int]:
        return self.config.learners_next

    def conf_state(self):
        from .types import ConfState

        return ConfState(
            voters=self.voters.incoming.slice(),
            voters_outgoing=self.voters.outgoing.slice(),
            learners=MajorityConfig(self.learners).slice(),
            learners_next=MajorityConfig(self.learners_next).slice(),
            auto_leave=self.config.auto_leave,
        )

    def is_singleton(self) -> bool:
        return len(self.voters.incoming) == 1 and len(self.voters.outgoing) == 0

    # -- reductions (the batched-engine kernels) ------------------------------

    def committed(self) -> int:
        """Quorum-acked commit index (ref: tracker.go:177-179)."""

        def acked(vid: int) -> Optional[int]:
            pr = self.progress.get(vid)
            return pr.match if pr is not None else None

        return self.voters.committed_index(acked)

    def visit(self, f: Callable[[int, Progress], None]) -> None:
        """Apply f to all progresses in sorted ID order (ref: tracker.go:191)."""
        for vid in sorted(self.progress):
            f(vid, self.progress[vid])

    def quorum_active(self) -> bool:
        """ref: tracker.go:215-225."""
        votes = {
            vid: pr.recent_active
            for vid, pr in self.progress.items()
            if not pr.is_learner
        }
        return self.voters.vote_result(votes) == VoteResult.VoteWon

    def voter_nodes(self) -> List[int]:
        return sorted(self.voters.ids())

    def learner_nodes(self) -> List[int]:
        return sorted(self.learners)

    def reset_votes(self) -> None:
        self.votes = {}

    def record_vote(self, vid: int, v: bool) -> None:
        self.votes.setdefault(vid, v)

    def tally_votes(self) -> Tuple[int, int, VoteResult]:
        """(granted, rejected, result) — ref: tracker.go:267-288."""
        granted = rejected = 0
        for vid, pr in self.progress.items():
            if pr.is_learner or vid not in self.votes:
                continue
            if self.votes[vid]:
                granted += 1
            else:
                rejected += 1
        return granted, rejected, self.voters.vote_result(self.votes)
