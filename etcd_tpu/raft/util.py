"""Human-readable descriptions of raft data structures
(ref: raft/util.go). Output is byte-compatible with the reference — these
renderings are what the interaction-trace parity tests compare.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .rawnode import Ready
from .read_only import ReadState
from .types import (
    ConfChange,
    ConfChangeV2,
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    Snapshot,
    conf_changes_to_string,
    is_empty_hard_state,
    is_empty_snap,
)
from .raft import SoftState

EntryFormatter = Callable[[bytes], str]

_GO_ESCAPES = {
    0x07: "\\a",
    0x08: "\\b",
    0x0C: "\\f",
    0x0A: "\\n",
    0x0D: "\\r",
    0x09: "\\t",
    0x0B: "\\v",
    0x5C: "\\\\",
    0x22: '\\"',
}


def go_quote(data: bytes) -> str:
    """Equivalent of Go's %q for a byte slice."""
    out = ['"']
    for b in data:
        if b in _GO_ESCAPES:
            out.append(_GO_ESCAPES[b])
        elif 0x20 <= b < 0x7F:
            out.append(chr(b))
        else:
            out.append(f"\\x{b:02x}")
    out.append('"')
    return "".join(out)


def default_entry_formatter(data: bytes) -> str:
    return go_quote(data)


def describe_hard_state(hs: HardState) -> str:
    buf = f"Term:{hs.term}"
    if hs.vote != 0:
        buf += f" Vote:{hs.vote}"
    buf += f" Commit:{hs.commit}"
    return buf


def describe_soft_state(ss: SoftState) -> str:
    return f"Lead:{ss.lead} State:{ss.raft_state}"


def describe_conf_state(state: ConfState) -> str:
    def sl(v: List[int]) -> str:
        return "[" + " ".join(str(x) for x in v) + "]"

    return (
        f"Voters:{sl(state.voters)} VotersOutgoing:{sl(state.voters_outgoing)} "
        f"Learners:{sl(state.learners)} LearnersNext:{sl(state.learners_next)} "
        f"AutoLeave:{'true' if state.auto_leave else 'false'}"
    )


def describe_snapshot(snap: Snapshot) -> str:
    m = snap.metadata
    return f"Index:{m.index} Term:{m.term} ConfState:{describe_conf_state(m.conf_state)}"


def describe_read_state(rs: ReadState) -> str:
    return "{%d %s}" % (rs.index, "[" + " ".join(str(b) for b in rs.request_ctx) + "]")


def describe_entry(e: Entry, f: Optional[EntryFormatter]) -> str:
    """ref: raft/util.go:166-199."""
    if f is None:
        f = go_quote

    if e.type == EntryType.EntryNormal:
        formatted = f(e.data)
    elif e.type == EntryType.EntryConfChange:
        formatted = conf_changes_to_string(ConfChange.unmarshal(e.data).as_v2().changes)
    elif e.type == EntryType.EntryConfChangeV2:
        formatted = conf_changes_to_string(ConfChangeV2.unmarshal(e.data).changes)
    else:
        formatted = ""
    if formatted:
        formatted = " " + formatted
    return f"{e.term}/{e.index} {e.type}{formatted}"


def describe_entries(ents: List[Entry], f: Optional[EntryFormatter]) -> str:
    return "".join(describe_entry(e, f) + "\n" for e in ents)


def describe_message(m: Message, f: Optional[EntryFormatter]) -> str:
    """ref: raft/util.go:137-163."""
    buf = [
        "%x->%x %s Term:%d Log:%d/%d"
        % (m.from_, m.to, m.type, m.term, m.log_term, m.index)
    ]
    if m.reject:
        buf.append(f" Rejected (Hint: {m.reject_hint})")
    if m.commit != 0:
        buf.append(f" Commit:{m.commit}")
    if m.entries:
        buf.append(" Entries:[")
        buf.append(", ".join(describe_entry(e, f) for e in m.entries))
        buf.append("]")
    if not is_empty_snap(m.snapshot):
        buf.append(f" Snapshot: {describe_snapshot(m.snapshot)}")
    return "".join(buf)


def describe_ready(rd: Ready, f: Optional[EntryFormatter]) -> str:
    """ref: raft/util.go:90-124."""
    buf: List[str] = []
    if rd.soft_state is not None:
        buf.append(describe_soft_state(rd.soft_state) + "\n")
    if not is_empty_hard_state(rd.hard_state):
        buf.append(f"HardState {describe_hard_state(rd.hard_state)}\n")
    if rd.read_states:
        states = " ".join(describe_read_state(rs) for rs in rd.read_states)
        buf.append(f"ReadStates [{states}]\n")
    if rd.entries:
        buf.append("Entries:\n")
        buf.append(describe_entries(rd.entries, f))
    if not is_empty_snap(rd.snapshot):
        buf.append(f"Snapshot {describe_snapshot(rd.snapshot)}\n")
    if rd.committed_entries:
        buf.append("CommittedEntries:\n")
        buf.append(describe_entries(rd.committed_entries, f))
    if rd.messages:
        buf.append("Messages:\n")
        for msg in rd.messages:
            buf.append(describe_message(msg, f) + "\n")
    if buf:
        return "Ready MustSync=%s:\n%s" % (
            "true" if rd.must_sync else "false",
            "".join(buf),
        )
    return "<empty Ready>"
