"""The raft log: stable Storage + unstable tail + commit/apply cursors
(ref: raft/log.go, raft/log_unstable.go).

In the batched engine this whole structure collapses to a ``[G, W]`` ring
of (term) values plus per-group (first, stable, last, committed, applied)
watermarks; payload bytes stay in a host arena. ``maybe_append``'s
term-match and ``find_conflict_by_term``'s scan are the vectorized
kernels; the versions here are the scalar oracles.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .errors import CompactedError, UnavailableError
from .logger import Logger, get_logger
from .storage import Storage, limit_size
from .types import Entry, Snapshot

NO_LIMIT = (1 << 64) - 1


class Unstable:
    """Entries/snapshot not yet persisted (ref: raft/log_unstable.go:23-31).

    entries[i] has raft log position i + offset.
    """

    def __init__(self, logger: Logger):
        self.snapshot: Optional[Snapshot] = None
        self.entries: List[Entry] = []
        self.offset = 0
        self.logger = logger

    def maybe_first_index(self) -> Optional[int]:
        if self.snapshot is not None:
            return self.snapshot.metadata.index + 1
        return None

    def maybe_last_index(self) -> Optional[int]:
        if self.entries:
            return self.offset + len(self.entries) - 1
        if self.snapshot is not None:
            return self.snapshot.metadata.index
        return None

    def maybe_term(self, i: int) -> Optional[int]:
        if i < self.offset:
            if self.snapshot is not None and self.snapshot.metadata.index == i:
                return self.snapshot.metadata.term
            return None
        last = self.maybe_last_index()
        if last is None or i > last:
            return None
        return self.entries[i - self.offset].term

    def stable_to(self, i: int, t: int) -> None:
        gt = self.maybe_term(i)
        if gt is None:
            return
        # An index below offset was stabilized by the snapshot; only drop
        # unstable entries when the term matches an unstable entry.
        if gt == t and i >= self.offset:
            self.entries = self.entries[i + 1 - self.offset :]
            self.offset = i + 1

    def stable_snap_to(self, i: int) -> None:
        if self.snapshot is not None and self.snapshot.metadata.index == i:
            self.snapshot = None

    def restore(self, s: Snapshot) -> None:
        self.offset = s.metadata.index + 1
        self.entries = []
        self.snapshot = s

    def truncate_and_append(self, ents: List[Entry]) -> None:
        """ref: log_unstable.go:121-141."""
        after = ents[0].index
        if after == self.offset + len(self.entries):
            self.entries = self.entries + list(ents)
        elif after <= self.offset:
            self.logger.infof("replace the unstable entries from index %d", after)
            self.offset = after
            self.entries = list(ents)
        else:
            self.logger.infof("truncate the unstable entries before index %d", after)
            self.entries = self.slice(self.offset, after) + list(ents)

    def slice(self, lo: int, hi: int) -> List[Entry]:
        self._must_check_out_of_bounds(lo, hi)
        return self.entries[lo - self.offset : hi - self.offset]

    def _must_check_out_of_bounds(self, lo: int, hi: int) -> None:
        if lo > hi:
            self.logger.panicf("invalid unstable.slice %d > %d", lo, hi)
        upper = self.offset + len(self.entries)
        if lo < self.offset or hi > upper:
            self.logger.panicf(
                "unstable.slice[%d,%d) out of bound [%d,%d]", lo, hi, self.offset, upper
            )


class RaftLog:
    """ref: raft/log.go:24-45."""

    def __init__(self, storage: Storage, logger: Optional[Logger] = None,
                 max_next_ents_size: int = NO_LIMIT):
        if storage is None:
            raise ValueError("storage must not be nil")
        self.storage = storage
        self.logger = logger if logger is not None else get_logger()
        self.max_next_ents_size = max_next_ents_size
        self.unstable = Unstable(self.logger)
        self.unstable.offset = storage.last_index() + 1
        first_index = storage.first_index()
        # committed/applied start at the point of the last compaction.
        self.committed = first_index - 1
        self.applied = first_index - 1

    def __str__(self) -> str:
        return (
            f"committed={self.committed}, applied={self.applied}, "
            f"unstable.offset={self.unstable.offset}, "
            f"len(unstable.Entries)={len(self.unstable.entries)}"
        )

    def maybe_append(
        self, index: int, log_term: int, committed: int, ents: List[Entry]
    ) -> Tuple[int, bool]:
        """Append if (index, log_term) matches; returns (last new index, ok)
        (ref: log.go:88-107)."""
        if not self.match_term(index, log_term):
            return 0, False
        lastnewi = index + len(ents)
        ci = self.find_conflict(ents)
        if ci == 0:
            pass
        elif ci <= self.committed:
            self.logger.panicf(
                "entry %d conflict with committed entry [committed(%d)]",
                ci, self.committed,
            )
        else:
            offset = index + 1
            if ci - offset > len(ents):
                self.logger.panicf("index, %d, is out of range [%d]", ci - offset, len(ents))
            self.append(ents[ci - offset :])
        self.commit_to(min(committed, lastnewi))
        return lastnewi, True

    def append(self, ents: List[Entry]) -> int:
        if not ents:
            return self.last_index()
        after = ents[0].index - 1
        if after < self.committed:
            self.logger.panicf("after(%d) is out of range [committed(%d)]", after, self.committed)
        self.unstable.truncate_and_append(ents)
        return self.last_index()

    def find_conflict(self, ents: List[Entry]) -> int:
        """First index where the given entries diverge (ref: log.go:130-141)."""
        for ne in ents:
            if not self.match_term(ne.index, ne.term):
                if ne.index <= self.last_index():
                    self.logger.infof(
                        "found conflict at index %d [existing term: %d, conflicting term: %d]",
                        ne.index,
                        self.zero_term_on_err_compacted(ne.index),
                        ne.term,
                    )
                return ne.index
        return 0

    def find_conflict_by_term(self, index: int, term: int) -> int:
        """Largest index ≤ `index` with term ≤ `term` (ref: log.go:150-171)."""
        li = self.last_index()
        if index > li:
            self.logger.warningf(
                "index(%d) is out of range [0, lastIndex(%d)] in findConflictByTerm",
                index, li,
            )
            return index
        while True:
            try:
                log_term = self.term(index)
            except (CompactedError, UnavailableError):
                break
            if log_term <= term:
                break
            index -= 1
        return index

    def unstable_entries(self) -> List[Entry]:
        return self.unstable.entries

    def next_ents(self) -> List[Entry]:
        """Committed-but-unapplied entries (ref: log.go:183-193)."""
        off = max(self.applied + 1, self.first_index())
        if self.committed + 1 > off:
            try:
                return self.slice(off, self.committed + 1, self.max_next_ents_size)
            except (CompactedError, UnavailableError) as e:
                self.logger.panicf("unexpected error when getting unapplied entries (%s)", e)
        return []

    def has_next_ents(self) -> bool:
        off = max(self.applied + 1, self.first_index())
        return self.committed + 1 > off

    def has_pending_snapshot(self) -> bool:
        s = self.unstable.snapshot
        return s is not None and s.metadata.index != 0

    def snapshot(self) -> Snapshot:
        if self.unstable.snapshot is not None:
            return self.unstable.snapshot
        return self.storage.snapshot()

    def first_index(self) -> int:
        i = self.unstable.maybe_first_index()
        if i is not None:
            return i
        return self.storage.first_index()

    def last_index(self) -> int:
        i = self.unstable.maybe_last_index()
        if i is not None:
            return i
        return self.storage.last_index()

    def commit_to(self, tocommit: int) -> None:
        if self.committed < tocommit:
            if self.last_index() < tocommit:
                self.logger.panicf(
                    "tocommit(%d) is out of range [lastIndex(%d)]. "
                    "Was the raft log corrupted, truncated, or lost?",
                    tocommit, self.last_index(),
                )
            self.committed = tocommit

    def applied_to(self, i: int) -> None:
        if i == 0:
            return
        if self.committed < i or i < self.applied:
            self.logger.panicf(
                "applied(%d) is out of range [prevApplied(%d), committed(%d)]",
                i, self.applied, self.committed,
            )
        self.applied = i

    def stable_to(self, i: int, t: int) -> None:
        self.unstable.stable_to(i, t)

    def stable_snap_to(self, i: int) -> None:
        self.unstable.stable_snap_to(i)

    def last_term(self) -> int:
        try:
            return self.term(self.last_index())
        except (CompactedError, UnavailableError) as e:
            self.logger.panicf("unexpected error when getting the last term (%s)", e)

    def term(self, i: int) -> int:
        """Term of entry i; 0 if outside [dummy index, last index]
        (ref: log.go:268-288). Raises CompactedError/UnavailableError only
        when the storage does."""
        dummy_index = self.first_index() - 1
        if i < dummy_index or i > self.last_index():
            return 0
        t = self.unstable.maybe_term(i)
        if t is not None:
            return t
        return self.storage.term(i)

    def zero_term_on_err_compacted(self, i: int) -> int:
        try:
            return self.term(i)
        except CompactedError:
            return 0

    def entries(self, i: int, max_size: int) -> List[Entry]:
        if i > self.last_index():
            return []
        return self.slice(i, self.last_index() + 1, max_size)

    def all_entries(self) -> List[Entry]:
        try:
            return self.entries(self.first_index(), NO_LIMIT)
        except CompactedError:  # racing compaction; retry
            return self.all_entries()

    def is_up_to_date(self, lasti: int, term: int) -> bool:
        """ref: log.go:316-318."""
        return term > self.last_term() or (
            term == self.last_term() and lasti >= self.last_index()
        )

    def match_term(self, i: int, term: int) -> bool:
        try:
            return self.term(i) == term
        except (CompactedError, UnavailableError):
            return False

    def maybe_commit(self, max_index: int, term: int) -> bool:
        if max_index > self.committed and self.zero_term_on_err_compacted(max_index) == term:
            self.commit_to(max_index)
            return True
        return False

    def restore(self, s: Snapshot) -> None:
        self.logger.infof(
            "log [%s] starts to restore snapshot [index: %d, term: %d]",
            self, s.metadata.index, s.metadata.term,
        )
        self.committed = s.metadata.index
        self.unstable.restore(s)

    def slice(self, lo: int, hi: int, max_size: int) -> List[Entry]:
        """Entries [lo, hi) subject to the size budget (ref: log.go:343-381)."""
        self._must_check_out_of_bounds(lo, hi)
        if lo == hi:
            return []
        ents: List[Entry] = []
        if lo < self.unstable.offset:
            try:
                stored = self.storage.entries(lo, min(hi, self.unstable.offset), max_size)
            except UnavailableError:
                self.logger.panicf(
                    "entries[%d:%d) is unavailable from storage",
                    lo, min(hi, self.unstable.offset),
                )
            if len(stored) < min(hi, self.unstable.offset) - lo:
                return stored  # hit the size limit
            ents = stored
        if hi > self.unstable.offset:
            unstable = self.unstable.slice(max(lo, self.unstable.offset), hi)
            ents = ents + unstable if ents else unstable
        return limit_size(ents, max_size)

    def _must_check_out_of_bounds(self, lo: int, hi: int) -> None:
        if lo > hi:
            self.logger.panicf("invalid slice %d > %d", lo, hi)
        fi = self.first_index()
        if lo < fi:
            raise CompactedError()
        length = self.last_index() + 1 - fi
        if hi > fi + length:
            self.logger.panicf(
                "slice[%d,%d) out of bound [%d,%d]", lo, hi, fi, self.last_index()
            )
