"""Async Node wrapper: the channel-based driver loop over RawNode
(ref: raft/node.go:126-207 Node interface, node.go:303-410 run loop).

The reference multiplexes Go channels (propc/recvc/confc/tickc/readyc/
advancec) in a select loop. The Python equivalent runs one event-loop
thread over a command deque + condition variable, preserving the
observable contract:

* proposals block the caller until accepted by the state machine, and
  are *deferred* (not dropped) while the group has no leader
  (node.go:305,348 — propc is nil until lead != None);
* at most one Ready is outstanding: the next Ready is only produced
  after Advance (node.go:316-327 readyc/advancec interlock);
* ticks never block the driver (buffered tickc, node.go:283,414) —
  they coalesce if the loop falls behind.

The batched engine (etcd_tpu/batched) is the many-group analog of this
loop; this single-group Node is the plugin boundary etcdserver-style
hosts program against.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .errors import RaftError
from .raft import NONE, Config
from .rawnode import RawNode, Ready, Status, marshal_conf_change
from .types import (
    ConfChange,
    ConfChangeType,
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
)
from .raft import is_local_msg, is_response_msg


class NodeStoppedError(RaftError):
    """ref: node.go ErrStopped."""


@dataclass
class Peer:
    """Initial cluster member for bootstrap (ref: raft/node.go:210-214)."""

    id: int = 0
    context: bytes = b""


def bootstrap(rn: RawNode, peers: List[Peer]) -> None:
    """Seed an empty Storage with a config describing the initial peers —
    appends one EntryConfChange per peer at term 1 and pre-commits them
    (ref: raft/bootstrap.go:30-80)."""
    if not peers:
        raise ValueError("must provide at least one peer to Bootstrap")
    if rn.raft.raft_log.storage.last_index() != 0:
        raise ValueError("can't bootstrap a nonempty Storage")
    rn.prev_hard_st = HardState()
    rn.raft.become_follower(1, NONE)
    ents: List[Entry] = []
    for i, peer in enumerate(peers):
        cc = ConfChange(
            type=ConfChangeType.ConfChangeAddNode,
            node_id=peer.id,
            context=peer.context,
        )
        ents.append(
            Entry(
                type=EntryType.EntryConfChange,
                term=1,
                index=i + 1,
                data=cc.marshal(),
            )
        )
    rn.raft.raft_log.append(ents)
    rn.raft.raft_log.committed = len(ents)
    for peer in peers:
        rn.raft.apply_conf_change(
            ConfChange(node_id=peer.id).as_v2()
        )


@dataclass
class _Prop:
    msg: Message
    done: threading.Event = field(default_factory=threading.Event)
    err: Optional[BaseException] = None


class Node:
    """Threaded driver over RawNode (ref: raft/node.go:116-124 node).

    Lifecycle: ``Node.start(cfg, peers)`` / ``Node.restart(cfg)`` spawn
    the loop thread; the host consumes ``ready()`` → persist/send →
    ``advance()``; ``stop()`` joins the thread.
    """

    def __init__(self, rn: RawNode):
        self.rn = rn
        self._cv = threading.Condition()
        self._cmds: deque = deque()  # _Prop | ("recv", m) | ("conf", cc, box) | ...
        self._props: deque = deque()  # deferred proposals (no leader yet)
        self._ready_q: deque = deque()  # at most 1 accepted Ready
        self._advance_pending: Optional[Ready] = None
        self._tick_count = 0  # coalesced pending ticks
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True)

    # -- lifecycle -------------------------------------------------------------

    @staticmethod
    def start(config: Config, peers: List[Peer]) -> "Node":
        """ref: node.go:218-241 StartNode."""
        rn = RawNode(config)
        bootstrap(rn, peers)
        n = Node(rn)
        n._thread.start()
        return n

    @staticmethod
    def restart(config: Config) -> "Node":
        """Rejoin from Storage state; no peers passed
        (ref: node.go:244-249 RestartNode)."""
        rn = RawNode(config)
        n = Node(rn)
        n._thread.start()
        return n

    def stop(self) -> None:
        with self._cv:
            if self._stopped:
                return
            self._stopped = True
            self._cv.notify_all()
        self._thread.join()

    # -- input side ------------------------------------------------------------

    def tick(self) -> None:
        """Never blocks; coalesces under load (ref: node.go:414-422)."""
        with self._cv:
            if self._stopped:
                return
            self._tick_count += 1
            self._cv.notify_all()

    def campaign(self) -> None:
        self._step_wait(Message(type=MessageType.MsgHup), wait=False)

    def propose(self, data: bytes, timeout: Optional[float] = None) -> None:
        """Blocks until the proposal is stepped into the state machine
        (ref: node.go:424-426 Propose → stepWait)."""
        self._step_wait(
            Message(type=MessageType.MsgProp, entries=[Entry(data=data)]),
            wait=True,
            timeout=timeout,
        )

    def propose_conf_change(self, cc, timeout: Optional[float] = None) -> None:
        typ, data = marshal_conf_change(cc)
        self._step_wait(
            Message(type=MessageType.MsgProp, entries=[Entry(type=typ, data=data)]),
            wait=True,
            timeout=timeout,
        )

    def step(self, m: Message) -> None:
        """Feed a message from the network (ref: node.go:428-436; local
        messages are dropped there, not erred)."""
        if is_local_msg(m.type):
            return
        self._enqueue(("recv", m))

    def read_index(self, rctx: bytes) -> None:
        self._enqueue(
            ("recv", Message(type=MessageType.MsgReadIndex, entries=[Entry(data=rctx)]))
        )

    def transfer_leadership(self, lead: int, transferee: int) -> None:
        """ref: node.go:551-558."""
        self._enqueue(
            ("recv", Message(type=MessageType.MsgTransferLeader, from_=transferee, to=lead))
        )

    def report_unreachable(self, vid: int) -> None:
        self._enqueue(("recv", Message(type=MessageType.MsgUnreachable, from_=vid)))

    def report_snapshot(self, vid: int, failure: bool) -> None:
        self._enqueue(
            ("recv", Message(type=MessageType.MsgSnapStatus, from_=vid, reject=failure))
        )

    def apply_conf_change(self, cc) -> ConfState:
        """Synchronous round-trip through the loop thread
        (ref: node.go:503-514)."""
        box: dict = {}
        ev = threading.Event()
        self._enqueue(("conf", cc, box, ev))
        ev.wait()
        if "err" in box:
            raise box["err"]
        return box["cs"]

    def status(self) -> Status:
        box: dict = {}
        ev = threading.Event()
        self._enqueue(("status", box, ev))
        ev.wait()
        if "err" in box:
            raise box["err"]
        return box["status"]

    # -- output side -----------------------------------------------------------

    def ready(self, timeout: Optional[float] = None) -> Optional[Ready]:
        """Block for the next Ready; None on timeout or stop."""
        with self._cv:
            deadline = None
            while not self._ready_q and not self._stopped:
                if not self._cv.wait(timeout=timeout):
                    return None
            if self._ready_q:
                return self._ready_q.popleft()
            return None

    def has_ready(self) -> bool:
        with self._cv:
            return bool(self._ready_q)

    def advance(self) -> None:
        """ref: node.go:516-520 — allows the next Ready."""
        with self._cv:
            self._cmds.append(("advance",))
            self._cv.notify_all()

    # -- loop ------------------------------------------------------------------

    def _enqueue(self, cmd) -> None:
        with self._cv:
            if self._stopped:
                if cmd and isinstance(cmd, _Prop):
                    cmd.err = NodeStoppedError()
                    cmd.done.set()
                elif cmd and cmd[0] in ("conf", "status"):
                    cmd[-2]["err"] = NodeStoppedError()
                    cmd[-1].set()
                return
            self._cmds.append(cmd)
            self._cv.notify_all()

    def _step_wait(
        self, m: Message, wait: bool, timeout: Optional[float] = None
    ) -> None:
        """ref: node.go:464-501 stepWithWaitOption."""
        p = _Prop(msg=m)
        if m.type != MessageType.MsgProp:
            self._enqueue(("recv", m))
            return
        self._enqueue(p)
        if not wait:
            return
        if not p.done.wait(timeout=timeout):
            raise TimeoutError("proposal not accepted in time")
        if p.err is not None:
            raise p.err

    def _run(self) -> None:
        r = self.rn.raft
        lead = NONE
        while True:
            with self._cv:
                while (
                    not self._cmds
                    and self._tick_count == 0
                    and not self._stopped
                    and not (
                        self._advance_pending is None
                        and not self._ready_q
                        and self.rn.has_ready()
                    )
                    and not (self._props and r.lead != NONE)
                ):
                    self._cv.wait()
                if self._stopped:
                    self._fail_pending()
                    return
                cmds = list(self._cmds)
                self._cmds.clear()
                ticks = self._tick_count
                self._tick_count = 0
            for _ in range(ticks):
                self.rn.tick()
            # Leader-gate deferred proposals (ref: node.go:305-312: propc
            # is enabled only while there is a leader).
            if r.lead != NONE and self._props:
                cmds = list(self._props) + cmds
                self._props.clear()
            for cmd in cmds:
                self._handle(cmd)
            lead = r.lead
            # Produce the next Ready when the previous one is consumed.
            with self._cv:
                if (
                    self._advance_pending is None
                    and not self._ready_q
                    and self.rn.has_ready()
                ):
                    rd = self.rn.ready_without_accept()
                    self.rn.accept_ready(rd)
                    self._advance_pending = rd
                    self._ready_q.append(rd)
                    self._cv.notify_all()

    def _handle(self, cmd) -> None:
        r = self.rn.raft
        if isinstance(cmd, _Prop):
            if r.lead == NONE:
                self._props.append(cmd)  # defer until a leader exists
                return
            m = cmd.msg
            m.from_ = r.id
            try:
                r.step(m)
                cmd.done.set()
            except BaseException as e:  # noqa: BLE001 — surfaced to caller
                cmd.err = e
                cmd.done.set()
            return
        kind = cmd[0]
        if kind == "recv":
            m = cmd[1]
            # Filter unknown-peer responses (ref: node.go:356-360).
            if r.prs.progress.get(m.from_) is not None or not is_response_msg(m.type):
                try:
                    r.step(m)
                except RaftError:
                    pass
        elif kind == "conf":
            _, cc, box, ev = cmd
            try:
                box["cs"] = r.apply_conf_change(cc.as_v2())
            except BaseException as e:  # noqa: BLE001
                box["err"] = e
            ev.set()
        elif kind == "status":
            _, box, ev = cmd
            try:
                box["status"] = RawNode.status(self.rn)
            except BaseException as e:  # noqa: BLE001
                box["err"] = e
            ev.set()
        elif kind == "advance":
            if self._advance_pending is not None:
                self.rn.advance(self._advance_pending)
                self._advance_pending = None

    def _fail_pending(self) -> None:
        for cmd in list(self._cmds) + list(self._props):
            if isinstance(cmd, _Prop):
                cmd.err = NodeStoppedError()
                cmd.done.set()
            elif isinstance(cmd, tuple) and cmd[0] in ("conf", "status"):
                cmd[-2]["err"] = NodeStoppedError()
                cmd[-1].set()
        self._cmds.clear()
        self._props.clear()
