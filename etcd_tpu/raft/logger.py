"""Logger interface for the consensus core (ref: raft/logger.go).

Log lines are part of the observable contract: the interaction-trace
harness captures them and compares against the reference's testdata, so
formatting uses printf-style strings identical to the reference's.
"""

from __future__ import annotations

import sys


class Logger:
    """Level methods mirror raft/logger.go:25 Logger."""

    def debugf(self, fmt: str, *args) -> None: ...

    def infof(self, fmt: str, *args) -> None: ...

    def warningf(self, fmt: str, *args) -> None: ...

    def errorf(self, fmt: str, *args) -> None: ...

    def fatalf(self, fmt: str, *args) -> None: ...

    def panicf(self, fmt: str, *args) -> None:
        raise RuntimeError(fmt % args if args else fmt)

    def error(self, *args) -> None: ...


class DefaultLogger(Logger):
    """Prints to stderr (ref: raft/logger.go DefaultLogger)."""

    def __init__(self, level: int = 1):
        self.level = level  # 0=DEBUG 1=INFO 2=WARN 3=ERROR

    def _emit(self, lvl: int, name: str, fmt: str, args) -> None:
        if self.level <= lvl:
            print(name, fmt % args if args else fmt, file=sys.stderr)

    def debugf(self, fmt: str, *args) -> None:
        self._emit(0, "DEBUG", fmt, args)

    def infof(self, fmt: str, *args) -> None:
        self._emit(1, "INFO", fmt, args)

    def warningf(self, fmt: str, *args) -> None:
        self._emit(2, "WARN", fmt, args)

    def errorf(self, fmt: str, *args) -> None:
        self._emit(3, "ERROR", fmt, args)

    def error(self, *args) -> None:
        self._emit(3, "ERROR", " ".join(str(a) for a in args), ())

    def fatalf(self, fmt: str, *args) -> None:
        self._emit(4, "FATAL", fmt, args)

    def panicf(self, fmt: str, *args) -> None:
        self._emit(4, "FATAL", fmt, args)
        raise RuntimeError(fmt % args if args else fmt)


_global_logger = DefaultLogger()


def get_logger() -> Logger:
    return _global_logger


def set_logger(logger: Logger) -> None:
    global _global_logger
    _global_logger = logger
