"""RawNode: the synchronous, thread-unsafe façade over the state machine
(ref: raft/rawnode.go). This is the plugin boundary the batched engine
preserves: ``etcd_tpu.batched.BatchedRawNode`` exposes the same
HasReady → Ready → persist → send → Advance contract over G groups at
once.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .errors import RaftError, StepLocalMsgError, StepPeerNotFoundError
from .raft import (
    NONE,
    Config,
    Raft,
    SoftState,
    StateType,
    is_local_msg,
    is_response_msg,
)
from .read_only import ReadState
from .tracker import Progress, TrackerConfig, progress_map_str
from .types import (
    ConfChangeV2,
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    is_empty_hard_state,
    is_empty_snap,
)


@dataclass
class Ready:
    """Outstanding work for the application (ref: raft/node.go:52-90)."""

    soft_state: Optional[SoftState] = None
    hard_state: HardState = field(default_factory=HardState)
    read_states: List[ReadState] = field(default_factory=list)
    # To persist BEFORE messages are sent.
    entries: List[Entry] = field(default_factory=list)
    snapshot: Snapshot = field(default_factory=Snapshot)
    committed_entries: List[Entry] = field(default_factory=list)
    # To send AFTER entries are persisted.
    messages: List[Message] = field(default_factory=list)
    must_sync: bool = False

    def contains_updates(self) -> bool:
        return (
            self.soft_state is not None
            or not is_empty_hard_state(self.hard_state)
            or not is_empty_snap(self.snapshot)
            or bool(self.entries)
            or bool(self.committed_entries)
            or bool(self.messages)
            or bool(self.read_states)
        )

    def applied_cursor(self) -> int:
        """Highest index applied once this Ready is confirmed
        (ref: node.go:112-121)."""
        if self.committed_entries:
            return self.committed_entries[-1].index
        if self.snapshot.metadata.index > 0:
            return self.snapshot.metadata.index
        return 0


def must_sync(st: HardState, prevst: HardState, entsnum: int) -> bool:
    """Synchronous fsync needed? (ref: raft/node.go:588-595): term, vote and
    new entries are the durable Raft state."""
    return entsnum != 0 or st.vote != prevst.vote or st.term != prevst.term


def new_ready(r: Raft, prev_soft_st: SoftState, prev_hard_st: HardState) -> Ready:
    """ref: raft/node.go:564-584."""
    rd = Ready(
        entries=list(r.raft_log.unstable_entries()),
        committed_entries=r.raft_log.next_ents(),
        messages=r.msgs,
    )
    soft_st = r.soft_state()
    if not soft_st.equal(prev_soft_st):
        rd.soft_state = soft_st
    hard_st = r.hard_state()
    if not (
        hard_st.term == prev_hard_st.term
        and hard_st.vote == prev_hard_st.vote
        and hard_st.commit == prev_hard_st.commit
    ):
        rd.hard_state = hard_st
    if r.raft_log.unstable.snapshot is not None:
        rd.snapshot = r.raft_log.unstable.snapshot
    if r.read_states:
        rd.read_states = r.read_states
    rd.must_sync = must_sync(r.hard_state(), prev_hard_st, len(rd.entries))
    return rd


@dataclass
class BasicStatus:
    """ref: raft/status.go:33-42."""

    id: int = 0
    hard_state: HardState = field(default_factory=HardState)
    soft_state: SoftState = field(default_factory=SoftState)
    applied: int = 0
    lead_transferee: int = 0


@dataclass
class Status:
    """ref: raft/status.go:26-30."""

    basic: BasicStatus = field(default_factory=BasicStatus)
    config: TrackerConfig = field(default_factory=TrackerConfig)
    progress: Dict[int, Progress] = field(default_factory=dict)

    @property
    def id(self) -> int:
        return self.basic.id

    @property
    def raft_state(self) -> StateType:
        return self.basic.soft_state.raft_state


class RawNode:
    """ref: raft/rawnode.go:34-38."""

    def __init__(self, config: Config):
        self.raft = Raft(config)
        self.prev_soft_st = self.raft.soft_state()
        self.prev_hard_st = self.raft.hard_state()

    def tick(self) -> None:
        self.raft.tick()

    def tick_quiesced(self) -> None:
        """Advance only the logical clock (ref: rawnode.go:62-72)."""
        self.raft.election_elapsed += 1

    def campaign(self) -> None:
        self.raft.step(Message(type=MessageType.MsgHup))

    def propose(self, data: bytes) -> None:
        self.raft.step(
            Message(
                type=MessageType.MsgProp,
                from_=self.raft.id,
                entries=[Entry(data=data)],
            )
        )

    def propose_conf_change(self, cc) -> None:
        typ, data = marshal_conf_change(cc)
        self.raft.step(
            Message(type=MessageType.MsgProp, entries=[Entry(type=typ, data=data)])
        )

    def apply_conf_change(self, cc) -> ConfState:
        return self.raft.apply_conf_change(cc.as_v2())

    def step(self, m: Message) -> None:
        # Local messages arriving over the network are invalid.
        if is_local_msg(m.type):
            raise StepLocalMsgError()
        if self.raft.prs.progress.get(m.from_) is not None or not is_response_msg(m.type):
            return self.raft.step(m)
        raise StepPeerNotFoundError()

    def ready(self) -> Ready:
        rd = self.ready_without_accept()
        self.accept_ready(rd)
        return rd

    def ready_without_accept(self) -> Ready:
        return new_ready(self.raft, self.prev_soft_st, self.prev_hard_st)

    def accept_ready(self, rd: Ready) -> None:
        if rd.soft_state is not None:
            self.prev_soft_st = rd.soft_state
        if rd.read_states:
            self.raft.read_states = []
        self.raft.msgs = []

    def has_ready(self) -> bool:
        """Must stay consistent with Ready.contains_updates()
        (ref: rawnode.go:152-170)."""
        r = self.raft
        if not r.soft_state().equal(self.prev_soft_st):
            return True
        hard_st = r.hard_state()
        if not is_empty_hard_state(hard_st) and not (
            hard_st.term == self.prev_hard_st.term
            and hard_st.vote == self.prev_hard_st.vote
            and hard_st.commit == self.prev_hard_st.commit
        ):
            return True
        if r.raft_log.has_pending_snapshot():
            return True
        if r.msgs or r.raft_log.unstable_entries() or r.raft_log.has_next_ents():
            return True
        if r.read_states:
            return True
        return False

    def advance(self, rd: Ready) -> None:
        if not is_empty_hard_state(rd.hard_state):
            self.prev_hard_st = rd.hard_state
        self.raft.advance(rd)

    def status(self) -> Status:
        r = self.raft
        s = Status(basic=self.basic_status())
        if s.basic.soft_state.raft_state == StateType.StateLeader:
            s.progress = {vid: pr.copy() for vid, pr in r.prs.progress.items()}
        s.config = r.prs.config.clone()
        return s

    def basic_status(self) -> BasicStatus:
        r = self.raft
        return BasicStatus(
            id=r.id,
            hard_state=r.hard_state(),
            soft_state=r.soft_state(),
            applied=r.raft_log.applied,
            lead_transferee=r.lead_transferee,
        )

    def report_unreachable(self, vid: int) -> None:
        try:
            self.raft.step(Message(type=MessageType.MsgUnreachable, from_=vid))
        except RaftError:
            pass

    def report_snapshot(self, vid: int, failure: bool) -> None:
        try:
            self.raft.step(
                Message(type=MessageType.MsgSnapStatus, from_=vid, reject=failure)
            )
        except RaftError:
            pass

    def transfer_leader(self, transferee: int) -> None:
        try:
            self.raft.step(Message(type=MessageType.MsgTransferLeader, from_=transferee))
        except RaftError:
            pass

    def read_index(self, rctx: bytes) -> None:
        try:
            self.raft.step(
                Message(type=MessageType.MsgReadIndex, entries=[Entry(data=rctx)])
            )
        except RaftError:
            pass


def marshal_conf_change(cc):
    """(EntryType, data) for a conf change (ref: raftpb/confchange.go:170)."""
    v1, ok = cc.as_v1()
    if ok:
        return EntryType.EntryConfChange, v1.marshal()
    return EntryType.EntryConfChangeV2, cc.as_v2().marshal()
