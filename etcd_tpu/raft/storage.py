"""Stable log storage interface and in-memory implementation
(ref: raft/storage.go).

In the batched TPU engine only a bounded tail window of each group's log
lives on-device (``[G, W]`` term ring); Storage is the host-side spill
target, so this interface is deliberately identical in contract to the
reference's, keeping the plugin boundary intact.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Protocol, Tuple

from .errors import CompactedError, SnapOutOfDateError, UnavailableError
from .types import ConfState, Entry, HardState, Snapshot, SnapshotMetadata


def limit_size(ents: List[Entry], max_size: int) -> List[Entry]:
    """Prefix of ents with aggregate proto size ≤ max_size, but always at
    least one entry (ref: raft/util.go:212 limitSize)."""
    if not ents:
        return ents
    size = ents[0].size()
    limit = 1
    while limit < len(ents):
        size += ents[limit].size()
        if size > max_size:
            break
        limit += 1
    return ents[:limit]


class Storage(Protocol):
    """ref: raft/storage.go:46-72."""

    def initial_state(self) -> Tuple[HardState, ConfState]: ...

    def entries(self, lo: int, hi: int, max_size: int) -> List[Entry]: ...

    def term(self, i: int) -> int: ...

    def last_index(self) -> int: ...

    def first_index(self) -> int: ...

    def snapshot(self) -> Snapshot: ...


class MemoryStorage:
    """In-memory Storage with a dummy entry at offset 0
    (ref: raft/storage.go:76-273)."""

    def __init__(self):
        self._mu = threading.Lock()
        self.hard_state = HardState()
        self._snapshot = Snapshot()
        # ents[i] has raft log position i + snapshot.metadata.index
        self.ents: List[Entry] = [Entry()]

    def initial_state(self) -> Tuple[HardState, ConfState]:
        return self.hard_state, self._snapshot.metadata.conf_state

    def set_hard_state(self, st: HardState) -> None:
        with self._mu:
            self.hard_state = st

    def entries(self, lo: int, hi: int, max_size: int) -> List[Entry]:
        with self._mu:
            offset = self.ents[0].index
            if lo <= offset:
                raise CompactedError()
            if hi > self._last_index() + 1:
                raise RuntimeError(
                    f"entries' hi({hi}) is out of bound lastindex({self._last_index()})"
                )
            if len(self.ents) == 1:  # only the dummy entry
                raise UnavailableError()
            return limit_size(self.ents[lo - offset : hi - offset], max_size)

    def term(self, i: int) -> int:
        with self._mu:
            offset = self.ents[0].index
            if i < offset:
                raise CompactedError()
            if i - offset >= len(self.ents):
                raise UnavailableError()
            return self.ents[i - offset].term

    def last_index(self) -> int:
        with self._mu:
            return self._last_index()

    def _last_index(self) -> int:
        return self.ents[0].index + len(self.ents) - 1

    def first_index(self) -> int:
        with self._mu:
            return self._first_index()

    def _first_index(self) -> int:
        return self.ents[0].index + 1

    def snapshot(self) -> Snapshot:
        with self._mu:
            return self._copy_snapshot()

    def _copy_snapshot(self) -> Snapshot:
        # Return a value copy, like Go's by-value Snapshot returns: callers
        # (e.g. a queued MsgSnap) must not observe later create_snapshot
        # mutations of the internal object.
        m = self._snapshot.metadata
        return Snapshot(
            data=self._snapshot.data,
            metadata=SnapshotMetadata(
                conf_state=m.conf_state.clone(), index=m.index, term=m.term
            ),
        )

    def apply_snapshot(self, snap: Snapshot) -> None:
        """Replace contents with the snapshot (ref: storage.go:172-187)."""
        with self._mu:
            if self._snapshot.metadata.index >= snap.metadata.index:
                raise SnapOutOfDateError()
            self._snapshot = snap
            self.ents = [Entry(term=snap.metadata.term, index=snap.metadata.index)]

    def create_snapshot(
        self, i: int, cs: Optional[ConfState], data: bytes
    ) -> Snapshot:
        """ref: storage.go:193-214."""
        with self._mu:
            if i <= self._snapshot.metadata.index:
                raise SnapOutOfDateError()
            offset = self.ents[0].index
            if i > self._last_index():
                raise RuntimeError(
                    f"snapshot {i} is out of bound lastindex({self._last_index()})"
                )
            self._snapshot.metadata.index = i
            self._snapshot.metadata.term = self.ents[i - offset].term
            if cs is not None:
                self._snapshot.metadata.conf_state = cs
            self._snapshot.data = data
            return self._copy_snapshot()

    def compact(self, compact_index: int) -> None:
        """Drop entries before compact_index (ref: storage.go:218-237)."""
        with self._mu:
            offset = self.ents[0].index
            if compact_index <= offset:
                raise CompactedError()
            if compact_index > self._last_index():
                raise RuntimeError(
                    f"compact {compact_index} is out of bound lastindex({self._last_index()})"
                )
            i = compact_index - offset
            ents = [Entry(index=self.ents[i].index, term=self.ents[i].term)]
            ents.extend(self.ents[i + 1 :])
            self.ents = ents

    def append(self, entries: List[Entry]) -> None:
        """ref: storage.go:241-273."""
        if not entries:
            return
        with self._mu:
            first = self._first_index()
            last = entries[0].index + len(entries) - 1
            if last < first:
                return
            if first > entries[0].index:
                entries = entries[first - entries[0].index :]
            offset = entries[0].index - self.ents[0].index
            if len(self.ents) > offset:
                self.ents = self.ents[:offset] + list(entries)
            elif len(self.ents) == offset:
                self.ents = self.ents + list(entries)
            else:
                raise RuntimeError(
                    f"missing log entry [last: {self._last_index()}, "
                    f"append at: {entries[0].index}]"
                )
