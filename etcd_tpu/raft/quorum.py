"""Quorum math: majority and joint configurations
(ref: raft/quorum/{majority,joint,quorum}.go).

``committed_index`` and ``vote_result`` are the two reductions that become
TPU kernels in the batched engine: commit index is the (n - n//2 - 1)-th
order statistic of the acked indexes over the replica axis, and vote
tallies are masked sums. The definitions here are the scalar oracles; the
array forms live in ``etcd_tpu.batched.kernels`` and are differentially
tested against these.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Callable, Dict, Iterable, Optional, Set, Tuple

MAX_UINT64 = (1 << 64) - 1


class VoteResult(IntEnum):
    """ref: raft/quorum/quorum.go:44-58."""

    VotePending = 1
    VoteLost = 2
    VoteWon = 3

    def __str__(self) -> str:
        return self.name


def index_str(i: int) -> str:
    return "∞" if i == MAX_UINT64 else str(i)


class MajorityConfig(set):
    """A set of voter IDs deciding by majority (ref: quorum/majority.go:25)."""

    def __str__(self) -> str:
        return "(" + " ".join(str(i) for i in sorted(self)) + ")"

    def slice(self):
        return sorted(self)

    def committed_index(self, acked_index: Callable[[int], Optional[int]]) -> int:
        """Largest index acked by a quorum (ref: quorum/majority.go:126-172).

        Voters that have not reported in count as 0; with n voters the
        result is the value at position n-(n//2+1) of the ascending sort.
        """
        n = len(self)
        if n == 0:
            # An empty config commits everything; makes a half-populated
            # joint quorum behave like a majority quorum.
            return MAX_UINT64
        srt = sorted((acked_index(vid) or 0) for vid in self)
        return srt[n - (n // 2 + 1)]

    def vote_result(self, votes: Dict[int, bool]) -> VoteResult:
        """ref: quorum/majority.go:178-210."""
        if len(self) == 0:
            return VoteResult.VoteWon
        yes = no = missing = 0
        for vid in self:
            if vid not in votes:
                missing += 1
            elif votes[vid]:
                yes += 1
            else:
                no += 1
        q = len(self) // 2 + 1
        if yes >= q:
            return VoteResult.VoteWon
        if yes + missing >= q:
            return VoteResult.VotePending
        return VoteResult.VoteLost

    def describe(self, acked_index: Callable[[int], Optional[int]]) -> str:
        """Multi-line commit-index chart (ref: quorum/majority.go:47-103)."""
        if len(self) == 0:
            return "<empty majority quorum>"
        n = len(self)
        info = []
        for vid in self:
            idx = acked_index(vid)
            info.append([vid, idx if idx is not None else 0, idx is not None, 0])
        info.sort(key=lambda t: (t[1], t[0]))
        for i in range(1, len(info)):
            if info[i - 1][1] < info[i][1]:
                info[i][3] = i
        info.sort(key=lambda t: t[0])
        out = [" " * n + "    idx"]
        for vid, idx, ok, bar in info:
            if not ok:
                row = "?" + " " * n
            else:
                row = "x" * bar + ">" + " " * (n - bar)
            out.append("%s %5d    (id=%d)" % (row, idx, vid))
        return "\n".join(out) + "\n"


class JointConfig:
    """Two possibly-overlapping majority configs; decisions need both
    (ref: quorum/joint.go:19)."""

    def __init__(self, incoming: Optional[Iterable[int]] = None,
                 outgoing: Optional[Iterable[int]] = None):
        self.incoming = MajorityConfig(incoming or ())
        self.outgoing = MajorityConfig(outgoing or ())

    def __getitem__(self, i: int) -> MajorityConfig:
        return (self.incoming, self.outgoing)[i]

    def __str__(self) -> str:
        if self.outgoing:
            return f"{self.incoming}&&{self.outgoing}"
        return str(self.incoming)

    def ids(self) -> Set[int]:
        return set(self.incoming) | set(self.outgoing)

    def committed_index(self, acked_index: Callable[[int], Optional[int]]) -> int:
        """min over both halves (ref: quorum/joint.go:49-56)."""
        return min(
            self.incoming.committed_index(acked_index),
            self.outgoing.committed_index(acked_index),
        )

    def vote_result(self, votes: Dict[int, bool]) -> VoteResult:
        """ref: quorum/joint.go:61-75."""
        r1 = self.incoming.vote_result(votes)
        r2 = self.outgoing.vote_result(votes)
        if r1 == r2:
            return r1
        if VoteResult.VoteLost in (r1, r2):
            return VoteResult.VoteLost
        return VoteResult.VotePending

    def describe(self, acked_index: Callable[[int], Optional[int]]) -> str:
        return MajorityConfig(self.ids()).describe(acked_index)

    def clone(self) -> "JointConfig":
        return JointConfig(set(self.incoming), set(self.outgoing))
