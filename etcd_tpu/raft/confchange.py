"""Joint-consensus configuration changes (ref: raft/confchange/).

This is control-plane code: in the TPU design, conf changes run host-side
and emit fresh ``[G, R]`` voter/learner masks that are uploaded to the
device; correctness (not throughput) is what matters here.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from .quorum import MajorityConfig
from .tracker import Inflights, Progress, ProgressTracker, TrackerConfig
from .types import ConfChangeSingle, ConfChangeType, ConfState


class ConfChangeError(Exception):
    pass


class Changer:
    """ref: raft/confchange/confchange.go:31-34."""

    def __init__(self, tracker: ProgressTracker, last_index: int):
        self.tracker = tracker
        self.last_index = last_index

    # -- public operations ----------------------------------------------------

    def enter_joint(
        self, auto_leave: bool, ccs: List[ConfChangeSingle]
    ) -> Tuple[TrackerConfig, Dict[int, Progress]]:
        """ref: confchange.go:49-76."""
        cfg, prs = self._check_and_copy()
        if _joint(cfg):
            raise ConfChangeError("config is already joint")
        if len(cfg.voters.incoming) == 0:
            # Adding nodes to an empty config is allowed (bootstrap), but a
            # joint transition from nothing is not.
            raise ConfChangeError("can't make a zero-voter config joint")
        cfg.voters.outgoing = MajorityConfig(cfg.voters.incoming)
        self._apply(cfg, prs, ccs)
        cfg.auto_leave = auto_leave
        return _check_and_return(cfg, prs)

    def leave_joint(self) -> Tuple[TrackerConfig, Dict[int, Progress]]:
        """ref: confchange.go:92-123."""
        cfg, prs = self._check_and_copy()
        if not _joint(cfg):
            raise ConfChangeError("can't leave a non-joint config")
        if len(cfg.voters.outgoing) == 0:
            raise ConfChangeError(f"configuration is not joint: {cfg}")
        for vid in list(cfg.learners_next):
            cfg.learners.add(vid)
            prs[vid].is_learner = True
        cfg.learners_next = set()

        for vid in list(cfg.voters.outgoing):
            is_voter = vid in cfg.voters.incoming
            is_learner = vid in cfg.learners
            if not is_voter and not is_learner:
                del prs[vid]
        cfg.voters.outgoing = MajorityConfig()
        cfg.auto_leave = False
        return _check_and_return(cfg, prs)

    def simple(
        self, ccs: List[ConfChangeSingle]
    ) -> Tuple[TrackerConfig, Dict[int, Progress]]:
        """At most one voter change outside a joint config
        (ref: confchange.go:130-147)."""
        cfg, prs = self._check_and_copy()
        if _joint(cfg):
            raise ConfChangeError("can't apply simple config change in joint config")
        self._apply(cfg, prs, ccs)
        if (
            len(
                set(self.tracker.voters.incoming).symmetric_difference(
                    cfg.voters.incoming
                )
            )
            > 1
        ):
            raise ConfChangeError(
                "more than one voter changed without entering joint config"
            )
        return _check_and_return(cfg, prs)

    # -- internals ------------------------------------------------------------

    def _apply(
        self,
        cfg: TrackerConfig,
        prs: Dict[int, Progress],
        ccs: List[ConfChangeSingle],
    ) -> None:
        for cc in ccs:
            if cc.node_id == 0:
                # etcd zeroes the NodeID to mark a change it refused to apply.
                continue
            if cc.type == ConfChangeType.ConfChangeAddNode:
                self._make_voter(cfg, prs, cc.node_id)
            elif cc.type == ConfChangeType.ConfChangeAddLearnerNode:
                self._make_learner(cfg, prs, cc.node_id)
            elif cc.type == ConfChangeType.ConfChangeRemoveNode:
                self._remove(cfg, prs, cc.node_id)
            elif cc.type == ConfChangeType.ConfChangeUpdateNode:
                pass
            else:
                raise ConfChangeError(f"unexpected conf type {cc.type}")
        if len(cfg.voters.incoming) == 0:
            raise ConfChangeError("removed all voters")

    def _make_voter(self, cfg: TrackerConfig, prs: Dict[int, Progress], vid: int) -> None:
        pr = prs.get(vid)
        if pr is None:
            self._init_progress(cfg, prs, vid, is_learner=False)
            return
        pr.is_learner = False
        cfg.learners.discard(vid)
        cfg.learners_next.discard(vid)
        cfg.voters.incoming.add(vid)

    def _make_learner(self, cfg: TrackerConfig, prs: Dict[int, Progress], vid: int) -> None:
        """ref: confchange.go:207-232 — demotions of outgoing voters are
        staged in learners_next until LeaveJoint."""
        pr = prs.get(vid)
        if pr is None:
            self._init_progress(cfg, prs, vid, is_learner=True)
            return
        if pr.is_learner:
            return
        self._remove(cfg, prs, vid)
        prs[vid] = pr
        if vid in cfg.voters.outgoing:
            cfg.learners_next.add(vid)
        else:
            pr.is_learner = True
            cfg.learners.add(vid)

    def _remove(self, cfg: TrackerConfig, prs: Dict[int, Progress], vid: int) -> None:
        if vid not in prs:
            return
        cfg.voters.incoming.discard(vid)
        cfg.learners.discard(vid)
        cfg.learners_next.discard(vid)
        # Keep the Progress while the peer is still an outgoing voter.
        if vid not in cfg.voters.outgoing:
            del prs[vid]

    def _init_progress(
        self, cfg: TrackerConfig, prs: Dict[int, Progress], vid: int, is_learner: bool
    ) -> None:
        if not is_learner:
            cfg.voters.incoming.add(vid)
        else:
            cfg.learners.add(vid)
        # Initializing Next to last_index means the follower is probed with
        # the last index; mark recently-active so CheckQuorum doesn't
        # immediately demote a leader that just added a node.
        prs[vid] = Progress(
            match=0,
            next=self.last_index,
            inflights=Inflights(self.tracker.max_inflight),
            is_learner=is_learner,
            recent_active=True,
        )

    def _check_and_copy(self) -> Tuple[TrackerConfig, Dict[int, Progress]]:
        cfg = self.tracker.config.clone()
        prs = {vid: pr.copy() for vid, pr in self.tracker.progress.items()}
        return _check_and_return(cfg, prs)


def _joint(cfg: TrackerConfig) -> bool:
    return len(cfg.voters.outgoing) > 0


def _check_invariants(cfg: TrackerConfig, prs: Dict[int, Progress]) -> None:
    """ref: confchange.go:283-330."""
    for ids in (cfg.voters.ids(), cfg.learners, cfg.learners_next):
        for vid in ids:
            if vid not in prs:
                raise ConfChangeError(f"no progress for {vid}")
    for vid in cfg.learners_next:
        if vid not in cfg.voters.outgoing:
            raise ConfChangeError(f"{vid} is in LearnersNext, but not Voters[1]")
        if prs[vid].is_learner:
            raise ConfChangeError(
                f"{vid} is in LearnersNext, but is already marked as learner"
            )
    for vid in cfg.learners:
        if vid in cfg.voters.outgoing:
            raise ConfChangeError(f"{vid} is in Learners and Voters[1]")
        if vid in cfg.voters.incoming:
            raise ConfChangeError(f"{vid} is in Learners and Voters[0]")
        if not prs[vid].is_learner:
            raise ConfChangeError(f"{vid} is in Learners, but is not marked as learner")
    if not _joint(cfg):
        if cfg.learners_next:
            raise ConfChangeError("cfg.LearnersNext must be nil when not joint")
        if cfg.auto_leave:
            raise ConfChangeError("AutoLeave must be false when not joint")


def _check_and_return(
    cfg: TrackerConfig, prs: Dict[int, Progress]
) -> Tuple[TrackerConfig, Dict[int, Progress]]:
    _check_invariants(cfg, prs)
    return cfg, prs


def to_conf_change_single(cs: ConfState) -> Tuple[List[ConfChangeSingle], List[ConfChangeSingle]]:
    """Translate a ConfState into (outgoing, incoming) op slices
    (ref: confchange/restore.go:26-100)."""
    out: List[ConfChangeSingle] = []
    in_: List[ConfChangeSingle] = []
    for vid in cs.voters_outgoing:
        out.append(ConfChangeSingle(ConfChangeType.ConfChangeAddNode, vid))
    for vid in cs.voters_outgoing:
        in_.append(ConfChangeSingle(ConfChangeType.ConfChangeRemoveNode, vid))
    for vid in cs.voters:
        in_.append(ConfChangeSingle(ConfChangeType.ConfChangeAddNode, vid))
    for vid in cs.learners:
        in_.append(ConfChangeSingle(ConfChangeType.ConfChangeAddLearnerNode, vid))
    for vid in cs.learners_next:
        in_.append(ConfChangeSingle(ConfChangeType.ConfChangeAddLearnerNode, vid))
    return out, in_


def restore(
    chg: Changer, cs: ConfState
) -> Tuple[TrackerConfig, Dict[int, Progress]]:
    """Rebuild a configuration from a ConfState
    (ref: confchange/restore.go:116-155)."""
    outgoing, incoming = to_conf_change_single(cs)

    tracker = chg.tracker

    def run(op):
        cfg, prs = op()
        tracker.config = cfg
        tracker.progress = prs

    if not outgoing:
        for cc in incoming:
            run(lambda cc=cc: Changer(tracker, chg.last_index).simple([cc]))
    else:
        # Build the outgoing config first as the active one, then rotate it
        # into place by entering the joint config with the incoming ops.
        for cc in outgoing:
            run(lambda cc=cc: Changer(tracker, chg.last_index).simple([cc]))
        run(lambda: Changer(tracker, chg.last_index).enter_joint(cs.auto_leave, incoming))
    return tracker.config, tracker.progress
