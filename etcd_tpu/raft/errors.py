"""Error types of the consensus core (ref: raft/storage.go:24-38, raft/raft.go:75,
raft/rawnode.go:24-29). String values must match the reference exactly: the
interaction-trace harness prints them verbatim."""


class RaftError(Exception):
    pass


class CompactedError(RaftError):
    def __str__(self) -> str:
        return "requested index is unavailable due to compaction"


class SnapOutOfDateError(RaftError):
    def __str__(self) -> str:
        return "requested index is older than the existing snapshot"


class UnavailableError(RaftError):
    def __str__(self) -> str:
        return "requested entry at index is unavailable"


class SnapshotTemporarilyUnavailableError(RaftError):
    def __str__(self) -> str:
        return "snapshot is temporarily unavailable"


class ProposalDroppedError(RaftError):
    def __str__(self) -> str:
        return "raft proposal dropped"


class StepLocalMsgError(RaftError):
    def __str__(self) -> str:
        return "raft: cannot step raft local message"


class StepPeerNotFoundError(RaftError):
    def __str__(self) -> str:
        return "raft: cannot step as peer not found"
