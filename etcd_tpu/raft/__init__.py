"""Single-group Raft consensus core, reference-semantics.

This package is the host-side oracle for the batched TPU engine: it
reproduces the observable behavior (message sequences, Ready contents, log
lines) of the reference implementation (ref: raft/raft.go and friends) and
must replay raft/testdata interaction traces bit-for-bit.

The hot arithmetic (quorum order statistics, vote tallies, log term
matching) is factored into small pure functions so the batched engine in
``etcd_tpu.batched`` can reuse the same definitions under vmap.
"""

from .types import (  # noqa: F401
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
    ConfState,
    ConfChange,
    ConfChangeV2,
    ConfChangeSingle,
    ConfChangeType,
    ConfChangeTransition,
    EMPTY_HARD_STATE,
    is_empty_hard_state,
    is_empty_snap,
)
from .errors import (  # noqa: F401
    CompactedError,
    UnavailableError,
    SnapOutOfDateError,
    SnapshotTemporarilyUnavailableError,
    ProposalDroppedError,
    StepLocalMsgError,
    StepPeerNotFoundError,
)
from .storage import MemoryStorage, Storage  # noqa: F401
from .raft import Config, Raft, StateType, ReadOnlyOption, NONE  # noqa: F401
from .rawnode import RawNode, Ready, SoftState, ReadState  # noqa: F401
