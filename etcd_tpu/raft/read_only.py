"""ReadIndex protocol bookkeeping (ref: raft/read_only.go).

In the batched engine the ack sets become ``[G, R]`` bitmasks and the
quorum check reuses the vote kernel; the request queue (keyed by opaque
request contexts) stays host-side since contexts are payload bytes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import Dict, List, Optional

from .types import Message


class ReadOnlyOption(IntEnum):
    # Linearizable via quorum heartbeat acks (default).
    ReadOnlySafe = 0
    # Linearizable via leader lease; affected by clock drift.
    ReadOnlyLeaseBased = 1


@dataclass
class ReadIndexStatus:
    req: Message
    index: int
    acks: Dict[int, bool] = field(default_factory=dict)


@dataclass
class ReadState:
    """ref: raft/read_only.go:24-27."""

    index: int
    request_ctx: bytes


class ReadOnly:
    def __init__(self, option: ReadOnlyOption):
        self.option = option
        self.pending_read_index: Dict[bytes, ReadIndexStatus] = {}
        self.read_index_queue: List[bytes] = []

    def add_request(self, index: int, m: Message) -> None:
        ctx = bytes(m.entries[0].data)
        if ctx in self.pending_read_index:
            return
        self.pending_read_index[ctx] = ReadIndexStatus(req=m, index=index)
        self.read_index_queue.append(ctx)

    def recv_ack(self, from_id: int, context: bytes) -> Dict[int, bool]:
        rs = self.pending_read_index.get(bytes(context))
        if rs is None:
            return {}
        rs.acks[from_id] = True
        return rs.acks

    def advance(self, m: Message) -> List[ReadIndexStatus]:
        """Dequeue requests up to and including the one matching m.Context
        (ref: read_only.go:81-112)."""
        ctx = bytes(m.context)
        rss: List[ReadIndexStatus] = []
        found = False
        i = 0
        for okctx in self.read_index_queue:
            i += 1
            rs = self.pending_read_index.get(okctx)
            if rs is None:
                raise RuntimeError("cannot find corresponding read state from pending map")
            rss.append(rs)
            if okctx == ctx:
                found = True
                break
        if found:
            self.read_index_queue = self.read_index_queue[i:]
            for rs in rss:
                del self.pending_read_index[bytes(rs.req.entries[0].data)]
            return rss
        return []

    def last_pending_request_ctx(self) -> bytes:
        if not self.read_index_queue:
            return b""
        return self.read_index_queue[-1]
