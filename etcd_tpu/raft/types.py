"""Wire types for the consensus core (ref: raft/raftpb/raft.proto).

These are plain Python dataclasses rather than protobufs: on the TPU path
messages are transposed into structure-of-arrays tensors (type, to, from,
term, logTerm, index, commit, reject as ``[G, M]`` int arrays) and payload
bytes live in a host arena, so the host object model only needs to be a
faithful carrier of the same fields. Conf-change payloads are serialized
with a protobuf-compatible varint encoding so that empty messages marshal
to empty bytes, matching the reference's round-trip behavior
(ref: raft/raftpb/confchange.go:170 MarshalConfChange).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from enum import IntEnum
from typing import List, Optional, Tuple


class MessageType(IntEnum):
    """ref: raft/raftpb/raft.pb.go:76-94 (19 message types)."""

    MsgHup = 0
    MsgBeat = 1
    MsgProp = 2
    MsgApp = 3
    MsgAppResp = 4
    MsgVote = 5
    MsgVoteResp = 6
    MsgSnap = 7
    MsgHeartbeat = 8
    MsgHeartbeatResp = 9
    MsgUnreachable = 10
    MsgSnapStatus = 11
    MsgCheckQuorum = 12
    MsgTransferLeader = 13
    MsgTimeoutNow = 14
    MsgReadIndex = 15
    MsgReadIndexResp = 16
    MsgPreVote = 17
    MsgPreVoteResp = 18

    def __str__(self) -> str:
        return self.name


class EntryType(IntEnum):
    EntryNormal = 0
    EntryConfChange = 1
    EntryConfChangeV2 = 2

    def __str__(self) -> str:
        return self.name


class ConfChangeType(IntEnum):
    ConfChangeAddNode = 0
    ConfChangeRemoveNode = 1
    ConfChangeUpdateNode = 2
    ConfChangeAddLearnerNode = 3

    def __str__(self) -> str:
        return self.name


class ConfChangeTransition(IntEnum):
    ConfChangeTransitionAuto = 0
    ConfChangeTransitionJointImplicit = 1
    ConfChangeTransitionJointExplicit = 2

    def __str__(self) -> str:
        return self.name


def _varint_size(x: int) -> int:
    n = 1
    while x >= 0x80:
        x >>= 7
        n += 1
    return n


@dataclass
class Entry:
    term: int = 0
    index: int = 0
    type: EntryType = EntryType.EntryNormal
    data: bytes = b""

    def size(self) -> int:
        """Marshaled proto size (ref: raftpb/raft.pb.go:1191 Entry.Size)."""
        n = 3 + _varint_size(self.type) + _varint_size(self.term) + _varint_size(self.index)
        if self.data:
            n += 1 + len(self.data) + _varint_size(len(self.data))
        return n

    def payload_size(self) -> int:
        """ref: raft/util.go PayloadSize — size of data only."""
        return len(self.data)

    def clone(self) -> "Entry":
        return replace(self)


@dataclass
class ConfState:
    """ref: raftpb/raft.proto ConfState."""

    voters: List[int] = field(default_factory=list)
    learners: List[int] = field(default_factory=list)
    voters_outgoing: List[int] = field(default_factory=list)
    learners_next: List[int] = field(default_factory=list)
    auto_leave: bool = False

    def equivalent(self, other: "ConfState") -> bool:
        """Compare after sorting (ref: raftpb/confstate.go Equivalent)."""
        return (
            sorted(self.voters) == sorted(other.voters)
            and sorted(self.learners) == sorted(other.learners)
            and sorted(self.voters_outgoing) == sorted(other.voters_outgoing)
            and sorted(self.learners_next) == sorted(other.learners_next)
            and self.auto_leave == other.auto_leave
        )

    def clone(self) -> "ConfState":
        return ConfState(
            voters=list(self.voters),
            learners=list(self.learners),
            voters_outgoing=list(self.voters_outgoing),
            learners_next=list(self.learners_next),
            auto_leave=self.auto_leave,
        )


@dataclass
class SnapshotMetadata:
    conf_state: ConfState = field(default_factory=ConfState)
    index: int = 0
    term: int = 0


@dataclass
class Snapshot:
    data: bytes = b""
    metadata: SnapshotMetadata = field(default_factory=SnapshotMetadata)


@dataclass
class Message:
    """ref: raftpb/raft.pb.go:384-402 Message fields."""

    type: MessageType = MessageType.MsgHup
    to: int = 0
    from_: int = 0
    term: int = 0
    log_term: int = 0
    index: int = 0
    entries: List[Entry] = field(default_factory=list)
    commit: int = 0
    snapshot: Snapshot = field(default_factory=Snapshot)
    reject: bool = False
    reject_hint: int = 0
    context: bytes = b""


@dataclass
class HardState:
    term: int = 0
    vote: int = 0
    commit: int = 0


EMPTY_HARD_STATE = HardState()


def is_empty_hard_state(hs: HardState) -> bool:
    return hs.term == 0 and hs.vote == 0 and hs.commit == 0


def is_empty_snap(s: Snapshot) -> bool:
    return s.metadata.index == 0


# --- Conf changes (ref: raftpb/confchange.go) ---------------------------------


@dataclass
class ConfChangeSingle:
    type: ConfChangeType = ConfChangeType.ConfChangeAddNode
    node_id: int = 0


@dataclass
class ConfChange:
    """V1 conf change: exactly one operation."""

    id: int = 0
    type: ConfChangeType = ConfChangeType.ConfChangeAddNode
    node_id: int = 0
    context: bytes = b""

    def as_v2(self) -> "ConfChangeV2":
        return ConfChangeV2(
            changes=[ConfChangeSingle(self.type, self.node_id)],
            context=self.context,
        )

    def as_v1(self) -> Tuple[Optional["ConfChange"], bool]:
        return self, True

    def marshal(self) -> bytes:
        return _encode_fields(
            (1, self.id), (2, int(self.type)), (3, self.node_id), (4, self.context)
        )

    @staticmethod
    def unmarshal(data: bytes) -> "ConfChange":
        cc = ConfChange()
        for tag, val in _decode_fields(data):
            if tag == 1:
                cc.id = val
            elif tag == 2:
                cc.type = ConfChangeType(val)
            elif tag == 3:
                cc.node_id = val
            elif tag == 4:
                cc.context = val
        return cc

    def go_str(self) -> str:
        """Go %v struct rendering, needed for trace-parity log lines."""
        return "{%d %s %d %s}" % (self.id, self.type, self.node_id, _go_bytes(self.context))


@dataclass
class ConfChangeV2:
    transition: ConfChangeTransition = ConfChangeTransition.ConfChangeTransitionAuto
    changes: List[ConfChangeSingle] = field(default_factory=list)
    context: bytes = b""

    def as_v2(self) -> "ConfChangeV2":
        return self

    def as_v1(self) -> Tuple[Optional[ConfChange], bool]:
        return None, False

    def enter_joint(self) -> Tuple[bool, bool]:
        """(autoLeave, useJoint) — ref: raftpb/confchange.go EnterJoint."""
        if (
            self.transition != ConfChangeTransition.ConfChangeTransitionAuto
            or len(self.changes) > 1
        ):
            auto_leave = self.transition in (
                ConfChangeTransition.ConfChangeTransitionAuto,
                ConfChangeTransition.ConfChangeTransitionJointImplicit,
            )
            return auto_leave, True
        return False, False

    def leave_joint(self) -> bool:
        """True if this is a zero-change request to leave a joint config."""
        return (
            self.transition == ConfChangeTransition.ConfChangeTransitionAuto
            and not self.changes
        )

    def marshal(self) -> bytes:
        parts = [_encode_fields((1, int(self.transition)))]
        for ch in self.changes:
            sub = _encode_fields((1, int(ch.type)), (2, ch.node_id))
            parts.append(_encode_len_field(2, sub))
        parts.append(_encode_fields((3, self.context)))
        return b"".join(parts)

    @staticmethod
    def unmarshal(data: bytes) -> "ConfChangeV2":
        cc = ConfChangeV2()
        for tag, val in _decode_fields(data):
            if tag == 1:
                cc.transition = ConfChangeTransition(val)
            elif tag == 2:
                single = ConfChangeSingle()
                for stag, sval in _decode_fields(val):
                    if stag == 1:
                        single.type = ConfChangeType(sval)
                    elif stag == 2:
                        single.node_id = sval
                cc.changes.append(single)
            elif tag == 3:
                cc.context = val
        return cc

    def go_str(self) -> str:
        changes = " ".join("{%s %d}" % (c.type, c.node_id) for c in self.changes)
        return "{%s [%s] %s}" % (self.transition, changes, _go_bytes(self.context))


def _go_bytes(b: bytes) -> str:
    """Go %v of a []byte: space-separated decimal values in brackets."""
    return "[" + " ".join(str(x) for x in b) + "]"


def _encode_varint(x: int) -> bytes:
    out = bytearray()
    while x >= 0x80:
        out.append((x & 0x7F) | 0x80)
        x >>= 7
    out.append(x)
    return bytes(out)


def _encode_fields(*fields_: Tuple[int, object]) -> bytes:
    """Encode (tag, value) pairs, omitting zero/empty values."""
    out = bytearray()
    for tag, val in fields_:
        if isinstance(val, bytes):
            if val:
                out += _encode_varint(tag << 3 | 2)
                out += _encode_varint(len(val))
                out += val
        else:
            if val:
                out += _encode_varint(tag << 3 | 0)
                out += _encode_varint(int(val))
    return bytes(out)


def _encode_len_field(tag: int, payload: bytes) -> bytes:
    return _encode_varint(tag << 3 | 2) + _encode_varint(len(payload)) + payload


def _decode_fields(data: bytes):
    i, n = 0, len(data)
    while i < n:
        key, i = _decode_varint(data, i)
        tag, wire = key >> 3, key & 7
        if wire == 0:
            val, i = _decode_varint(data, i)
            yield tag, val
        elif wire == 2:
            ln, i = _decode_varint(data, i)
            yield tag, data[i : i + ln]
            i += ln
        else:
            raise ValueError(f"unsupported wire type {wire}")


def _decode_varint(data: bytes, i: int) -> Tuple[int, int]:
    shift, val = 0, 0
    while True:
        b = data[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def conf_changes_from_string(s: str) -> List[ConfChangeSingle]:
    """Parse 'v1 l2 r3 u4' notation (ref: raftpb/confchange.go ConfChangesFromString)."""
    ccs: List[ConfChangeSingle] = []
    toks = s.strip().split()
    kinds = {
        "v": ConfChangeType.ConfChangeAddNode,
        "l": ConfChangeType.ConfChangeAddLearnerNode,
        "r": ConfChangeType.ConfChangeRemoveNode,
        "u": ConfChangeType.ConfChangeUpdateNode,
    }
    for tok in toks:
        if len(tok) < 2 or tok[0] not in kinds:
            raise ValueError(f"unknown token {tok}")
        ccs.append(ConfChangeSingle(kinds[tok[0]], int(tok[1:])))
    return ccs


def conf_changes_to_string(ccs: List[ConfChangeSingle]) -> str:
    rev = {
        ConfChangeType.ConfChangeAddNode: "v",
        ConfChangeType.ConfChangeAddLearnerNode: "l",
        ConfChangeType.ConfChangeRemoveNode: "r",
        ConfChangeType.ConfChangeUpdateNode: "u",
    }
    return " ".join(f"{rev[c.type]}{c.node_id}" for c in ccs)
