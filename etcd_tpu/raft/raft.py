"""The Raft state machine (ref: raft/raft.go).

This is the single-group, message-in/message-out oracle. It is written as
a self-contained state machine with no I/O and abstract tick-based time,
exactly like the reference, so that the batched TPU engine
(``etcd_tpu.batched``) can be differentially tested against it: both
consume the same Message stream and must produce identical HardState /
commit-index / outbound-message sequences for the hot-path message types.

Log lines are part of the observable contract (trace parity), so format
strings mirror the reference byte-for-byte; citations give file:line into
the reference tree.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field as dataclass_field
from enum import IntEnum
from typing import Callable, List, Optional

from . import confchange as confchange_mod
from .errors import (
    CompactedError,
    ProposalDroppedError,
    RaftError,
    SnapshotTemporarilyUnavailableError,
    UnavailableError,
)
from .log import NO_LIMIT, RaftLog
from .logger import Logger, get_logger
from .quorum import VoteResult
from .read_only import ReadOnly, ReadOnlyOption, ReadState
from .storage import Storage
from .tracker import (
    Progress,
    ProgressTracker,
    StateProbe,
    StateReplicate,
    StateSnapshot,
    progress_map_str,
)
from .types import (
    ConfChange,
    ConfChangeV2,
    ConfState,
    Entry,
    EntryType,
    HardState,
    Message,
    MessageType,
    Snapshot,
    is_empty_hard_state,
    is_empty_snap,
)

NONE = 0  # placeholder node ID when there is no leader


class StateType(IntEnum):
    StateFollower = 0
    StateCandidate = 1
    StateLeader = 2
    StatePreCandidate = 3

    def __str__(self) -> str:
        return self.name


CAMPAIGN_PRE_ELECTION = "CampaignPreElection"
CAMPAIGN_ELECTION = "CampaignElection"
CAMPAIGN_TRANSFER = "CampaignTransfer"


@dataclass
class SoftState:
    """Volatile state useful for logging/debugging (ref: raft/node.go:60-68)."""

    lead: int = NONE
    raft_state: StateType = StateType.StateFollower

    def equal(self, other: "SoftState") -> bool:
        return self.lead == other.lead and self.raft_state == other.raft_state


def is_local_msg(t: MessageType) -> bool:
    return t in (
        MessageType.MsgHup,
        MessageType.MsgBeat,
        MessageType.MsgUnreachable,
        MessageType.MsgSnapStatus,
        MessageType.MsgCheckQuorum,
    )


def is_response_msg(t: MessageType) -> bool:
    return t in (
        MessageType.MsgAppResp,
        MessageType.MsgVoteResp,
        MessageType.MsgHeartbeatResp,
        MessageType.MsgUnreachable,
        MessageType.MsgPreVoteResp,
    )


def vote_resp_msg_type(t: MessageType) -> MessageType:
    if t == MessageType.MsgVote:
        return MessageType.MsgVoteResp
    if t == MessageType.MsgPreVote:
        return MessageType.MsgPreVoteResp
    raise ValueError(f"not a vote message: {t}")


_global_rand = random.Random()


@dataclass
class Config:
    """Parameters to start a raft instance (ref: raft/raft.go:116-199)."""

    id: int = 0
    election_tick: int = 0
    heartbeat_tick: int = 0
    storage: Optional[Storage] = None
    applied: int = 0
    max_size_per_msg: int = 0
    max_committed_size_per_ready: int = 0
    max_uncommitted_entries_size: int = 0
    max_inflight_msgs: int = 0
    check_quorum: bool = False
    pre_vote: bool = False
    read_only_option: ReadOnlyOption = ReadOnlyOption.ReadOnlySafe
    logger: Optional[Logger] = None
    disable_proposal_forwarding: bool = False
    # Deterministic substitute for the reference's global lockedRand; tests
    # can inject a seeded Random.
    rand: Optional[random.Random] = None

    def validate(self) -> None:
        if self.id == NONE:
            raise ValueError("cannot use none as id")
        if self.heartbeat_tick <= 0:
            raise ValueError("heartbeat tick must be greater than 0")
        if self.election_tick <= self.heartbeat_tick:
            raise ValueError("election tick must be greater than heartbeat tick")
        if self.storage is None:
            raise ValueError("storage cannot be nil")
        if self.max_uncommitted_entries_size == 0:
            self.max_uncommitted_entries_size = NO_LIMIT
        if self.max_committed_size_per_ready == 0:
            self.max_committed_size_per_ready = self.max_size_per_msg
        if self.max_inflight_msgs <= 0:
            raise ValueError("max inflight messages must be greater than 0")
        if self.logger is None:
            self.logger = get_logger()
        if (
            self.read_only_option == ReadOnlyOption.ReadOnlyLeaseBased
            and not self.check_quorum
        ):
            raise ValueError(
                "CheckQuorum must be enabled when ReadOnlyOption is ReadOnlyLeaseBased"
            )


class Raft:
    """ref: raft/raft.go:243-316."""

    def __init__(self, c: Config):
        c.validate()
        raftlog = RaftLog(c.storage, c.logger, c.max_committed_size_per_ready)
        hs, cs = c.storage.initial_state()

        self.id = c.id
        self.term = 0
        self.vote = NONE
        self.read_states: List[ReadState] = []
        self.raft_log = raftlog
        self.max_msg_size = c.max_size_per_msg
        self.max_uncommitted_size = c.max_uncommitted_entries_size
        self.prs = ProgressTracker(c.max_inflight_msgs)
        self.state: StateType = StateType.StateFollower
        self.is_learner = False
        self.msgs: List[Message] = []
        self.lead = NONE
        self.lead_transferee = NONE
        self.pending_conf_index = 0
        self.uncommitted_size = 0
        self.read_only = ReadOnly(c.read_only_option)
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self.check_quorum = c.check_quorum
        self.pre_vote = c.pre_vote
        self.heartbeat_timeout = c.heartbeat_tick
        self.election_timeout = c.election_tick
        self.randomized_election_timeout = 0
        self.disable_proposal_forwarding = c.disable_proposal_forwarding
        self.logger: Logger = c.logger
        self.rand = c.rand if c.rand is not None else _global_rand
        self.pending_read_index_messages: List[Message] = []

        self.tick: Callable[[], None] = self.tick_election
        self.step_fn: Callable[[Raft, Message], None] = step_follower

        cfg, prs = confchange_mod.restore(
            confchange_mod.Changer(self.prs, raftlog.last_index()), cs
        )
        cs2 = self.switch_to_config(cfg, prs)
        if not cs.equivalent(cs2):
            self.logger.panicf("ConfStates not equivalent: %s vs %s", cs, cs2)

        if not is_empty_hard_state(hs):
            self.load_state(hs)
        if c.applied > 0:
            raftlog.applied_to(c.applied)
        self.become_follower(self.term, NONE)

        nodes_strs = ",".join(format(n, "x") for n in self.prs.voter_nodes())
        self.logger.infof(
            "newRaft %x [peers: [%s], term: %d, commit: %d, applied: %d, "
            "lastindex: %d, lastterm: %d]",
            self.id, nodes_strs, self.term, self.raft_log.committed,
            self.raft_log.applied, self.raft_log.last_index(),
            self.raft_log.last_term(),
        )

    # -- state snapshots ------------------------------------------------------

    def has_leader(self) -> bool:
        return self.lead != NONE

    def soft_state(self) -> SoftState:
        return SoftState(lead=self.lead, raft_state=self.state)

    def hard_state(self) -> HardState:
        return HardState(term=self.term, vote=self.vote, commit=self.raft_log.committed)

    # -- sending --------------------------------------------------------------

    def send(self, m: Message) -> None:
        """Queue m for the next Ready; persistence happens first
        (ref: raft.go:384-419)."""
        if m.from_ == NONE:
            m.from_ = self.id
        if m.type in (
            MessageType.MsgVote,
            MessageType.MsgVoteResp,
            MessageType.MsgPreVote,
            MessageType.MsgPreVoteResp,
        ):
            if m.term == 0:
                # Campaign messages carry the term they campaign for; the
                # pre-vote variants carry a future term.
                raise RuntimeError(f"term should be set when sending {m.type}")
        else:
            if m.term != 0:
                raise RuntimeError(
                    f"term should not be set when sending {m.type} (was {m.term})"
                )
            # MsgProp and MsgReadIndex are forwarded to the leader and act
            # as local messages; they carry no term.
            if m.type not in (MessageType.MsgProp, MessageType.MsgReadIndex):
                m.term = self.term
        self.msgs.append(m)

    def send_append(self, to: int) -> None:
        self.maybe_send_append(to, send_if_empty=True)

    def maybe_send_append(self, to: int, send_if_empty: bool) -> bool:
        """Send an append (or snapshot) to `to` if useful
        (ref: raft.go:432-492)."""
        pr = self.prs.progress[to]
        if pr.is_paused():
            return False
        m = Message(to=to)

        term_err = ents_err = None
        term = 0
        ents: List[Entry] = []
        try:
            term = self.raft_log.term(pr.next - 1)
        except (CompactedError, UnavailableError) as e:
            term_err = e
        try:
            ents = self.raft_log.entries(pr.next, self.max_msg_size)
        except CompactedError as e:
            # NB: UnavailableError from slice() is a panic in the reference
            # (log.go:357) and propagates here too.
            ents_err = e
        if not ents and not send_if_empty:
            return False

        if term_err is not None or ents_err is not None:
            # The follower's tail is compacted away: fall back to a snapshot.
            if not pr.recent_active:
                self.logger.debugf(
                    "ignore sending snapshot to %x since it is not recently active", to
                )
                return False
            m.type = MessageType.MsgSnap
            try:
                snapshot = self.raft_log.snapshot()
            except SnapshotTemporarilyUnavailableError:
                self.logger.debugf(
                    "%x failed to send snapshot to %x because snapshot is "
                    "temporarily unavailable",
                    self.id, to,
                )
                return False
            if is_empty_snap(snapshot):
                raise RuntimeError("need non-empty snapshot")
            m.snapshot = snapshot
            sindex, sterm = snapshot.metadata.index, snapshot.metadata.term
            self.logger.debugf(
                "%x [firstindex: %d, commit: %d] sent snapshot[index: %d, term: %d] to %x [%s]",
                self.id, self.raft_log.first_index(), self.raft_log.committed,
                sindex, sterm, to, pr,
            )
            pr.become_snapshot(sindex)
            self.logger.debugf(
                "%x paused sending replication messages to %x [%s]", self.id, to, pr
            )
        else:
            m.type = MessageType.MsgApp
            m.index = pr.next - 1
            m.log_term = term
            m.entries = ents
            m.commit = self.raft_log.committed
            if m.entries:
                if pr.state == StateReplicate:
                    last = m.entries[-1].index
                    pr.optimistic_update(last)
                    pr.inflights.add(last)
                elif pr.state == StateProbe:
                    pr.probe_sent = True
                else:
                    self.logger.panicf(
                        "%x is sending append in unhandled state %s", self.id, pr.state
                    )
        self.send(m)
        return True

    def send_heartbeat(self, to: int, ctx: bytes) -> None:
        """ref: raft.go:495-511 — commit is clamped to the follower's match."""
        commit = min(self.prs.progress[to].match, self.raft_log.committed)
        self.send(
            Message(to=to, type=MessageType.MsgHeartbeat, commit=commit, context=ctx)
        )

    def bcast_append(self) -> None:
        def f(vid: int, _pr: Progress) -> None:
            if vid == self.id:
                return
            self.send_append(vid)

        self.prs.visit(f)

    def bcast_heartbeat(self) -> None:
        last_ctx = self.read_only.last_pending_request_ctx()
        self.bcast_heartbeat_with_ctx(last_ctx if last_ctx else b"")

    def bcast_heartbeat_with_ctx(self, ctx: bytes) -> None:
        def f(vid: int, _pr: Progress) -> None:
            if vid == self.id:
                return
            self.send_heartbeat(vid, ctx)

        self.prs.visit(f)

    # -- Ready/advance --------------------------------------------------------

    def advance(self, rd) -> None:
        """Commit the effects of a handled Ready (ref: raft.go:543-580)."""
        self.reduce_uncommitted_size(rd.committed_entries)

        new_applied = rd.applied_cursor()
        if new_applied > 0:
            old_applied = self.raft_log.applied
            self.raft_log.applied_to(new_applied)

            if (
                self.prs.config.auto_leave
                and old_applied <= self.pending_conf_index <= new_applied
                and self.state == StateType.StateLeader
            ):
                # Auto-leave the joint configuration: propose an empty
                # ConfChangeV2 (nil data can never be size-refused).
                ent = Entry(type=EntryType.EntryConfChangeV2, data=b"")
                if not self.append_entry([ent]):
                    raise RuntimeError("refused un-refusable auto-leaving ConfChangeV2")
                self.pending_conf_index = self.raft_log.last_index()
                self.logger.infof(
                    "initiating automatic transition out of joint configuration %s",
                    self.prs.config,
                )

        if rd.entries:
            e = rd.entries[-1]
            self.raft_log.stable_to(e.index, e.term)
        if not is_empty_snap(rd.snapshot):
            self.raft_log.stable_snap_to(rd.snapshot.metadata.index)

    def maybe_commit(self) -> bool:
        """Advance the commit index from quorum acks (ref: raft.go:585-588).

        This — prs.committed() feeding raft_log.maybe_commit — is the
        replica-axis reduction kernel of the batched engine.
        """
        mci = self.prs.committed()
        return self.raft_log.maybe_commit(mci, self.term)

    def reset(self, term: int) -> None:
        if self.term != term:
            self.term = term
            self.vote = NONE
        self.lead = NONE
        self.election_elapsed = 0
        self.heartbeat_elapsed = 0
        self.reset_randomized_election_timeout()
        self.abort_leader_transfer()

        self.prs.reset_votes()

        def f(vid: int, pr: Progress) -> None:
            from .tracker import Inflights

            is_learner = pr.is_learner
            new_pr = Progress(
                match=0,
                next=self.raft_log.last_index() + 1,
                inflights=Inflights(self.prs.max_inflight),
                is_learner=is_learner,
            )
            if vid == self.id:
                new_pr.match = self.raft_log.last_index()
            # In-place replacement, preserving identity within the map.
            pr.__dict__.update(new_pr.__dict__)

        self.prs.visit(f)

        self.pending_conf_index = 0
        self.uncommitted_size = 0
        self.read_only = ReadOnly(self.read_only.option)

    def append_entry(self, es: List[Entry]) -> bool:
        """ref: raft.go:621-642."""
        li = self.raft_log.last_index()
        for i, e in enumerate(es):
            e.term = self.term
            e.index = li + 1 + i
        if not self.increase_uncommitted_size(es):
            self.logger.debugf(
                "%x appending new entries to log would exceed uncommitted entry "
                "size limit; dropping proposal",
                self.id,
            )
            return False
        li = self.raft_log.append(es)
        self.prs.progress[self.id].maybe_update(li)
        # The caller is responsible for bcast_append regardless.
        self.maybe_commit()
        return True

    # -- ticks ----------------------------------------------------------------

    def tick_election(self) -> None:
        """Followers and candidates (ref: raft.go:645-654)."""
        self.election_elapsed += 1
        if self.promotable() and self.past_election_timeout():
            self.election_elapsed = 0
            try:
                self.step(Message(from_=self.id, type=MessageType.MsgHup))
            except RaftError as e:
                self.logger.debugf("error occurred during election: %s", e)

    def tick_heartbeat(self) -> None:
        """Leaders (ref: raft.go:657-684)."""
        self.heartbeat_elapsed += 1
        self.election_elapsed += 1

        if self.election_elapsed >= self.election_timeout:
            self.election_elapsed = 0
            if self.check_quorum:
                try:
                    self.step(Message(from_=self.id, type=MessageType.MsgCheckQuorum))
                except RaftError as e:
                    self.logger.debugf(
                        "error occurred during checking sending heartbeat: %s", e
                    )
            # A leader that can't finish a transfer within an election
            # timeout resumes normal operation.
            if self.state == StateType.StateLeader and self.lead_transferee != NONE:
                self.abort_leader_transfer()

        if self.state != StateType.StateLeader:
            return

        if self.heartbeat_elapsed >= self.heartbeat_timeout:
            self.heartbeat_elapsed = 0
            try:
                self.step(Message(from_=self.id, type=MessageType.MsgBeat))
            except RaftError as e:
                self.logger.debugf(
                    "error occurred during checking sending heartbeat: %s", e
                )

    # -- role transitions -----------------------------------------------------

    def become_follower(self, term: int, lead: int) -> None:
        self.step_fn = step_follower
        self.reset(term)
        self.tick = self.tick_election
        self.lead = lead
        self.state = StateType.StateFollower
        self.logger.infof("%x became follower at term %d", self.id, self.term)

    def become_candidate(self) -> None:
        if self.state == StateType.StateLeader:
            raise RuntimeError("invalid transition [leader -> candidate]")
        self.step_fn = step_candidate
        self.reset(self.term + 1)
        self.tick = self.tick_election
        self.vote = self.id
        self.state = StateType.StateCandidate
        self.logger.infof("%x became candidate at term %d", self.id, self.term)

    def become_pre_candidate(self) -> None:
        if self.state == StateType.StateLeader:
            raise RuntimeError("invalid transition [leader -> pre-candidate]")
        # Pre-candidacy changes step/tick/state but neither Term nor Vote.
        self.step_fn = step_candidate
        self.prs.reset_votes()
        self.tick = self.tick_election
        self.lead = NONE
        self.state = StateType.StatePreCandidate
        self.logger.infof("%x became pre-candidate at term %d", self.id, self.term)

    def become_leader(self) -> None:
        if self.state == StateType.StateFollower:
            raise RuntimeError("invalid transition [follower -> leader]")
        self.step_fn = step_leader
        self.reset(self.term)
        self.tick = self.tick_heartbeat
        self.lead = self.id
        self.state = StateType.StateLeader
        self.prs.progress[self.id].become_replicate()

        # Conservatively gate conf-change proposals until the log tail is
        # committed; scanning the tail would be more precise but costly.
        self.pending_conf_index = self.raft_log.last_index()

        empty_ent = Entry(data=b"")
        if not self.append_entry([empty_ent]):
            self.logger.panicf("empty entry was dropped")
        # The initial empty entry doesn't count against the uncommitted
        # quota: one over-quota entry is allowed when usage is zero.
        self.reduce_uncommitted_size([empty_ent])
        self.logger.infof("%x became leader at term %d", self.id, self.term)

    def hup(self, t: str) -> None:
        """ref: raft.go:760-781."""
        if self.state == StateType.StateLeader:
            self.logger.debugf("%x ignoring MsgHup because already leader", self.id)
            return
        if not self.promotable():
            self.logger.warningf("%x is unpromotable and can not campaign", self.id)
            return
        try:
            ents = self.raft_log.slice(
                self.raft_log.applied + 1, self.raft_log.committed + 1, NO_LIMIT
            )
        except Exception as e:
            self.logger.panicf("unexpected error getting unapplied entries (%s)", e)
        n = num_of_pending_conf(ents)
        if n != 0 and self.raft_log.committed > self.raft_log.applied:
            self.logger.warningf(
                "%x cannot campaign at term %d since there are still %d pending "
                "configuration changes to apply",
                self.id, self.term, n,
            )
            return
        self.logger.infof("%x is starting a new election at term %d", self.id, self.term)
        self.campaign(t)

    def campaign(self, t: str) -> None:
        """ref: raft.go:785-835."""
        if not self.promotable():
            self.logger.warningf(
                "%x is unpromotable; campaign() should have been called", self.id
            )
        if t == CAMPAIGN_PRE_ELECTION:
            self.become_pre_candidate()
            vote_msg = MessageType.MsgPreVote
            # Pre-vote RPCs carry the next term without bumping self.term.
            term = self.term + 1
        else:
            self.become_candidate()
            vote_msg = MessageType.MsgVote
            term = self.term
        _, _, res = self.poll(self.id, vote_resp_msg_type(vote_msg), True)
        if res == VoteResult.VoteWon:
            # Single-node quorum: advance immediately.
            if t == CAMPAIGN_PRE_ELECTION:
                self.campaign(CAMPAIGN_ELECTION)
            else:
                self.become_leader()
            return
        ids = sorted(self.prs.voters.ids())
        for vid in ids:
            if vid == self.id:
                continue
            self.logger.infof(
                "%x [logterm: %d, index: %d] sent %s request to %x at term %d",
                self.id, self.raft_log.last_term(), self.raft_log.last_index(),
                vote_msg, vid, self.term,
            )
            ctx = t.encode() if t == CAMPAIGN_TRANSFER else b""
            self.send(
                Message(
                    term=term,
                    to=vid,
                    type=vote_msg,
                    index=self.raft_log.last_index(),
                    log_term=self.raft_log.last_term(),
                    context=ctx,
                )
            )

    def poll(self, vid: int, t: MessageType, v: bool):
        if v:
            self.logger.infof("%x received %s from %x at term %d", self.id, t, vid, self.term)
        else:
            self.logger.infof(
                "%x received %s rejection from %x at term %d", self.id, t, vid, self.term
            )
        self.prs.record_vote(vid, v)
        return self.prs.tally_votes()

    # -- stepping -------------------------------------------------------------

    def step(self, m: Message) -> None:
        """Top-level message handling incl. term logic (ref: raft.go:847-987)."""
        if m.term == 0:
            pass  # local message
        elif m.term > self.term:
            if m.type in (MessageType.MsgVote, MessageType.MsgPreVote):
                force = bytes(m.context) == CAMPAIGN_TRANSFER.encode()
                in_lease = (
                    self.check_quorum
                    and self.lead != NONE
                    and self.election_elapsed < self.election_timeout
                )
                if not force and in_lease:
                    # Within the lease period we neither bump our term nor
                    # grant the vote.
                    self.logger.infof(
                        "%x [logterm: %d, index: %d, vote: %x] ignored %s from %x "
                        "[logterm: %d, index: %d] at term %d: lease is not expired "
                        "(remaining ticks: %d)",
                        self.id, self.raft_log.last_term(), self.raft_log.last_index(),
                        self.vote, m.type, m.from_, m.log_term, m.index, self.term,
                        self.election_timeout - self.election_elapsed,
                    )
                    return
            if m.type == MessageType.MsgPreVote:
                pass  # never change term in response to a pre-vote
            elif m.type == MessageType.MsgPreVoteResp and not m.reject:
                # A granted pre-vote carries our own future term; the term
                # bump happens when the quorum is in.
                pass
            else:
                self.logger.infof(
                    "%x [term: %d] received a %s message with higher term from %x [term: %d]",
                    self.id, self.term, m.type, m.from_, m.term,
                )
                if m.type in (
                    MessageType.MsgApp,
                    MessageType.MsgHeartbeat,
                    MessageType.MsgSnap,
                ):
                    self.become_follower(m.term, m.from_)
                else:
                    self.become_follower(m.term, NONE)
        elif m.term < self.term:
            if (self.check_quorum or self.pre_vote) and m.type in (
                MessageType.MsgHeartbeat,
                MessageType.MsgApp,
            ):
                # A removed node's stale leader traffic gets an empty
                # MsgAppResp to nudge it toward the current term without
                # disruptive term bumps (ref: raft.go:884-906).
                self.send(Message(to=m.from_, type=MessageType.MsgAppResp))
            elif m.type == MessageType.MsgPreVote:
                self.logger.infof(
                    "%x [logterm: %d, index: %d, vote: %x] rejected %s from %x "
                    "[logterm: %d, index: %d] at term %d",
                    self.id, self.raft_log.last_term(), self.raft_log.last_index(),
                    self.vote, m.type, m.from_, m.log_term, m.index, self.term,
                )
                self.send(
                    Message(
                        to=m.from_,
                        term=self.term,
                        type=MessageType.MsgPreVoteResp,
                        reject=True,
                    )
                )
            else:
                self.logger.infof(
                    "%x [term: %d] ignored a %s message with lower term from %x [term: %d]",
                    self.id, self.term, m.type, m.from_, m.term,
                )
            return

        if m.type == MessageType.MsgHup:
            self.hup(CAMPAIGN_PRE_ELECTION if self.pre_vote else CAMPAIGN_ELECTION)
        elif m.type in (MessageType.MsgVote, MessageType.MsgPreVote):
            # Vote if repeating a prior vote, if we have no vote and know of
            # no leader this term, or for a future-term pre-vote...
            can_vote = (
                self.vote == m.from_
                or (self.vote == NONE and self.lead == NONE)
                or (m.type == MessageType.MsgPreVote and m.term > self.term)
            )
            # ...and only for an up-to-date candidate. NB: learners must be
            # allowed to vote — they may be voters who haven't yet applied
            # their own promotion (ref: raft.go:938-956).
            if can_vote and self.raft_log.is_up_to_date(m.index, m.log_term):
                self.logger.infof(
                    "%x [logterm: %d, index: %d, vote: %x] cast %s for %x "
                    "[logterm: %d, index: %d] at term %d",
                    self.id, self.raft_log.last_term(), self.raft_log.last_index(),
                    self.vote, m.type, m.from_, m.log_term, m.index, self.term,
                )
                # Respond with the term from the message, not the local term:
                # pre-vote grants keep the local term unchanged.
                self.send(
                    Message(to=m.from_, term=m.term, type=vote_resp_msg_type(m.type))
                )
                if m.type == MessageType.MsgVote:
                    self.election_elapsed = 0
                    self.vote = m.from_
            else:
                self.logger.infof(
                    "%x [logterm: %d, index: %d, vote: %x] rejected %s from %x "
                    "[logterm: %d, index: %d] at term %d",
                    self.id, self.raft_log.last_term(), self.raft_log.last_index(),
                    self.vote, m.type, m.from_, m.log_term, m.index, self.term,
                )
                self.send(
                    Message(
                        to=m.from_,
                        term=self.term,
                        type=vote_resp_msg_type(m.type),
                        reject=True,
                    )
                )
        else:
            self.step_fn(self, m)

    # -- message handlers -----------------------------------------------------

    def handle_append_entries(self, m: Message) -> None:
        """ref: raft.go:1475-1511."""
        if m.index < self.raft_log.committed:
            self.send(
                Message(to=m.from_, type=MessageType.MsgAppResp,
                        index=self.raft_log.committed)
            )
            return
        mlast_index, ok = self.raft_log.maybe_append(m.index, m.log_term, m.commit, m.entries)
        if ok:
            self.send(Message(to=m.from_, type=MessageType.MsgAppResp, index=mlast_index))
        else:
            self.logger.debugf(
                "%x [logterm: %d, index: %d] rejected MsgApp [logterm: %d, index: %d] from %x",
                self.id, self.raft_log.zero_term_on_err_compacted(m.index), m.index,
                m.log_term, m.index, m.from_,
            )
            # Hint the leader at the largest (index, term) pair that could
            # possibly still match, skipping the divergent uncommitted tail
            # in one round trip (ref: raft.go:1487-1509).
            hint_index = min(m.index, self.raft_log.last_index())
            hint_index = self.raft_log.find_conflict_by_term(hint_index, m.log_term)
            hint_term = self.raft_log.term(hint_index)
            self.send(
                Message(
                    to=m.from_,
                    type=MessageType.MsgAppResp,
                    index=m.index,
                    reject=True,
                    reject_hint=hint_index,
                    log_term=hint_term,
                )
            )

    def handle_heartbeat(self, m: Message) -> None:
        self.raft_log.commit_to(m.commit)
        self.send(
            Message(to=m.from_, type=MessageType.MsgHeartbeatResp, context=m.context)
        )

    def handle_snapshot(self, m: Message) -> None:
        sindex, sterm = m.snapshot.metadata.index, m.snapshot.metadata.term
        if self.restore(m.snapshot):
            self.logger.infof(
                "%x [commit: %d] restored snapshot [index: %d, term: %d]",
                self.id, self.raft_log.committed, sindex, sterm,
            )
            self.send(
                Message(to=m.from_, type=MessageType.MsgAppResp,
                        index=self.raft_log.last_index())
            )
        else:
            self.logger.infof(
                "%x [commit: %d] ignored snapshot [index: %d, term: %d]",
                self.id, self.raft_log.committed, sindex, sterm,
            )
            self.send(
                Message(to=m.from_, type=MessageType.MsgAppResp,
                        index=self.raft_log.committed)
            )

    def restore(self, s: Snapshot) -> bool:
        """Apply a snapshot: log + configuration (ref: raft.go:1534-1614)."""
        if s.metadata.index <= self.raft_log.committed:
            return False
        if self.state != StateType.StateFollower:
            # Defense-in-depth; shouldn't fire (ref: raft.go:1538-1549).
            self.logger.warningf(
                "%x attempted to restore snapshot as leader; should never happen",
                self.id,
            )
            self.become_follower(self.term + 1, NONE)
            return False

        cs = s.metadata.conf_state
        found = self.id in (
            set(cs.voters) | set(cs.learners) | set(cs.voters_outgoing)
        )
        if not found:
            self.logger.warningf(
                "%x attempted to restore snapshot but it is not in the ConfState %s; "
                "should never happen",
                self.id, cs,
            )
            return False

        if self.raft_log.match_term(s.metadata.index, s.metadata.term):
            self.logger.infof(
                "%x [commit: %d, lastindex: %d, lastterm: %d] fast-forwarded commit "
                "to snapshot [index: %d, term: %d]",
                self.id, self.raft_log.committed, self.raft_log.last_index(),
                self.raft_log.last_term(), s.metadata.index, s.metadata.term,
            )
            self.raft_log.commit_to(s.metadata.index)
            return False

        self.raft_log.restore(s)

        self.prs = ProgressTracker(self.prs.max_inflight)
        cfg, prs = confchange_mod.restore(
            confchange_mod.Changer(self.prs, self.raft_log.last_index()), cs
        )
        cs2 = self.switch_to_config(cfg, prs)
        if not cs.equivalent(cs2):
            self.logger.panicf("ConfStates not equivalent: %s vs %s", cs, cs2)

        pr = self.prs.progress[self.id]
        pr.maybe_update(pr.next - 1)

        self.logger.infof(
            "%x [commit: %d, lastindex: %d, lastterm: %d] restored snapshot "
            "[index: %d, term: %d]",
            self.id, self.raft_log.committed, self.raft_log.last_index(),
            self.raft_log.last_term(), s.metadata.index, s.metadata.term,
        )
        return True

    def promotable(self) -> bool:
        """Can this node be leader? (ref: raft.go:1618-1621)."""
        pr = self.prs.progress.get(self.id)
        return (
            pr is not None
            and not pr.is_learner
            and not self.raft_log.has_pending_snapshot()
        )

    def apply_conf_change(self, cc: ConfChangeV2) -> ConfState:
        changer = confchange_mod.Changer(self.prs, self.raft_log.last_index())
        if cc.leave_joint():
            cfg, prs = changer.leave_joint()
        else:
            auto_leave, ok = cc.enter_joint()
            if ok:
                cfg, prs = changer.enter_joint(auto_leave, cc.changes)
            else:
                cfg, prs = changer.simple(cc.changes)
        return self.switch_to_config(cfg, prs)

    def switch_to_config(self, cfg, prs) -> ConfState:
        """Install a new configuration (ref: raft.go:1651-1700)."""
        self.prs.config = cfg
        self.prs.progress = prs

        self.logger.infof("%x switched to configuration %s", self.id, self.prs.config)
        cs = self.prs.conf_state()
        pr = self.prs.progress.get(self.id)
        self.is_learner = pr is not None and pr.is_learner

        if (pr is None or self.is_learner) and self.state == StateType.StateLeader:
            # The leader was removed or demoted; hold off on anything else
            # until it steps down.
            return cs

        if self.state != StateType.StateLeader or len(cs.voters) == 0:
            return cs

        if self.maybe_commit():
            # The config change may lower the quorum size and commit
            # entries; tell everyone.
            self.bcast_append()
        else:
            # Probe newly added replicas right away.
            def f(vid: int, _pr: Progress) -> None:
                self.maybe_send_append(vid, send_if_empty=False)

            self.prs.visit(f)

        if self.lead_transferee != 0 and self.lead_transferee not in self.prs.voters.ids():
            self.abort_leader_transfer()
        return cs

    def load_state(self, state: HardState) -> None:
        if state.commit < self.raft_log.committed or state.commit > self.raft_log.last_index():
            self.logger.panicf(
                "%x state.commit %d is out of range [%d, %d]",
                self.id, state.commit, self.raft_log.committed, self.raft_log.last_index(),
            )
        self.raft_log.committed = state.commit
        self.term = state.term
        self.vote = state.vote

    def past_election_timeout(self) -> bool:
        return self.election_elapsed >= self.randomized_election_timeout

    def reset_randomized_election_timeout(self) -> None:
        self.randomized_election_timeout = (
            self.election_timeout + self.rand.randrange(self.election_timeout)
        )

    def send_timeout_now(self, to: int) -> None:
        self.send(Message(to=to, type=MessageType.MsgTimeoutNow))

    def abort_leader_transfer(self) -> None:
        self.lead_transferee = NONE

    def committed_entry_in_current_term(self) -> bool:
        return (
            self.raft_log.zero_term_on_err_compacted(self.raft_log.committed)
            == self.term
        )

    def response_to_read_index_req(self, req: Message, read_index: int) -> Message:
        """ref: raft.go:1737-1751."""
        if req.from_ == NONE or req.from_ == self.id:
            self.read_states.append(
                ReadState(index=read_index, request_ctx=req.entries[0].data)
            )
            return Message()
        return Message(
            type=MessageType.MsgReadIndexResp,
            to=req.from_,
            index=read_index,
            entries=req.entries,
        )

    def increase_uncommitted_size(self, ents: List[Entry]) -> bool:
        """ref: raft.go:1761-1779 — empty payloads are never refused."""
        s = sum(e.payload_size() for e in ents)
        if (
            self.uncommitted_size > 0
            and s > 0
            and self.uncommitted_size + s > self.max_uncommitted_size
        ):
            return False
        self.uncommitted_size += s
        return True

    def reduce_uncommitted_size(self, ents: List[Entry]) -> None:
        if self.uncommitted_size == 0:
            return  # follower fast path
        s = sum(e.payload_size() for e in ents)
        if s > self.uncommitted_size:
            self.uncommitted_size = 0
        else:
            self.uncommitted_size -= s


# -- step functions (ref: raft.go:991-1473) -----------------------------------


def step_leader(r: Raft, m: Message) -> None:
    # Messages that need no per-peer progress.
    if m.type == MessageType.MsgBeat:
        r.bcast_heartbeat()
        return
    if m.type == MessageType.MsgCheckQuorum:
        # The leader always counts itself active; if the quorum isn't, it
        # steps down (ref: raft.go:997-1018).
        pr = r.prs.progress.get(r.id)
        if pr is not None:
            pr.recent_active = True
        if not r.prs.quorum_active():
            r.logger.warningf(
                "%x stepped down to follower since quorum is not active", r.id
            )
            r.become_follower(r.term, NONE)

        def f(vid: int, pr: Progress) -> None:
            if vid != r.id:
                pr.recent_active = False

        r.prs.visit(f)
        return
    if m.type == MessageType.MsgProp:
        if not m.entries:
            r.logger.panicf("%x stepped empty MsgProp", r.id)
        if r.id not in r.prs.progress:
            # We were removed from the config while leading.
            raise ProposalDroppedError()
        if r.lead_transferee != NONE:
            r.logger.debugf(
                "%x [term %d] transfer leadership to %x is in progress; dropping proposal",
                r.id, r.term, r.lead_transferee,
            )
            raise ProposalDroppedError()

        for i, e in enumerate(m.entries):
            cc = None
            if e.type == EntryType.EntryConfChange:
                cc = ConfChange.unmarshal(e.data)
            elif e.type == EntryType.EntryConfChangeV2:
                cc = ConfChangeV2.unmarshal(e.data)
            if cc is not None:
                already_pending = r.pending_conf_index > r.raft_log.applied
                already_joint = len(r.prs.voters.outgoing) > 0
                wants_leave_joint = len(cc.as_v2().changes) == 0

                refused = ""
                if already_pending:
                    refused = (
                        f"possible unapplied conf change at index "
                        f"{r.pending_conf_index} (applied to {r.raft_log.applied})"
                    )
                elif already_joint and not wants_leave_joint:
                    refused = "must transition out of joint config first"
                elif not already_joint and wants_leave_joint:
                    refused = "not in joint state; refusing empty conf change"

                if refused:
                    r.logger.infof(
                        "%x ignoring conf change %s at config %s: %s",
                        r.id, cc.go_str(), r.prs.config, refused,
                    )
                    m.entries[i] = Entry(type=EntryType.EntryNormal)
                else:
                    r.pending_conf_index = r.raft_log.last_index() + i + 1

        if not r.append_entry(m.entries):
            raise ProposalDroppedError()
        r.bcast_append()
        return
    if m.type == MessageType.MsgReadIndex:
        # Leader-only singleton: respond immediately.
        if r.prs.is_singleton():
            resp = r.response_to_read_index_req(m, r.raft_log.committed)
            if resp.to != NONE:
                r.send(resp)
            return
        # Reads wait until this leader has committed in its own term.
        if not r.committed_entry_in_current_term():
            r.pending_read_index_messages.append(m)
            return
        send_msg_read_index_response(r, m)
        return

    # All remaining types need m.From's progress.
    pr = r.prs.progress.get(m.from_)
    if pr is None:
        r.logger.debugf("%x no progress available for %x", r.id, m.from_)
        return

    if m.type == MessageType.MsgAppResp:
        pr.recent_active = True
        if m.reject:
            # The follower rejected (index=m.index, logterm=m.log_term at
            # its hint m.reject_hint); use term-skipping probing to find
            # the common prefix in O(#terms) round trips
            # (ref: raft.go:1109-1236).
            r.logger.debugf(
                "%x received MsgAppResp(rejected, hint: (index %d, term %d)) "
                "from %x for index %d",
                r.id, m.reject_hint, m.log_term, m.from_, m.index,
            )
            next_probe_idx = m.reject_hint
            if m.log_term > 0:
                next_probe_idx = r.raft_log.find_conflict_by_term(
                    m.reject_hint, m.log_term
                )
            if pr.maybe_decr_to(m.index, next_probe_idx):
                r.logger.debugf(
                    "%x decreased progress of %x to [%s]", r.id, m.from_, pr
                )
                if pr.state == StateReplicate:
                    pr.become_probe()
                r.send_append(m.from_)
        else:
            old_paused = pr.is_paused()
            if pr.maybe_update(m.index):
                if pr.state == StateProbe:
                    pr.become_replicate()
                elif pr.state == StateSnapshot and pr.match >= pr.pending_snapshot:
                    r.logger.debugf(
                        "%x recovered from needing snapshot, resumed sending "
                        "replication messages to %x [%s]",
                        r.id, m.from_, pr,
                    )
                    # Probe-then-replicate keeps the snapshot index in the
                    # transition (ref: raft.go:1243-1254).
                    pr.become_probe()
                    pr.become_replicate()
                elif pr.state == StateReplicate:
                    pr.inflights.free_le(m.index)

                if r.maybe_commit():
                    release_pending_read_index_messages(r)
                    r.bcast_append()
                elif old_paused:
                    # A previously-paused node may lack the latest commit.
                    r.send_append(m.from_)
                # Flow control may have opened up; drain what we can.
                while r.maybe_send_append(m.from_, send_if_empty=False):
                    pass
                if m.from_ == r.lead_transferee and pr.match == r.raft_log.last_index():
                    r.logger.infof(
                        "%x sent MsgTimeoutNow to %x after received MsgAppResp",
                        r.id, m.from_,
                    )
                    r.send_timeout_now(m.from_)
    elif m.type == MessageType.MsgHeartbeatResp:
        pr.recent_active = True
        pr.probe_sent = False
        if pr.state == StateReplicate and pr.inflights.full():
            pr.inflights.free_first_one()
        if pr.match < r.raft_log.last_index():
            r.send_append(m.from_)

        if r.read_only.option != ReadOnlyOption.ReadOnlySafe or len(m.context) == 0:
            return
        if (
            r.prs.voters.vote_result(r.read_only.recv_ack(m.from_, m.context))
            != VoteResult.VoteWon
        ):
            return
        rss = r.read_only.advance(m)
        for rs in rss:
            resp = r.response_to_read_index_req(rs.req, rs.index)
            if resp.to != NONE:
                r.send(resp)
    elif m.type == MessageType.MsgSnapStatus:
        if pr.state != StateSnapshot:
            return
        if not m.reject:
            pr.become_probe()
            r.logger.debugf(
                "%x snapshot succeeded, resumed sending replication messages to %x [%s]",
                r.id, m.from_, pr,
            )
        else:
            # Order matters: clear the pending snapshot before probing.
            pr.pending_snapshot = 0
            pr.become_probe()
            r.logger.debugf(
                "%x snapshot failed, resumed sending replication messages to %x [%s]",
                r.id, m.from_, pr,
            )
        # Wait for the next MsgAppResp (success) or heartbeat (failure)
        # before sending more appends.
        pr.probe_sent = True
    elif m.type == MessageType.MsgUnreachable:
        # An optimistic pipeline probably lost a MsgApp; drop to probing.
        if pr.state == StateReplicate:
            pr.become_probe()
        r.logger.debugf(
            "%x failed to send message to %x because it is unreachable [%s]",
            r.id, m.from_, pr,
        )
    elif m.type == MessageType.MsgTransferLeader:
        if pr.is_learner:
            r.logger.debugf("%x is learner. Ignored transferring leadership", r.id)
            return
        lead_transferee = m.from_
        last_lead_transferee = r.lead_transferee
        if last_lead_transferee != NONE:
            if last_lead_transferee == lead_transferee:
                r.logger.infof(
                    "%x [term %d] transfer leadership to %x is in progress, "
                    "ignores request to same node %x",
                    r.id, r.term, lead_transferee, lead_transferee,
                )
                return
            r.abort_leader_transfer()
            r.logger.infof(
                "%x [term %d] abort previous transferring leadership to %x",
                r.id, r.term, last_lead_transferee,
            )
        if lead_transferee == r.id:
            r.logger.debugf(
                "%x is already leader. Ignored transferring leadership to self", r.id
            )
            return
        r.logger.infof(
            "%x [term %d] starts to transfer leadership to %x",
            r.id, r.term, lead_transferee,
        )
        # The transfer should finish within one election timeout.
        r.election_elapsed = 0
        r.lead_transferee = lead_transferee
        if pr.match == r.raft_log.last_index():
            r.send_timeout_now(lead_transferee)
            r.logger.infof(
                "%x sends MsgTimeoutNow to %x immediately as %x already has "
                "up-to-date log",
                r.id, lead_transferee, lead_transferee,
            )
        else:
            r.send_append(lead_transferee)


def step_candidate(r: Raft, m: Message) -> None:
    """Shared by StateCandidate and StatePreCandidate; they differ in which
    vote-response type they count (ref: raft.go:1376-1419)."""
    if r.state == StateType.StatePreCandidate:
        my_vote_resp_type = MessageType.MsgPreVoteResp
    else:
        my_vote_resp_type = MessageType.MsgVoteResp

    if m.type == MessageType.MsgProp:
        r.logger.infof("%x no leader at term %d; dropping proposal", r.id, r.term)
        raise ProposalDroppedError()
    elif m.type == MessageType.MsgApp:
        r.become_follower(m.term, m.from_)  # always m.term == r.term
        r.handle_append_entries(m)
    elif m.type == MessageType.MsgHeartbeat:
        r.become_follower(m.term, m.from_)
        r.handle_heartbeat(m)
    elif m.type == MessageType.MsgSnap:
        r.become_follower(m.term, m.from_)
        r.handle_snapshot(m)
    elif m.type == my_vote_resp_type:
        gr, rj, res = r.poll(m.from_, m.type, not m.reject)
        r.logger.infof(
            "%x has received %d %s votes and %d vote rejections", r.id, gr, m.type, rj
        )
        if res == VoteResult.VoteWon:
            if r.state == StateType.StatePreCandidate:
                r.campaign(CAMPAIGN_ELECTION)
            else:
                r.become_leader()
                r.bcast_append()
        elif res == VoteResult.VoteLost:
            # A pre-vote response carries our future term; keep r.term.
            r.become_follower(r.term, NONE)
    elif m.type == MessageType.MsgTimeoutNow:
        r.logger.debugf(
            "%x [term %d state %s] ignored MsgTimeoutNow from %x",
            r.id, r.term, r.state, m.from_,
        )


def step_follower(r: Raft, m: Message) -> None:
    """ref: raft.go:1421-1473."""
    if m.type == MessageType.MsgProp:
        if r.lead == NONE:
            r.logger.infof("%x no leader at term %d; dropping proposal", r.id, r.term)
            raise ProposalDroppedError()
        elif r.disable_proposal_forwarding:
            r.logger.infof(
                "%x not forwarding to leader %x at term %d; dropping proposal",
                r.id, r.lead, r.term,
            )
            raise ProposalDroppedError()
        m.to = r.lead
        r.send(m)
    elif m.type == MessageType.MsgApp:
        r.election_elapsed = 0
        r.lead = m.from_
        r.handle_append_entries(m)
    elif m.type == MessageType.MsgHeartbeat:
        r.election_elapsed = 0
        r.lead = m.from_
        r.handle_heartbeat(m)
    elif m.type == MessageType.MsgSnap:
        r.election_elapsed = 0
        r.lead = m.from_
        r.handle_snapshot(m)
    elif m.type == MessageType.MsgTransferLeader:
        if r.lead == NONE:
            r.logger.infof(
                "%x no leader at term %d; dropping leader transfer msg", r.id, r.term
            )
            return
        m.to = r.lead
        r.send(m)
    elif m.type == MessageType.MsgTimeoutNow:
        r.logger.infof(
            "%x [term %d] received MsgTimeoutNow from %x and starts an election "
            "to get leadership.",
            r.id, r.term, m.from_,
        )
        # Leadership transfers never use pre-vote: we know we're not
        # recovering from a partition.
        r.hup(CAMPAIGN_TRANSFER)
    elif m.type == MessageType.MsgReadIndex:
        if r.lead == NONE:
            r.logger.infof(
                "%x no leader at term %d; dropping index reading msg", r.id, r.term
            )
            return
        m.to = r.lead
        r.send(m)
    elif m.type == MessageType.MsgReadIndexResp:
        if len(m.entries) != 1:
            r.logger.errorf(
                "%x invalid format of MsgReadIndexResp from %x, entries count: %d",
                r.id, m.from_, len(m.entries),
            )
            return
        r.read_states.append(
            ReadState(index=m.index, request_ctx=m.entries[0].data)
        )


def num_of_pending_conf(ents: List[Entry]) -> int:
    return sum(
        1
        for e in ents
        if e.type in (EntryType.EntryConfChange, EntryType.EntryConfChangeV2)
    )


def release_pending_read_index_messages(r: Raft) -> None:
    if not r.committed_entry_in_current_term():
        r.logger.error(
            "pending MsgReadIndex should be released only after first commit in "
            "current term"
        )
        return
    msgs = r.pending_read_index_messages
    r.pending_read_index_messages = []
    for m in msgs:
        send_msg_read_index_response(r, m)


def send_msg_read_index_response(r: Raft, m: Message) -> None:
    """ref: raft.go:1827-1843."""
    if r.read_only.option == ReadOnlyOption.ReadOnlySafe:
        r.read_only.add_request(r.raft_log.committed, m)
        # The local node acks automatically.
        r.read_only.recv_ack(r.id, m.entries[0].data)
        r.bcast_heartbeat_with_ctx(m.entries[0].data)
    elif r.read_only.option == ReadOnlyOption.ReadOnlyLeaseBased:
        resp = r.response_to_read_index_req(m, r.raft_log.committed)
        if resp.to != NONE:
            r.send(resp)
