"""Version info (ref: api/version/version.go)."""

# The etcd API surface this framework is capability-parity with.
MIN_CLUSTER_VERSION = "3.0.0"
CLUSTER_VERSION = "3.6.0"
SERVER_VERSION = "3.6.0-alpha.0+tpu"
API_VERSION = "3.6"
