"""etcd_tpu: a TPU-native distributed KV framework with etcd's capabilities.

The consensus core is a batched multi-Raft engine: thousands of independent
Raft groups packed into structure-of-arrays tensors and stepped in lockstep
by JAX/XLA kernels (see ``etcd_tpu.batched``), with a reference-semantics
host core (``etcd_tpu.raft``) that replays the upstream etcd
``raft/testdata`` interaction traces with exact parity and serves as the
control plane for rare transitions (membership changes, snapshots).

Layer map (mirrors the reference's, SURVEY.md §1):
  - ``etcd_tpu.raft``     — consensus state machine (ref: raft/)
  - ``etcd_tpu.batched``  — SoA multi-group TPU engine (the north star)
  - ``etcd_tpu.rafttest`` — datadriven interaction-trace harness (ref: raft/rafttest)
  - ``etcd_tpu.storage``  — WAL / snapshots / MVCC (ref: server/storage)
  - ``etcd_tpu.server``   — replicated KV server (ref: server/etcdserver)
"""

__version__ = "0.1.0"
