"""Functional/chaos harness (ref: tests/functional/ — agent + tester +
stressers + checkers driven by functional.yaml).

The reference supervises real processes via per-member agents; here
members are in-proc EtcdServers supervised by `Cluster` (kill =
stop + recreate on the same data dir, which exercises the same WAL
replay/snapshot recovery paths), faults ride the network hooks and
failpoints, and the tester loop is `run_case`.
"""

from .cluster import Cluster
from .checker import (
    check_config_safety,
    check_durability_envelope,
    check_leader_claims,
    check_sequential_history,
    committed_never_lost,
    hash_check,
    kv_map_hash,
    lease_expire_check,
    linearizable_check,
    multiraft_hash_check,
)
from .stresser import KVStresser, LeaseStresser

__all__ = [
    "Cluster", "KVStresser", "LeaseStresser",
    "hash_check", "lease_expire_check", "linearizable_check",
    "kv_map_hash", "multiraft_hash_check", "committed_never_lost",
    "check_leader_claims", "check_sequential_history",
    "check_config_safety", "check_durability_envelope",
]
