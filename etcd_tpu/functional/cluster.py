"""Supervised in-proc cluster: the agent half of the functional
harness (ref: tests/functional/agent/ — the per-member supervisor that
can stop/restart/blackhole its member on tester command)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..raft.raft import NONE
from ..raftexample.transport import InProcNetwork
from ..server import EtcdServer, ServerConfig


class Cluster:
    def __init__(self, data_dir: str, n: int = 3,
                 tick_interval: float = 0.01, **cfg_kw) -> None:
        self.data_dir = data_dir
        self.peers = list(range(1, n + 1))
        self.tick_interval = tick_interval
        self.cfg_kw = cfg_kw
        self.net = InProcNetwork()
        self.servers: Dict[int, Optional[EtcdServer]] = {}
        for nid in self.peers:
            self.servers[nid] = self._spawn(nid)

    def _spawn(self, nid: int) -> EtcdServer:
        kw = {"request_timeout": 10.0, **self.cfg_kw}
        return EtcdServer(
            ServerConfig(
                member_id=nid,
                peers=self.peers,
                data_dir=self.data_dir,
                network=self.net,
                tick_interval=self.tick_interval,
                **kw,
            )
        )

    # -- membership of the living ----------------------------------------------

    def alive(self) -> List[EtcdServer]:
        return [s for s in self.servers.values() if s is not None]

    def leader(self) -> Optional[EtcdServer]:
        for s in self.alive():
            if s.is_leader():
                return s
        return None

    def followers(self) -> List[EtcdServer]:
        return [s for s in self.alive() if not s.is_leader()]

    def wait_leader(self, timeout: float = 20.0) -> EtcdServer:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            lead = self.leader()
            # Settled: a leader exists and every live member agrees.
            if lead is not None and all(
                s.leader() == lead.id for s in self.alive()
            ):
                return lead
            time.sleep(0.02)
        raise AssertionError("no leader within timeout")

    def wait_no_leader(self, timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(s.leader() == NONE for s in self.alive()):
                return
            time.sleep(0.02)
        raise AssertionError("leader still present")

    # -- failures (tester/case_*.go) -------------------------------------------

    def kill(self, nid: int) -> None:
        """SIGKILL equivalent: stop the member (WAL/backend stay)."""
        s = self.servers[nid]
        if s is not None:
            s.stop()
            self.net.unregister(nid)
            self.servers[nid] = None

    def restart(self, nid: int) -> EtcdServer:
        """Agent restart: same data dir → WAL replay recovery path."""
        assert self.servers[nid] is None, f"member {nid} still running"
        self.net.heal(nid)
        s = self._spawn(nid)
        self.servers[nid] = s
        return s

    def blackhole(self, nid: int) -> None:
        """Drop all peer traffic to/from nid (BLACKHOLE_PEER cases)."""
        self.net.isolate(nid)

    def unblackhole(self, nid: int) -> None:
        self.net.heal(nid)

    def drop(self, a: int, b: int, prob: float) -> None:
        self.net.drop(a, b, prob)
        self.net.drop(b, a, prob)

    def delay_peer(self, nid: int, base_s: float,
                   jitter_s: float = 0.0) -> None:
        """Add latency to ALL of nid's links, both directions
        (DELAY_PEER_PORT_TX_RX_{ONE_FOLLOWER,LEADER} cases)."""
        for other in self.peers:
            if other != nid:
                self.net.delay(nid, other, base_s, jitter_s)
                self.net.delay(other, nid, base_s, jitter_s)

    def undelay_all(self) -> None:
        self.net.undelay()

    def close(self) -> None:
        for nid, s in self.servers.items():
            if s is not None:
                s.stop()
        self.net.stop()
