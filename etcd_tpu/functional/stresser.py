"""Stressers: sustained load that tolerates member failures
(ref: tests/functional/tester/stresser_key.go, stresser_lease.go)."""

from __future__ import annotations

import random
import threading
from typing import List, Optional

from ..server.api import (
    Compare, CompareResult, CompareTarget, DeleteRangeRequest, PutRequest,
    RangeRequest, RequestOp, TxnRequest,
)


class KVStresser:
    """Writer threads hammering random keys with put/delete/txn against
    whichever member currently accepts writes. Errors during faults are
    expected and counted, not raised."""

    def __init__(self, cluster, prefix: bytes = b"stress/",
                 keyspace: int = 64, writers: int = 2, seed: int = 0) -> None:
        self.cluster = cluster
        self.prefix = prefix
        self.keyspace = keyspace
        self.writers = writers
        self.rand = random.Random(seed)
        self.success = 0
        self.failure = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    def start(self) -> None:
        for i in range(self.writers):
            t = threading.Thread(target=self._loop, args=(i,), daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=15)

    def _key(self, rnd: random.Random) -> bytes:
        return self.prefix + str(rnd.randrange(self.keyspace)).encode()

    def _loop(self, idx: int) -> None:
        rnd = random.Random(idx)
        seq = 0
        while not self._stop.is_set():
            lead = self.cluster.leader()
            if lead is None:
                self._stop.wait(0.05)
                continue
            key = self._key(rnd)
            seq += 1
            try:
                op = rnd.random()
                if op < 0.7:
                    lead.put(PutRequest(key=key, value=b"v%d" % seq))
                elif op < 0.85:
                    lead.delete_range(DeleteRangeRequest(key=key))
                else:
                    lead.txn(TxnRequest(
                        compare=[Compare(
                            target=CompareTarget.VERSION,
                            result=CompareResult.GREATER,
                            key=key, version=0,
                        )],
                        success=[RequestOp(request_put=PutRequest(
                            key=key, value=b"t%d" % seq,
                        ))],
                        failure=[RequestOp(request_put=PutRequest(
                            key=key, value=b"f%d" % seq,
                        ))],
                    ))
                with self._lock:
                    self.success += 1
            except Exception:  # noqa: BLE001 — faults make these expected
                with self._lock:
                    self.failure += 1
                self._stop.wait(0.02)


class LeaseStresser:
    """Grants short leases with attached keys; the checker later
    verifies expiry semantics (stresser_lease.go)."""

    def __init__(self, cluster, prefix: bytes = b"leased/",
                 ttl: int = 2) -> None:
        self.cluster = cluster
        self.prefix = prefix
        self.ttl = ttl
        self.granted: List[int] = []
        self.keys: List[bytes] = []

    def grant_with_keys(self, n: int = 3) -> None:
        lead = self.cluster.wait_leader()
        for i in range(n):
            resp = lead.lease_grant(ttl=self.ttl)
            key = self.prefix + str(resp.id).encode()
            lead.put(PutRequest(key=key, value=b"x", lease=resp.id))
            self.granted.append(resp.id)
            self.keys.append(key)
