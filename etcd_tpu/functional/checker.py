"""Checkers: post-fault invariants
(ref: tests/functional/tester/checker_kv_hash.go, checker_lease_expire.go,
checker_no_check.go; cluster consistency = same KV hash at the same
revision across members).

Two families share the converge-then-assert skeleton (`_converge`):

* the single-group server checkers (`hash_check`, `lease_expire_check`,
  `linearizable_check`) over ``EtcdServer`` members, and
* the batched multi-raft checkers (`multiraft_hash_check`,
  `committed_never_lost`, `check_leader_claims`,
  `check_sequential_history`) over ``MultiRaftMember``-shaped hosts —
  duck-typed on ``.kvs`` / ``.applied_index`` so this module never
  imports the batched engine.
"""

from __future__ import annotations

import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..server import EtcdServer
from ..server.api import RangeRequest


def _converge(poll: Callable[[], Tuple[bool, object]], timeout: float,
              desc: str, interval: float = 0.1):
    """Deadline-poll a convergence predicate. ``poll`` returns
    (ok, info); exceptions count as not-yet (members mid-recovery
    mutate state under the poller). On success returns the final info;
    on deadline raises AssertionError carrying the last observation."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            ok, last = poll()
            if ok:
                return last
        except Exception as e:  # noqa: BLE001 — members mid-recovery
            last = e
        time.sleep(interval)
    raise AssertionError(f"{desc} after {timeout}s: {last}")


def hash_check(servers: List[EtcdServer], timeout: float = 20.0) -> int:
    """All members converge to the same hash_kv at the same revision
    (checker_kv_hash.go waits up to 7 rounds). Returns the agreed rev."""

    def poll():
        # Pin the comparison at the smallest current revision.
        rev = min(s.kv.rev() for s in servers)
        hashes = {s.hash_kv(rev)[0] for s in servers}
        return len(hashes) == 1, rev if len(hashes) == 1 else hashes

    return _converge(poll, timeout, "kv hash mismatch")


def lease_expire_check(server: EtcdServer, lease_ids: List[int],
                       keys: List[bytes], timeout: float = 30.0) -> None:
    """Expired leases are gone and their keys deleted
    (checker_lease_expire.go)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = set(server.lease_leases())
        if not (alive & set(lease_ids)):
            break
        time.sleep(0.1)
    else:
        raise AssertionError("leases did not expire")
    for key in keys:
        rr = server.range(RangeRequest(key=key, serializable=True))
        assert not rr.kvs, f"leased key {key!r} survived expiry"


def linearizable_check(server: EtcdServer, key: bytes,
                       expect_value: bytes) -> None:
    """A linearizable read observes the latest committed write."""
    rr = server.range(RangeRequest(key=key))
    assert rr.kvs and rr.kvs[0].value == expect_value, (
        f"linearizable read saw {rr.kvs[0].value if rr.kvs else None!r}, "
        f"want {expect_value!r}"
    )


# -- batched multi-raft checkers -----------------------------------------------


def kv_map_hash(data: Dict[bytes, bytes]) -> int:
    """Order-independent-input, order-pinned hash of one group's KV map
    (crc32c chain over sorted items — the per-group analog of the
    server's hash_kv)."""
    h = 0
    for k in sorted(data):
        h = zlib.crc32(k, h)
        h = zlib.crc32(b"\x00", h)
        h = zlib.crc32(data[k], h)
        h = zlib.crc32(b"\x01", h)
    return h


def multiraft_hash_check(members: Sequence, timeout: float = 30.0,
                         allow_lag: int = 0) -> List[int]:
    """Per-group KV-hash parity across the surviving members — the
    hash_check invariant batched over every group at once. Members are
    MultiRaftMember-shaped: ``.kvs`` (list of GroupKV) and
    ``.applied_index`` (numpy [G]). Waits for the apply watermarks to
    agree first (cheap vector compare) before hashing. Returns the
    per-group hash list of the agreeing majority.

    ``allow_lag=k`` relaxes parity to the quorum theorem raft actually
    proves: per group, at least ``len(members) - k`` members must agree
    on (applied, hash); up to k members may lag behind (a follower
    being behind is a liveness condition every live cluster passes
    through, not a safety violation). Strict parity (k=0) is the
    default and — since the ISSUE 5 durability fence — what every
    chaos episode class asserts; the relaxation remains for
    fence-disabled runs that deliberately re-open the torn-tail
    divergence (tools/repro_progress_wedge.py --torn-acked)."""
    import numpy as np

    members = list(members)
    assert members, "no members to check"
    need = len(members) - allow_lag

    def poll():
        applied = np.stack(
            [np.asarray(m.applied_index) for m in members])
        hashes = None
        if (applied == applied[0]).all():
            hashes = [[kv_map_hash(kv.data) for kv in m.kvs]
                      for m in members]
            for mi, hs in enumerate(hashes[1:], 1):
                if hs != hashes[0]:
                    bad = [g for g, (a, b)
                           in enumerate(zip(hashes[0], hs)) if a != b]
                    return False, (
                        f"kv hash mismatch member {members[mi].id} "
                        f"groups {bad[:8]}")
            return True, hashes[0]
        lag = np.nonzero((applied != applied[0]).any(axis=0))[0]
        if not allow_lag:
            return False, (
                f"applied divergence on groups {lag[:8].tolist()}: "
                f"{applied[:, lag[:4]].tolist()}")
        # Quorum mode: per group the modal (applied, hash) pair must be
        # held by >= need members.
        hashes = [[kv_map_hash(kv.data) for kv in m.kvs]
                  for m in members]
        agreed: List[int] = []
        for g in range(applied.shape[1]):
            pairs = [(int(applied[mi, g]), hashes[mi][g])
                     for mi in range(len(members))]
            top, count = max(
                ((p, pairs.count(p)) for p in pairs),
                key=lambda t: t[1])
            if count < need:
                return False, (
                    f"group {g}: no {need}-member agreement, "
                    f"states {pairs}")
            agreed.append(top[1])
        return True, agreed

    return _converge(poll, timeout, "multi-raft kv hash parity")


def committed_never_lost(members: Sequence,
                         acked: Dict[Tuple[int, bytes], bytes],
                         timeout: float = 30.0,
                         allow_lag: int = 0,
                         history: Optional[
                             Dict[Tuple[int, bytes], List[bytes]]
                         ] = None) -> None:
    """Every acked write — applied at its proposer, hence committed —
    is present with the acked value on EVERY surviving member after
    recovery (the tester's 'no lost writes' core; Jepsen's
    acknowledged-writes-survive).

    ``allow_lag=k``: each acked write must be present on at least
    ``len(members) - k`` members (quorum durability — the theorem raft
    proves). A member holding a value NEVER acked for the key is
    DIVERGENT (immediate failure); a member holding an OLDER acked
    version from ``history`` (key -> acked values in order) is merely
    lagging — missing a suffix, never diverging."""
    members = list(members)
    need = len(members) - allow_lag
    history = history or {}

    def poll():
        missing = []
        for (g, k), v in acked.items():
            have = 0
            for m in members:
                got = m.kvs[g].data.get(k)
                if got == v:
                    have += 1
                elif got is not None and \
                        got not in history.get((g, k), ()):
                    return False, (
                        f"DIVERGENT acked write g{g} {k!r} on "
                        f"member {m.id}: {got!r} never acked "
                        f"(latest {v!r})")
            if have < need:
                missing.append((g, k, have))
                if len(missing) >= 8:
                    break
        return not missing, (
            f"acked writes below {need}-member durability: "
            f"{missing[:8]}" if missing
            else f"{len(acked)} acked writes intact")

    _converge(poll, timeout, "committed-never-lost")


def check_leader_claims(
        conflicts: List[Tuple[int, int, int, int]]) -> None:
    """Assert the LeaderObserver saw at most one leader per (group,
    term) — raft election safety across the whole batch."""
    assert not conflicts, (
        "two leaders claimed the same (group, term): "
        f"{[(g, t, a, b) for g, t, a, b in conflicts[:8]]}")


def _quorums_can_be_disjoint(a, b) -> bool:
    """Whether two majority configs admit DISJOINT quorums — i.e. a
    quorum of `a` and a quorum of `b` with no member in common, the
    precondition for two leaders committing divergent entries in one
    term. Feasible exactly when |q_a| + |q_b| <= |a ∪ b| (fill each
    quorum from its private members first, then the shared pool)."""
    a, b = set(a), set(b)
    if not a or not b:
        return False  # empty config commits nothing on its own
    qa = len(a) // 2 + 1
    qb = len(b) // 2 + 1
    return qa + qb <= len(a | b)


def check_config_safety(members: Sequence,
                        timeout: float = 30.0) -> None:
    """Membership-change safety over the batched hosting path (the
    conf-change analog of the KV checkers; members are
    MultiRaftMember-shaped, duck-typed on ``conf_snapshot()`` /
    ``conf_history(g)``):

    1. **no committed config lost** — after convergence every member
       holds the SAME final per-group config (voters/learners/joint),
       and histories never disagree about the config applied at a
       given log index;
    2. **no two disjoint quorums for one group** — every adjacent pair
       of configs in the applied sequence overlaps: a joint entry's
       outgoing half must equal the previous incoming voters (the
       §4.3 discipline), a simple change moves at most one voter, and
       the quorum-disjointness formula is checked explicitly on every
       transition (old config vs new, both joint halves);
    3. **joint state always exited** — no group ends the episode
       inside a joint config.
    """
    members = list(members)
    assert members, "no members to check"

    def poll():
        snaps = [m.conf_snapshot() for m in members]
        s0 = snaps[0]
        g = len(s0["voters"])
        for mi, s in enumerate(snaps[1:], 1):
            for gi in range(g):
                if (s["voters"][gi] != s0["voters"][gi]
                        or s["learners"][gi] != s0["learners"][gi]
                        or bool(s["in_joint"][gi])
                        != bool(s0["in_joint"][gi])):
                    return False, (
                        f"conf divergence g{gi}: member "
                        f"{members[mi].id} {s['voters'][gi]}/"
                        f"{s['learners'][gi]} vs member "
                        f"{members[0].id} {s0['voters'][gi]}/"
                        f"{s0['learners'][gi]}")
        joint = [gi for gi in range(g) if bool(s0["in_joint"][gi])]
        if joint:
            return False, f"groups still in joint config: {joint[:8]}"
        return True, g

    g = _converge(poll, timeout, "config parity / joint exit")

    # History audit (post-convergence; histories are bounded rings, so
    # compare only the indexes both members still hold).
    for gi in range(g):
        hists = [m.conf_history(gi) for m in members]
        by_index: Dict[int, Tuple] = {}
        for m, h in zip(members, hists):
            for ent in h:
                key = (ent["voters"], ent["voters_out"],
                       ent["learners"], ent["joint"])
                prev = by_index.setdefault(ent["index"], key)
                assert prev == key, (
                    f"committed config lost/diverged g{gi} "
                    f"i{ent['index']}: member {m.id} applied {key}, "
                    f"another member applied {prev}")
        for h in hists:
            prev = None  # boot config = all voters, checked via first
            for ent in h:
                cur_voters = set(ent["voters"])
                if ent.get("restored"):
                    # A snapshot-carried config: the entries between
                    # prev and here were compacted away, so adjacency
                    # re-anchors at the restored state (its own
                    # legality was audited by the members that applied
                    # the original entries).
                    prev = ent
                    continue
                if ent["joint"]:
                    # Enter-joint: commits now need BOTH halves, and
                    # the outgoing half must be exactly the previous
                    # incoming voters — any joint quorum then contains
                    # a majority of the old config, so no quorum of
                    # the old and new systems can ever be disjoint
                    # (§4.3; quorum/joint.go).
                    out = set(ent["voters_out"])
                    if prev is not None:
                        assert out == set(prev["voters"]), (
                            f"g{gi} i{ent['index']}: joint outgoing "
                            f"{sorted(out)} != previous incoming "
                            f"{sorted(prev['voters'])}")
                elif prev is not None:
                    if prev["joint"]:
                        # Leave-joint: the incoming half carries over
                        # unchanged — quorums before (joint: needs an
                        # incoming majority) and after (incoming
                        # majority) share a set, so they intersect.
                        assert cur_voters == set(prev["voters"]), (
                            f"g{gi} i{ent['index']}: leave-joint "
                            f"changed voters {sorted(prev['voters'])} "
                            f"-> {sorted(cur_voters)}")
                    else:
                        delta = cur_voters ^ set(prev["voters"])
                        assert len(delta) <= 1, (
                            f"g{gi} i{ent['index']}: simple change "
                            f"moved {len(delta)} voters "
                            f"({sorted(delta)}) without joint")
                        assert not _quorums_can_be_disjoint(
                            set(prev["voters"]), cur_voters), (
                            f"g{gi} i{ent['index']}: adjacent simple "
                            f"configs {sorted(prev['voters'])} -> "
                            f"{sorted(cur_voters)} admit disjoint "
                            "quorums")
                prev = ent


def check_durability_envelope(applied: Dict[int, int],
                              durable: Dict[int, int]) -> None:
    """Release-barrier audit for a fail-stopped member (the ISSUE 15
    IO-error contract): ``applied`` is the dead member's per-group
    apply watermark at death, ``durable`` what its WAL can actually
    replay (max entry/snapshot index per group). Every apply a member
    ever RELEASES must ride a successful covering fsync — so an
    ``applied[g] > durable[g]`` group means an ack/apply escaped the
    failed window: exactly the ATC'19 failure (state served to clients
    that recovery cannot reproduce). Pure function — the chaos harness
    (faults.failstop_envelope) assembles both maps."""
    bad = {g: (a, durable.get(g, 0)) for g, a in applied.items()
           if a > durable.get(g, 0)}
    assert not bad, (
        "applies escaped the failed window (applied > durable log): "
        f"{dict(list(bad.items())[:8])}")


def check_sequential_history(
        history: List[Tuple],
) -> None:
    """Replay a SEQUENTIAL client's observed history: with no client
    concurrency, linearizability degenerates to 'every successful read
    returns the latest acked write to that key'. Events:
    ``('w', key, value)`` — an acked write; ``('r', key, got, ok)`` —
    a read that returned `got` (ok=True) or failed cleanly (ok=False,
    e.g. NotLeaderError/TimeoutError during failover — always legal).
    A successful STALE read is the bug this catches."""
    latest: Dict[bytes, Optional[bytes]] = {}
    for i, ev in enumerate(history):
        if ev[0] == "w":
            _op, key, value = ev
            latest[key] = value
        elif ev[0] == "r":
            _op, key, got, ok = ev
            if ok:
                want = latest.get(key)
                assert got == want, (
                    f"stale read at history[{i}]: key {key!r} returned "
                    f"{got!r}, latest acked write was {want!r}")
        else:
            raise ValueError(f"unknown history event {ev!r}")
