"""Checkers: post-fault invariants
(ref: tests/functional/tester/checker_kv_hash.go, checker_lease_expire.go,
checker_no_check.go; cluster consistency = same KV hash at the same
revision across members)."""

from __future__ import annotations

import time
from typing import List

from ..server import EtcdServer
from ..server.api import RangeRequest


def hash_check(servers: List[EtcdServer], timeout: float = 20.0) -> int:
    """All members converge to the same hash_kv at the same revision
    (checker_kv_hash.go waits up to 7 rounds). Returns the agreed rev."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            # Pin the comparison at the smallest current revision.
            rev = min(s.kv.rev() for s in servers)
            hashes = {s.hash_kv(rev)[0] for s in servers}
            if len(hashes) == 1:
                return rev
            last = hashes
        except Exception as e:  # noqa: BLE001 — members mid-recovery
            last = e
        time.sleep(0.1)
    raise AssertionError(f"kv hash mismatch after {timeout}s: {last}")


def lease_expire_check(server: EtcdServer, lease_ids: List[int],
                       keys: List[bytes], timeout: float = 30.0) -> None:
    """Expired leases are gone and their keys deleted
    (checker_lease_expire.go)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        alive = set(server.lease_leases())
        if not (alive & set(lease_ids)):
            break
        time.sleep(0.1)
    else:
        raise AssertionError("leases did not expire")
    for key in keys:
        rr = server.range(RangeRequest(key=key, serializable=True))
        assert not rr.kvs, f"leased key {key!r} survived expiry"


def linearizable_check(server: EtcdServer, key: bytes,
                       expect_value: bytes) -> None:
    """A linearizable read observes the latest committed write."""
    rr = server.range(RangeRequest(key=key))
    assert rr.kvs and rr.kvs[0].value == expect_value, (
        f"linearizable read saw {rr.kvs[0].value if rr.kvs else None!r}, "
        f"want {expect_value!r}"
    )
