"""Replicated in-memory KV on top of the Ready loop
(ref: contrib/raftexample/kvstore.go — map + gob + snapshot; here the
wire/snapshot encoding is JSON, the fields are the same).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from ..raft.types import Entry


class ReplicatedKV:
    """The app: proposals are {"key","val"} JSON blobs; lookups are
    served from the local applied map (ref: kvstore.go Lookup/Propose)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._store: Dict[str, str] = {}
        self.node = None  # set by attach()

    def attach(self, node) -> None:
        self.node = node

    # -- raftnode callbacks ----------------------------------------------------

    def apply(self, ents: List[Entry]) -> None:
        with self._lock:
            for e in ents:
                kv = json.loads(e.data.decode())
                self._store[kv["key"]] = kv["val"]

    def snapshot(self) -> bytes:
        with self._lock:
            return json.dumps(self._store).encode()

    def restore(self, data: bytes) -> None:
        with self._lock:
            self._store = json.loads(data.decode()) if data else {}

    # -- client API ------------------------------------------------------------

    def propose(self, key: str, val: str, timeout: float = 5.0) -> None:
        data = json.dumps({"key": key, "val": val}).encode()
        self.node.propose(data, timeout=timeout)

    def lookup(self, key: str) -> Optional[str]:
        with self._lock:
            return self._store.get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)
