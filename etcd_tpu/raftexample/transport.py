"""In-process message router with fault injection.

Plays the role of rafthttp for in-proc clusters: per-destination ordered
delivery, drop-don't-block (ref: etcdserver/raft.go:108-111 comment),
plus the fault hooks integration tests rely on (isolate/partition/drop —
ref: tests/framework/integration bridge + raft/rafttest/network.go).
"""

from __future__ import annotations

import queue
import random
import time
import threading
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..raft.types import Message, MessageType

MAX_PENDING = 4096


class InProcNetwork:
    def __init__(self, seed: int = 0) -> None:
        self._lock = threading.Lock()
        self._handlers: Dict[int, Callable[[Message], None]] = {}
        self._reporters: Dict[int, Callable[[int, bool], None]] = {}
        self._queues: Dict[int, "queue.Queue[Message]"] = {}
        self._pumps: Dict[int, threading.Thread] = {}
        self._isolated: Set[int] = set()
        self._dropped: Dict[Tuple[int, int], float] = {}
        # Directed-link latency injection: (from, to) -> (base_s, jitter_s)
        # (ref: functional DELAY_PEER_PORT_TX_RX cases, rpcpb/rpc.proto).
        self._delayed: Dict[Tuple[int, int], Tuple[float, float]] = {}
        # Per-link delivery-time floor keeping delayed links FIFO
        # (a TCP stream delays, it does not reorder).
        self._delay_floor: Dict[Tuple[int, int], float] = {}
        self._rand = random.Random(seed)
        self._stopped = False

    def register(self, node_id: int, handler: Callable[[Message], None],
                 reporter=None) -> None:
        """Attach a node; messages to `node_id` are pumped on a dedicated
        thread to preserve per-peer ordering without blocking senders.

        `reporter` (optional) receives snapshot delivery outcomes:
        ``reporter(to_id, failure: bool)`` — the in-proc analog of
        rafthttp's snapshot sender always reporting finish/failure to
        the sender's raft (ref: rafthttp/snapshot_sender.go:200,
        raft.go:1316-1331 MsgSnapStatus), which is what unsticks a
        StateSnapshot progress when the receiver crashes mid-install."""
        with self._lock:
            if self._stopped:
                return
            self._handlers[node_id] = handler
            if reporter is not None:
                self._reporters[node_id] = reporter
            if node_id not in self._queues:
                q: "queue.Queue[Message]" = queue.Queue(maxsize=MAX_PENDING)
                self._queues[node_id] = q
                t = threading.Thread(
                    target=self._pump, args=(node_id, q), daemon=True
                )
                self._pumps[node_id] = t
                t.start()

    def unregister(self, node_id: int) -> None:
        with self._lock:
            self._handlers.pop(node_id, None)
            self._reporters.pop(node_id, None)

    def _report_snap(self, m: Message, failure: bool) -> None:
        """Tell the sender's raft how its MsgSnap delivery went."""
        if m.type != MessageType.MsgSnap:
            return
        with self._lock:
            rep = self._reporters.get(m.from_)
        if rep is not None:
            try:
                rep(m.to, failure)
            except Exception:  # noqa: BLE001 — sender may be stopping
                pass

    def send(self, from_id: int, msgs: List[Message]) -> None:
        for m in msgs:
            self._send_one(from_id, m)

    def _send_one(self, from_id: int, m: Message) -> None:
        with self._lock:
            if self._stopped:
                return
            drop = (
                from_id in self._isolated
                or m.to in self._isolated
                or self._rand.random() < self._dropped.get(
                    (from_id, m.to), 0.0)
            )
            delay_s = 0.0
            q = None
            if not drop:
                dly = self._delayed.get((from_id, m.to))
                if dly:
                    now = time.monotonic()
                    at = now + dly[0] + self._rand.random() * dly[1]
                    # FIFO floor: a later message never overtakes an
                    # earlier one on the same link, jitter or not.
                    key = (from_id, m.to)
                    at = max(at, self._delay_floor.get(key, 0.0))
                    self._delay_floor[key] = at
                    delay_s = at - now
                q = self._queues.get(m.to)
        if drop or q is None:
            self._report_snap(m, failure=True)
            return

        def put() -> None:
            try:
                q.put_nowait(m)  # drop, never block (rafthttp semantics)
            except queue.Full:
                self._report_snap(m, failure=True)

        if delay_s > 0:
            t = threading.Timer(delay_s, put)
            t.daemon = True
            t.start()
        else:
            put()

    def _pump(self, node_id: int, q: "queue.Queue[Message]") -> None:
        while True:
            m = q.get()
            if m is None:  # type: ignore[comparison-overlap]
                return
            with self._lock:
                h = self._handlers.get(node_id)
                stopped = self._stopped
            if stopped:
                return
            if h is None:
                self._report_snap(m, failure=True)
                continue
            try:
                h(m)
            except Exception:  # noqa: BLE001 — a dead node mustn't kill the pump
                self._report_snap(m, failure=True)
            else:
                self._report_snap(m, failure=False)

    # -- fault injection (ref: rafttest/network.go:33-46) ----------------------

    def isolate(self, node_id: int) -> None:
        with self._lock:
            self._isolated.add(node_id)

    def heal(self, node_id: Optional[int] = None) -> None:
        """Clear faults: all of them (no arg), or everything touching
        one member — isolation, drops, and delays alike."""
        with self._lock:
            if node_id is None:
                self._isolated.clear()
                self._dropped.clear()
                self._delayed.clear()
                self._delay_floor.clear()
            else:
                self._isolated.discard(node_id)
                for d in (self._dropped, self._delayed, self._delay_floor):
                    for k in [k for k in d if node_id in k]:
                        del d[k]

    def drop(self, from_id: int, to_id: int, prob: float) -> None:
        with self._lock:
            self._dropped[(from_id, to_id)] = prob

    def cut(self, a: int, b: int) -> None:
        self.drop(a, b, 1.0)
        self.drop(b, a, 1.0)

    def mend(self, a: int, b: int) -> None:
        with self._lock:
            self._dropped.pop((a, b), None)
            self._dropped.pop((b, a), None)

    def delay(self, from_id: int, to_id: int, base_s: float,
              jitter_s: float = 0.0) -> None:
        """Add latency (with jitter) to a directed link — the
        functional suite's delay-peer-traffic fault class."""
        with self._lock:
            self._delayed[(from_id, to_id)] = (base_s, jitter_s)

    def undelay(self, from_id: Optional[int] = None,
                to_id: Optional[int] = None) -> None:
        """Clear delays: everything (no args), every link touching
        from_id (one arg), or one directed link (both args)."""
        with self._lock:
            if from_id is None:
                self._delayed.clear()
                self._delay_floor.clear()
            elif to_id is None:
                for d in (self._delayed, self._delay_floor):
                    for k in [k for k in d if from_id in k]:
                        del d[k]
            else:
                self._delayed.pop((from_id, to_id), None)
                self._delay_floor.pop((from_id, to_id), None)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            queues = list(self._queues.values())
        for q in queues:
            try:
                q.put_nowait(None)  # type: ignore[arg-type]
            except queue.Full:
                pass
