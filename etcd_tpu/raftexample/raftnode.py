"""The production Ready loop for one raft member
(ref: contrib/raftexample/raft.go:87 newRaftNode, serveChannels
~raft.go:416-472, with the persistence ordering of
server/etcdserver/raft.go:226-268).

Loop order per Ready:
  1. save snapshot file + WAL marker (raftBeforeSaveSnap);
  2. WAL save HardState+entries, fsync per MustSync;
  3. apply snapshot to MemoryStorage, publish to the app;
  4. MemoryStorage append;
  5. send messages (after persistence — the conservative follower
     order; the leader-parallel-send optimization lives in the
     etcdserver-style host, not this minimal example);
  6. publish committed entries, trigger snapshot every snap_count;
  7. Advance.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional

from ..batched.node import BatchedNode
from ..batched.rawnode import RowRestore
from ..raft.node import Node, Peer
from ..raft.raft import Config, NONE, StateType
from ..raft.rawnode import Ready
from ..raft.storage import MemoryStorage
from ..raft.types import (
    ConfChange,
    ConfChangeType,
    ConfChangeV2,
    Entry,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
    is_empty_snap,
)
from ..storage.snap import NoSnapshotError, Snapshotter
from ..storage.storage import ServerStorage
from ..storage.wal import WAL, WalSnapshot
from .transport import InProcNetwork

DEFAULT_SNAP_COUNT = 10000  # raftexample's defaultSnapshotCount (raft.go:121)
SNAPSHOT_CATCHUP_ENTRIES = 10000


class ExampleRaftNode:
    """One member: raft Node + WAL + snapshots + transport glue."""

    def __init__(
        self,
        node_id: int,
        peers: List[int],
        network: InProcNetwork,
        data_dir: str,
        apply_fn: Callable[[List[Entry]], None],
        snapshot_fn: Callable[[], bytes],
        restore_fn: Callable[[bytes], None],
        join: bool = False,
        snap_count: int = DEFAULT_SNAP_COUNT,
        tick_interval: float = 0.05,
        election_tick: int = 10,
        heartbeat_tick: int = 1,
        backend: str = "host",
    ) -> None:
        """`backend` selects the raft implementation at this single
        construction site (ref: etcdserver/bootstrap.go:473-536
        bootstrapRaft): "host" = the reference-shaped Python core,
        "tpu" = the batched device engine behind the same Node
        contract (batched/node.py)."""
        assert backend in ("host", "tpu"), backend
        self.backend = backend
        self.id = node_id
        self.peers = list(peers)
        self.network = network
        self.data_dir = data_dir
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.snap_count = snap_count
        self.tick_interval = tick_interval

        self.wal_dir = os.path.join(data_dir, f"member-{node_id}", "wal")
        self.snap_dir = os.path.join(data_dir, f"member-{node_id}", "snap")
        os.makedirs(self.snap_dir, exist_ok=True)

        self.raft_storage = MemoryStorage()
        self.snapshotter = Snapshotter(self.snap_dir)
        self.confstate = None
        self.snapshot_index = 0
        self.applied_index = 0
        self._stopped = threading.Event()

        old_wal = WAL.exists(self.wal_dir)
        self._restore_data = None  # set by _replay for the tpu backend
        self._replay()

        if backend == "tpu":
            # Device ring must cover the un-snapshotted tail: snapshots
            # (and the host-driven ring compaction that follows them)
            # happen every `snap_count` entries, so size the window past
            # that plus catch-up margin.
            window = 1 << max(6, (2 * snap_count + 64).bit_length())
            window = min(window, 1 << 15)
            self.snap_count = min(snap_count, window // 4)
            self.node = BatchedNode(
                node_id=node_id,
                peers=peers,
                election_tick=election_tick,
                heartbeat_tick=heartbeat_tick,
                window=window,
                restore=self._restore_data,
            )
            self._restore_data = None
        else:
            cfg = Config(
                id=node_id,
                election_tick=election_tick,
                heartbeat_tick=heartbeat_tick,
                storage=self.raft_storage,
                max_size_per_msg=1024 * 1024,
                max_inflight_msgs=256,
                max_uncommitted_entries_size=1 << 30,
                check_quorum=True,
                pre_vote=True,
            )
            if old_wal or join:
                self.node = Node.restart(cfg)
            else:
                self.node = Node.start(cfg, [Peer(id=p) for p in peers])

        self.storage = ServerStorage(self.wal, self.snapshotter)
        network.register(
            node_id, self._receive,
            reporter=lambda vid, failure: self.node.report_snapshot(
                vid, failure),
        )

        self._ticker = threading.Thread(target=self._tick_loop, daemon=True)
        self._server = threading.Thread(target=self._serve_loop, daemon=True)
        self._ticker.start()
        self._server.start()

    # -- boot ------------------------------------------------------------------

    def _replay(self) -> None:
        """Snapshot → WAL replay → MemoryStorage
        (ref: raftexample/raft.go replayWAL)."""
        snap = Snapshot()
        if WAL.exists(self.wal_dir):
            try:
                snap = self.snapshotter.load()
            except NoSnapshotError:
                snap = Snapshot()
            self.wal = WAL.open(self.wal_dir)
            walsnap = WalSnapshot(
                index=snap.metadata.index, term=snap.metadata.term
            )
            _meta, hs, ents = self.wal.read_all(walsnap)
            if not is_empty_snap(snap):
                self.raft_storage.apply_snapshot(snap)
                self.confstate = snap.metadata.conf_state
                self.snapshot_index = snap.metadata.index
                self.applied_index = snap.metadata.index
                self.restore_fn(snap.data)
            self.raft_storage.set_hard_state(hs)
            self.raft_storage.append(ents)
            base = snap.metadata.index
            if self.backend != "tpu":
                return
            self._restore_data = RowRestore(
                term=hs.term,
                vote=hs.vote,
                commit=hs.commit,
                applied=base,
                snap_index=base,
                snap_term=snap.metadata.term,
                entries=[
                    (e.index, e.term, e.data, int(e.type))
                    for e in ents
                    if e.index > base
                ],
                # Membership at the snapshot point; conf entries in the
                # replayed tail re-apply through Ready on top of it.
                conf_state=(snap.metadata.conf_state
                            if snap.metadata.index > 0 else None),
            )
        else:
            self.wal = WAL.create(
                self.wal_dir, metadata=self.id.to_bytes(8, "big")
            )

    # -- loops -----------------------------------------------------------------

    def _tick_loop(self) -> None:
        while not self._stopped.wait(self.tick_interval):
            self.node.tick()

    def _serve_loop(self) -> None:
        while not self._stopped.is_set():
            rd = self.node.ready(timeout=0.1)
            if rd is None:
                continue
            self._process_ready(rd)

    def _process_ready(self, rd: Ready) -> None:
        if not is_empty_snap(rd.snapshot):
            self.storage.save_snap(rd.snapshot)
        self.wal.save(rd.hard_state, rd.entries, rd.must_sync)
        if not is_empty_snap(rd.snapshot):
            if self.backend == "host":
                self.raft_storage.apply_snapshot(rd.snapshot)
            self._publish_snapshot(rd.snapshot)
        if rd.entries and self.backend == "host":
            self.raft_storage.append(rd.entries)
        self.network.send(self.id, rd.messages)
        ok = self._publish_entries(self._entries_to_apply(rd.committed_entries))
        if not ok:
            self.stop()
            return
        self._maybe_trigger_snapshot()
        self.node.advance()

    def _entries_to_apply(self, ents: List[Entry]) -> List[Entry]:
        if not ents:
            return []
        first = ents[0].index
        if first > self.applied_index + 1:
            raise RuntimeError(
                f"first index of committed entry[{first}] should <= "
                f"progress.appliedIndex[{self.applied_index}]+1"
            )
        if self.applied_index - first + 1 < len(ents):
            return ents[self.applied_index - first + 1 :]
        return []

    def _publish_entries(self, ents: List[Entry]) -> bool:
        """Apply committed entries (ref: raftexample/raft.go publishEntries):
        normal data goes to the app; conf changes reconfigure raft and
        the network."""
        if not ents:
            return True
        data_ents: List[Entry] = []
        for e in ents:
            if e.type == EntryType.EntryNormal:
                if e.data:
                    data_ents.append(e)
            elif e.type == EntryType.EntryConfChange:
                cc = ConfChange.unmarshal(e.data)
                self.confstate = self.node.apply_conf_change(cc)
                if (
                    cc.type == ConfChangeType.ConfChangeRemoveNode
                    and cc.node_id == self.id
                ):
                    return False  # removed from the cluster: shut down
            elif e.type == EntryType.EntryConfChangeV2:
                ccv2 = ConfChangeV2.unmarshal(e.data)
                self.confstate = self.node.apply_conf_change(ccv2)
        if data_ents:
            self.apply_fn(data_ents)
        self.applied_index = ents[-1].index
        return True

    def _publish_snapshot(self, snap: Snapshot) -> None:
        if snap.metadata.index <= self.applied_index:
            raise RuntimeError(
                f"snapshot index [{snap.metadata.index}] should > "
                f"progress.appliedIndex[{self.applied_index}]"
            )
        self.confstate = snap.metadata.conf_state
        self.snapshot_index = snap.metadata.index
        self.applied_index = snap.metadata.index
        self.restore_fn(snap.data)

    def _maybe_trigger_snapshot(self) -> None:
        """ref: raftexample/raft.go maybeTriggerSnapshot."""
        if self.applied_index - self.snapshot_index <= self.snap_count:
            return
        data = self.snapshot_fn()
        if self.backend == "tpu":
            snap = self.node.create_snapshot(
                self.applied_index, self.confstate, data
            )
            self.storage.save_snap(snap)
            # Catch-up margin below the floor, like the host path: a
            # slightly-lagging follower replays entries instead of
            # taking a full snapshot (ref: raftexample/raft.go
            # snapshotCatchUpEntriesN).
            margin = min(SNAPSHOT_CATCHUP_ENTRIES,
                         self.node.cfg.window // 8)
            self.node.compact(
                max(1, self.applied_index - margin), snap)
        else:
            snap = self.raft_storage.create_snapshot(
                self.applied_index, self.confstate, data
            )
            self.storage.save_snap(snap)
            compact_index = 1
            if self.applied_index > SNAPSHOT_CATCHUP_ENTRIES:
                compact_index = self.applied_index - SNAPSHOT_CATCHUP_ENTRIES
            try:
                self.raft_storage.compact(compact_index)
            except Exception:  # noqa: BLE001 — already compacted is fine
                pass
        self.storage.release(snap)
        self.snapshot_index = self.applied_index

    # -- API -------------------------------------------------------------------

    def _receive(self, m: Message) -> None:
        try:
            self.node.step(m)
        except Exception:  # noqa: BLE001
            pass

    def propose(self, data: bytes, timeout: float = 5.0) -> None:
        self.node.propose(data, timeout=timeout)

    def propose_conf_change(self, cc, timeout: float = 5.0) -> None:
        self.node.propose_conf_change(cc, timeout=timeout)

    def is_leader(self) -> bool:
        st = self.node.status()
        return st.basic.soft_state.raft_state == StateType.StateLeader

    def leader(self) -> int:
        return self.node.status().basic.soft_state.lead

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self.network.unregister(self.id)
        self.node.stop()
        for t in (self._ticker, self._server):
            if t.is_alive() and t is not threading.current_thread():
                t.join(timeout=5)
        self.wal.close()
