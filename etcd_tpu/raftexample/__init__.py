"""The raftexample-equivalent: a minimal replicated KV on the raft core
(ref: contrib/raftexample — the canonical Ready loop outside etcdserver).

This is the reference's "one model running end-to-end" slice: ticker →
Node; proposal queue → MsgProp; Ready drain → WAL append/fsync →
message router → apply to an in-memory KV; in-proc N-node network with
fault injection for tests.
"""

from .transport import InProcNetwork  # noqa: F401
from .raftnode import ExampleRaftNode  # noqa: F401
from .kvstore import ReplicatedKV  # noqa: F401
