"""Peer transport (ref: server/etcdserver/api/rafthttp/).

The reference moves raft messages over HTTP/1.1 long-lived streams (one
writer/reader pair per peer) plus a POST-per-message pipeline for rare
big messages and a dedicated snapshot sender. This package keeps those
semantics — ordered stream per peer, drop-don't-block, pipeline
fallback, peer probing — over framed TCP:

* ``InProcNetwork`` (etcd_tpu/raftexample/transport.py) for in-process
  clusters;
* ``TCPTransport`` for real socket clusters (tests/e2e and deployment).
"""

from .codec import decode_message, encode_message  # noqa: F401
from .tcp import TCPTransport  # noqa: F401
