"""Peer-transport metric set (ref: server/etcdserver/api/rafthttp/metrics.go)."""

from __future__ import annotations

from ..pkg import metrics as m

peer_sent_bytes = m.counter(
    "etcd_network_peer_sent_bytes_total", "The total number of bytes sent to peers.", ("To",)
)
peer_received_bytes = m.counter(
    "etcd_network_peer_received_bytes_total", "The total number of bytes received from peers.", ("From",)
)
peer_sent_failures = m.counter(
    "etcd_network_peer_sent_failures_total", "The total number of send failures from peers.", ("To",)
)
snapshot_send_success = m.counter(
    "etcd_network_snapshot_send_success", "Total number of successful snapshot sends.", ("To",)
)
snapshot_send_failures = m.counter(
    "etcd_network_snapshot_send_failures", "Total number of snapshot send failures.", ("To",)
)
