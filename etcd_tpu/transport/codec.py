"""Binary wire codec for raft messages
(ref: rafthttp "message" codec, server/etcdserver/api/rafthttp/msg_codec.go —
length-prefixed marshaled Message; here a fixed struct header instead of
protobuf, same framing role).

Frame layout (little-endian):

    u32 total_len | header | context | entries... | snapshot?

    header: u8 type | u64 to | u64 from | u64 term | u64 log_term |
            u64 index | u64 commit | u8 reject | u64 reject_hint |
            u32 ctx_len | u32 n_entries | u8 has_snapshot
    entry:  u64 term | u64 index | u8 etype | u32 dlen | data
    snapshot: u64 index | u64 term | conf_state | u32 dlen | data
    conf_state: u32 counts ×4 | u8 auto_leave | u64 ids...
"""

from __future__ import annotations

import struct
from typing import List, Tuple

from ..raft.types import (
    ConfState,
    Entry,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    SnapshotMetadata,
    is_empty_snap,
)

_HDR = struct.Struct("<BQQQQQQBQIIB")
_ENT = struct.Struct("<QQBI")
_SNAP = struct.Struct("<QQ")
_CS = struct.Struct("<IIIIB")

MAX_FRAME = 512 << 20  # hard cap (2GiB max recv in the reference gRPC)


def encode_message(m: Message) -> bytes:
    parts = []
    has_snap = not is_empty_snap(m.snapshot)
    parts.append(
        _HDR.pack(
            int(m.type),
            m.to,
            m.from_,
            m.term,
            m.log_term,
            m.index,
            m.commit,
            1 if m.reject else 0,
            m.reject_hint,
            len(m.context),
            len(m.entries),
            1 if has_snap else 0,
        )
    )
    if m.context:
        parts.append(m.context)
    for e in m.entries:
        parts.append(_ENT.pack(e.term, e.index, int(e.type), len(e.data)))
        if e.data:
            parts.append(e.data)
    if has_snap:
        md = m.snapshot.metadata
        cs = md.conf_state
        parts.append(_SNAP.pack(md.index, md.term))
        ids = cs.voters + cs.learners + cs.voters_outgoing + cs.learners_next
        parts.append(
            _CS.pack(
                len(cs.voters),
                len(cs.learners),
                len(cs.voters_outgoing),
                len(cs.learners_next),
                1 if cs.auto_leave else 0,
            )
        )
        if ids:
            parts.append(struct.pack(f"<{len(ids)}Q", *ids))
        parts.append(struct.pack("<I", len(m.snapshot.data)))
        parts.append(m.snapshot.data)
    payload = b"".join(parts)
    return struct.pack("<I", len(payload)) + payload


def decode_message(payload: bytes) -> Message:
    (
        mtype,
        to,
        from_,
        term,
        log_term,
        index,
        commit,
        reject,
        reject_hint,
        ctx_len,
        n_entries,
        has_snap,
    ) = _HDR.unpack_from(payload)
    off = _HDR.size
    context = payload[off : off + ctx_len]
    off += ctx_len
    entries: List[Entry] = []
    for _ in range(n_entries):
        eterm, eindex, etype, dlen = _ENT.unpack_from(payload, off)
        off += _ENT.size
        data = payload[off : off + dlen]
        off += dlen
        entries.append(
            Entry(term=eterm, index=eindex, type=EntryType(etype), data=data)
        )
    snapshot = Snapshot()
    if has_snap:
        sindex, sterm = _SNAP.unpack_from(payload, off)
        off += _SNAP.size
        nv, nl, nvo, nln, auto_leave = _CS.unpack_from(payload, off)
        off += _CS.size
        n = nv + nl + nvo + nln
        ids = list(struct.unpack_from(f"<{n}Q", payload, off))
        off += 8 * n
        (dlen,) = struct.unpack_from("<I", payload, off)
        off += 4
        data = payload[off : off + dlen]
        off += dlen
        snapshot = Snapshot(
            data=data,
            metadata=SnapshotMetadata(
                conf_state=ConfState(
                    voters=ids[:nv],
                    learners=ids[nv : nv + nl],
                    voters_outgoing=ids[nv + nl : nv + nl + nvo],
                    learners_next=ids[nv + nl + nvo :],
                    auto_leave=bool(auto_leave),
                ),
                index=sindex,
                term=sterm,
            ),
        )
    return Message(
        type=MessageType(mtype),
        to=to,
        from_=from_,
        term=term,
        log_term=log_term,
        index=index,
        commit=commit,
        entries=entries,
        snapshot=snapshot,
        reject=bool(reject),
        reject_hint=reject_hint,
        context=context,
    )
