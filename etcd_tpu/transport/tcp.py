"""TCP peer transport (ref: server/etcdserver/api/rafthttp/transport.go,
peer.go, stream.go, pipeline.go, snapshot_sender.go).

Semantics preserved from the reference:

* one **ordered stream** per peer: a writer thread drains a bounded
  queue over a persistent connection — congested queues **drop**
  messages instead of blocking raft (raftNodeConfig comment,
  etcdserver/raft.go:108-111); raft's retries recover;
* a **pipeline** path for big/rare messages (MsgSnap): one-shot
  connections on worker threads so a slow snapshot never head-of-line
  blocks heartbeats (pipeline.go, 4 workers);
* **probing/ActiveSince**: reconnect loop tracks when a peer became
  reachable; send errors surface to raft via report_unreachable /
  report_snapshot (peer status, probing_status.go);
* **fault injection**: pause/resume per peer (rafthttp.Pausable,
  transport.go:420-441) and drop filters, used by the integration
  bridge-style tests.

Wire format: 16-byte hello (cluster_id, from_id) then length-prefixed
message frames (codec.py).
"""

from __future__ import annotations

import queue
import random
import socket
import ssl
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..raft.types import Message, MessageType
from . import metrics as smet
from .codec import MAX_FRAME, decode_message, encode_message

STREAM_BUF = 4096  # queued msgs per peer (streamBufSize stream.go:32)
PIPELINE_WORKERS = 4  # pipeline.go connPerPipeline
RECONNECT_INTERVAL = 0.1
_HELLO = struct.Struct("<QQ")
# First payload byte of a peer-RPC control frame; raft MessageType
# values stay well below 0xFF, so the channel is unambiguous.
CONTROL_BYTE = b"\xff"


def _is_snap(m: Message) -> bool:
    return m.type == MessageType.MsgSnap


class _Peer:
    """Outbound half of a peer (ref: rafthttp/peer.go:63-130)."""

    def __init__(self, transport: "TCPTransport", peer_id: int, addr: Tuple[str, int]):
        self.t = transport
        self.id = peer_id
        self.addr = addr
        self.q: "queue.Queue[Optional[Message]]" = queue.Queue(maxsize=STREAM_BUF)
        self.snap_q: "queue.Queue[Optional[Message]]" = queue.Queue(maxsize=16)
        self.paused = False
        self.active_since: float = 0.0
        self._stopped = threading.Event()
        self._writer = threading.Thread(target=self._stream_loop, daemon=True)
        self._snap_workers = [
            threading.Thread(target=self._pipeline_loop, daemon=True)
            for _ in range(PIPELINE_WORKERS)
        ]
        self._writer.start()
        for w in self._snap_workers:
            w.start()

    def send(self, m: Message) -> None:
        if self.paused:
            return
        q = self.snap_q if _is_snap(m) else self.q
        try:
            q.put_nowait(m)
        except queue.Full:
            # Drop, never block (etcdserver/raft.go:108-111). Raft's
            # probe/retry machinery recovers; tell it now.
            self.t._report_unreachable(self.id)

    def stop(self) -> None:
        self._stopped.set()
        for q in (self.q, self.snap_q):
            try:
                q.put_nowait(None)
            except queue.Full:
                pass

    # -- stream (persistent conn, ordered) ------------------------------------

    def _stream_loop(self) -> None:
        sock: Optional[socket.socket] = None
        while not self._stopped.is_set():
            m = self.q.get()
            if m is None or self._stopped.is_set():
                break
            frame = encode_message(m)
            sent = False
            for _attempt in (0, 1):
                if sock is None:
                    sock = self._dial()
                    if sock is None:
                        self.t._report_unreachable(self.id)
                        break  # drop m
                try:
                    sock.sendall(frame)
                    smet.peer_sent_bytes.labels(str(self.id)).inc(len(frame))
                    sent = True
                    break
                except OSError:
                    try:
                        sock.close()
                    except OSError:
                        pass
                    sock = None
                    self.active_since = 0.0
            if not sent:
                smet.peer_sent_failures.labels(str(self.id)).inc()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _pipeline_loop(self) -> None:
        """One-shot connection per big message (rafthttp/pipeline.go)."""
        while not self._stopped.is_set():
            m = self.snap_q.get()
            if m is None or self._stopped.is_set():
                return
            ok = False
            s = self._dial()
            if s is not None:
                try:
                    s.sendall(encode_message(m))
                    ok = True
                except OSError:
                    pass
                finally:
                    try:
                        s.close()
                    except OSError:
                        pass
            if _is_snap(m):
                self.t._report_snapshot(self.id, failure=not ok)
            if not ok:
                self.t._report_unreachable(self.id)

    def _dial(self) -> Optional[socket.socket]:
        try:
            s = socket.create_connection(self.addr, timeout=2.0)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.t._client_ssl is not None:
                # Peer channel TLS (ref: rafthttp dials through
                # transport.TLSInfo ClientConfig, listener.go:376).
                s = self.t._client_ssl.wrap_socket(
                    s, server_hostname=self.t._tls_server_name or self.addr[0])
            s.sendall(_HELLO.pack(self.t.cluster_id, self.t.member_id))
            if self.active_since == 0.0:
                self.active_since = time.monotonic()
            return s
        except OSError:  # covers ssl.SSLError
            return None


class TCPTransport:
    """ref: rafthttp/transport.go:97-132 Transport."""

    def __init__(
        self,
        member_id: int,
        cluster_id: int = 0,
        bind: Tuple[str, int] = ("127.0.0.1", 0),
        tls_info=None,
    ) -> None:
        self.member_id = member_id
        self.cluster_id = cluster_id
        # Peer-channel TLS both ways (ref: --peer-cert-file/--peer-key-file,
        # listener.go NewTLSListener on the server side).
        self._server_ssl = self._client_ssl = None
        self._tls_server_name = ""
        if tls_info is not None and not tls_info.empty():
            self._server_ssl = tls_info.server_context()
            self._client_ssl = tls_info.client_context()
            self._tls_server_name = tls_info.server_name
        self._lock = threading.Lock()
        self._peers: Dict[int, _Peer] = {}
        self._handler: Optional[Callable[[Message], None]] = None
        self._raft_reporter = None  # object with report_unreachable/report_snapshot
        self._stopped = threading.Event()
        self._drop: Dict[int, float] = {}  # peer_id -> drop probability (recv side)
        self._rand = random.Random(0)
        self._conns: List[socket.socket] = []  # accepted, closed on stop

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(bind)
        self._listener.listen(64)
        self.addr: Tuple[str, int] = self._listener.getsockname()
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accept_thread.start()

    # -- the network interface used by EtcdServer ------------------------------

    def register(self, node_id: int, handler: Callable[[Message], None],
                 reporter=None) -> None:
        assert node_id == self.member_id, "TCPTransport is per-member"
        self._handler = handler
        if reporter is not None and self._raft_reporter is None:
            # Wire snapshot-status reporting immediately so a server
            # that never calls set_raft_reporter (the richer node-object
            # path, which also feeds ReportUnreachable and overwrites
            # this) still unsticks StateSnapshot progress on failures.
            class _SnapOnly:
                @staticmethod
                def report_snapshot(vid: int, failure: bool) -> None:
                    reporter(vid, failure)

                @staticmethod
                def report_unreachable(vid: int) -> None:
                    pass

            self._raft_reporter = _SnapOnly()
            self._reporter_from_register = True

    def unregister(self, node_id: int) -> None:
        self._handler = None
        if getattr(self, "_reporter_from_register", False):
            # Drop the register()-installed reporter so a server
            # re-registered on this transport wires its OWN node, not
            # the dead predecessor's.
            self._raft_reporter = None
            self._reporter_from_register = False

    def send(self, _from_id: int, msgs: List[Message]) -> None:
        """ref: transport.go:175 Send — route each message to its peer."""
        for m in msgs:
            if m.to == self.member_id:
                if self._handler is not None:
                    self._handler(m)
                continue
            with self._lock:
                p = self._peers.get(m.to)
            if p is not None:
                p.send(m)

    def set_raft_reporter(self, node) -> None:
        """Wire ReportUnreachable/ReportSnapshot back into raft
        (ref: node.go:535-549 via transport error paths)."""
        self._raft_reporter = node

    # -- peer management (transport.go:295 AddPeer) ----------------------------

    def add_peer(self, peer_id: int, addr: Tuple[str, int]) -> None:
        with self._lock:
            if peer_id in self._peers or peer_id == self.member_id:
                return
            self._peers[peer_id] = _Peer(self, peer_id, tuple(addr))

    def remove_peer(self, peer_id: int) -> None:
        with self._lock:
            p = self._peers.pop(peer_id, None)
        if p is not None:
            p.stop()

    def update_peer(self, peer_id: int, addr: Tuple[str, int]) -> None:
        self.remove_peer(peer_id)
        self.add_peer(peer_id, addr)

    def active_since(self, peer_id: int) -> float:
        with self._lock:
            p = self._peers.get(peer_id)
        return p.active_since if p is not None else 0.0

    # -- fault injection (rafthttp.Pausable + bridge drops) --------------------

    def pause_sending(self, peer_id: Optional[int] = None) -> None:
        with self._lock:
            for pid, p in self._peers.items():
                if peer_id is None or pid == peer_id:
                    p.paused = True

    def resume_sending(self, peer_id: Optional[int] = None) -> None:
        with self._lock:
            for pid, p in self._peers.items():
                if peer_id is None or pid == peer_id:
                    p.paused = False

    def drop_from(self, peer_id: int, prob: float) -> None:
        """Drop incoming messages from peer_id with probability prob."""
        with self._lock:
            self._drop[peer_id] = prob

    # -- inbound ---------------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            with self._lock:
                self._conns.append(conn)
            threading.Thread(
                target=self._recv_loop, args=(conn,), daemon=True
            ).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._server_ssl is not None:
                # Handshake on the per-conn thread so a stalled dialer
                # can't block the accept loop.
                try:
                    conn = self._server_ssl.wrap_socket(conn, server_side=True)
                except OSError:  # covers ssl.SSLError
                    return
            hello = self._read_exact(conn, _HELLO.size)
            if hello is None:
                return
            cid, from_id = _HELLO.unpack(hello)
            if cid != self.cluster_id:
                return  # cluster-id mismatch rejected (http.go checks)
            while not self._stopped.is_set():
                ln_b = self._read_exact(conn, 4)
                if ln_b is None:
                    return
                (ln,) = struct.unpack("<I", ln_b)
                if ln > MAX_FRAME:
                    return
                payload = self._read_exact(conn, ln)
                if payload is None:
                    return
                smet.peer_received_bytes.labels(str(from_id)).inc(4 + ln)
                with self._lock:
                    drop = self._drop.get(from_id, 0.0)
                if drop and self._rand.random() < drop:
                    continue
                if payload[:1] == CONTROL_BYTE:
                    # Peer-RPC side channel (the analog of the extra
                    # handlers on the reference's peer listener —
                    # hashKVHandler etc., corrupt.go:261).
                    resp = self._handle_control(payload[1:])
                    try:
                        conn.sendall(struct.pack("<I", len(resp)) + resp)
                    except OSError:
                        return
                    continue
                m = decode_message(payload)
                h = self._handler
                if h is not None:
                    try:
                        h(m)
                    except Exception:  # noqa: BLE001
                        pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- peer-RPC control channel ----------------------------------------------

    def set_hash_provider(self, fn: Callable[[], Tuple[int, int, int]]) -> None:
        """fn() -> (hash, revision, compact_revision) — the tuple order
        of mvcc ``hash_kv``; served to peers asking over the control
        channel (ref: corrupt.go:261 hashKVHandler on the peer
        listener)."""
        self._hash_provider = fn

    def _handle_control(self, body: bytes) -> bytes:
        import json

        try:
            req = json.loads(body)
        except ValueError:
            return b"{}"
        if req.get("op") == "hashkv":
            fn = getattr(self, "_hash_provider", None)
            if fn is None:
                return b"{}"
            try:
                h, rev, crev = fn()
            except Exception:  # noqa: BLE001
                return b"{}"
            return json.dumps({
                "member_id": self.member_id, "hash": h,
                "compact_revision": crev, "revision": rev,
            }).encode()
        return b"{}"

    def peer_hash_kv(self, peer_id: int, timeout: float = 3.0):
        """One-shot control query to a peer's listener; None when the
        peer is unreachable or doesn't answer."""
        import json

        with self._lock:
            p = self._peers.get(peer_id)
        if p is None:
            return None
        try:
            s = p._dial()
            if s is None:
                return None
            try:
                s.settimeout(timeout)
                body = CONTROL_BYTE + json.dumps({"op": "hashkv"}).encode()
                s.sendall(struct.pack("<I", len(body)) + body)
                ln_b = self._read_exact(s, 4)
                if ln_b is None:
                    return None
                (ln,) = struct.unpack("<I", ln_b)
                if ln > MAX_FRAME:
                    return None
                resp = self._read_exact(s, ln)
                if resp is None:
                    return None
                out = json.loads(resp)
                return out if "hash" in out else None
            finally:
                try:
                    s.close()
                except OSError:
                    pass
        except (OSError, ValueError):
            return None

    @staticmethod
    def _read_exact(conn: socket.socket, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            try:
                chunk = conn.recv(n - len(buf))
            except OSError:
                return None
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- raft feedback ---------------------------------------------------------

    def _report_unreachable(self, peer_id: int) -> None:
        r = self._raft_reporter
        if r is not None:
            try:
                r.report_unreachable(peer_id)
            except Exception:  # noqa: BLE001
                pass

    def _report_snapshot(self, peer_id: int, failure: bool) -> None:
        r = self._raft_reporter
        if r is not None:
            try:
                r.report_snapshot(peer_id, failure)
            except Exception:  # noqa: BLE001
                pass

    def stop(self) -> None:
        self._stopped.set()
        try:
            # shutdown() wakes the thread blocked in accept(); a bare
            # close() would leave the fd held by the syscall and the
            # port in LISTEN until process exit.
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            peers = list(self._peers.values())
            self._peers.clear()
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for p in peers:
            p.stop()
