"""Cross-member corruption detection
(ref: server/etcdserver/corrupt.go:39 CheckInitialHashKV,
:123 monitorKVHash, :151 checkHashKV).

The checker compares this member's hash-KV against every peer's at the
same (revision, compact_revision) coordinates. A mismatch at boot
refuses to serve; a mismatch while running raises the CORRUPT alarm
through raft against the deviant member (or this one, if the leader
itself diverges), which fences all writes cluster-wide
(apply.py AlarmApplier).

Peer hashes arrive through a pluggable fetcher (corrupt.go's Hasher /
peerHashKVHTTP seam): the embed layer wires it to the peer transport's
control channel (the hash-KV analog of the reference's extra handlers
on the peer listener); in-proc harnesses wire it straight to sibling
server objects.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, List, Optional

from .api import AlarmAction, AlarmRequest, AlarmType


class CorruptCheckError(Exception):
    """ref: etcdserver.ErrCorrupt — boot-time divergence."""


@dataclass
class PeerHashKV:
    """One peer's answer (ref: corrupt.go peerHashKVResp)."""

    member_id: int
    hash: int
    compact_revision: int
    revision: int


# fetcher(peer_id) -> PeerHashKV | None (unreachable peers return None,
# matching corrupt.go's skip-on-error behavior)
PeerHashFetcher = Callable[[int], Optional[PeerHashKV]]


class CorruptionChecker:
    """ref: corrupt.go corruptionChecker."""

    def __init__(self, server, fetcher: PeerHashFetcher) -> None:
        self.s = server
        self.fetch = fetcher
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- boot (corrupt.go:39 CheckInitialHashKV) -------------------------------

    def initial_check(self) -> None:
        """Compare against every reachable peer; same coordinates with
        a different hash is fatal at boot (we cannot know which side is
        corrupt, so refuse to serve)."""
        h, rev, crev = self.s.hash_kv(0)
        for pid in self._peer_ids():
            p = self.fetch(pid)
            if p is None:
                continue  # mirrors corrupt.go: unreachable peers skipped
            if p.revision == rev and p.compact_revision == crev \
                    and p.hash != h:
                raise CorruptCheckError(
                    f"found data inconsistency with peer {pid:x} "
                    f"(revision {rev}, compact_revision {crev}, "
                    f"hash {h} != peer hash {p.hash})")

    # -- runtime (corrupt.go:123 monitorKVHash) --------------------------------

    def start_periodic(self, interval: float) -> None:
        def loop() -> None:
            while not self._stop.wait(interval):
                if not self.s.is_leader():
                    continue  # leader-only, corrupt.go:131
                try:
                    self.periodic_check()
                except Exception:  # noqa: BLE001 — keep monitoring
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name="corruption-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def periodic_check(self) -> None:
        """One comparison pass (corrupt.go:151 checkHashKV). Raises the
        CORRUPT alarm through raft against whichever member diverged."""
        h, rev, crev = self.s.hash_kv(0)
        bad: List[int] = []
        for pid in self._peer_ids():
            p = self.fetch(pid)
            if p is None:
                continue
            # Only same-coordinate comparisons are meaningful: a peer
            # at another revision/compaction window legitimately hashes
            # differently (corrupt.go:200-231).
            if p.revision == rev and p.compact_revision == crev \
                    and p.hash != h:
                bad.append(pid)
        if not bad:
            return
        peers = len(self._peer_ids())
        if len(bad) >= 2 and len(bad) > peers // 2:
            # Two or more peers agree against us → we are the deviant.
            # A single divergent peer is always blamed directly (in a
            # 2-member cluster there is no majority to invert on).
            targets = [self.s.id]
        else:
            targets = bad
        for mid in targets:
            self._alarm_corrupt(mid)

    def _alarm_corrupt(self, member_id: int) -> None:
        try:
            self.s.alarm(AlarmRequest(
                action=AlarmAction.ACTIVATE,
                member_id=member_id,
                alarm=AlarmType.CORRUPT,
            ))
        except Exception:  # noqa: BLE001 — alarm is best-effort;
            pass           # the next pass retries

    def _peer_ids(self) -> List[int]:
        return [m.id for m in self.s.cluster.member_list()
                if m.id != self.s.id]


def transport_peer_fetcher(transport) -> PeerHashFetcher:
    """Fetcher over the peer transport's control channel (the embed
    wiring — the hash-KV analog of the reference's extra handlers on
    the peer listener, corrupt.go:261 hashKVHandler)."""

    def fetch(pid: int) -> Optional[PeerHashKV]:
        out = transport.peer_hash_kv(pid)
        if out is None:
            return None
        return PeerHashKV(
            member_id=out.get("member_id", pid), hash=out["hash"],
            compact_revision=out["compact_revision"],
            revision=out["revision"])

    return fetch


def inproc_peer_fetcher(servers_by_id) -> PeerHashFetcher:
    """Fetcher over sibling in-proc server objects (test harnesses)."""

    def fetch(pid: int) -> Optional[PeerHashKV]:
        peer = servers_by_id().get(pid) if callable(servers_by_id) \
            else servers_by_id.get(pid)
        if peer is None:
            return None
        try:
            h, rev, crev = peer.hash_kv(0)
        except Exception:  # noqa: BLE001
            return None
        return PeerHashKV(member_id=pid, hash=h,
                          compact_revision=crev, revision=rev)

    return fetch
