"""The applier chain: decorated interpreter for committed
InternalRaftRequests (ref: server/etcdserver/apply.go).

``ApplierBackend`` executes each op against mvcc/lease/auth/alarm;
wrapped by ``AuthApplier`` (apply-time permission re-check,
apply_auth.go), ``QuotaApplier`` (backend-size gate → NOSPACE,
apply.go:974) and ``AlarmApplier`` (corrupt/nospace write fence,
corrupt.go:306 + applierV3Capped semantics).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

from ..auth.store import AuthInfo, PermissionType, Permission
from ..lease.lessor import LeaseItem, LeaseNotFoundError, NoLease
from ..storage.mvcc.kv import KeyValue, RangeOptions
from ..storage.mvcc import metrics as mmet
from .api import (
    AlarmAction,
    AlarmMember,
    AlarmRequest,
    AlarmResponse,
    AuthRequest,
    Compare,
    CompareResult,
    CompareTarget,
    CompactionRequest,
    CompactionResponse,
    DeleteRangeRequest,
    DeleteRangeResponse,
    InternalRaftRequest,
    LeaseCheckpointRequest,
    LeaseGrantRequest,
    LeaseGrantResponse,
    LeaseRevokeRequest,
    LeaseRevokeResponse,
    PutRequest,
    PutResponse,
    RangeRequest,
    RangeResponse,
    RequestOp,
    ResponseHeader,
    ResponseOp,
    SortOrder,
    SortTarget,
    TxnRequest,
    TxnResponse,
)


class NoSpaceError(Exception):
    """ref: rpctypes.ErrNoSpace."""


class CorruptError(Exception):
    """ref: rpctypes.ErrCorrupt."""


class LeaseNotFound(Exception):
    """ref: rpctypes.ErrLeaseNotFound (apply-level)."""


@dataclass
class ApplyResult:
    """ref: apply.go:56-60 applyResult."""

    resp: Any = None
    err: Optional[Exception] = None
    physc: Any = None  # compaction completion signal


class ApplierBackend:
    """ref: apply.go:104-133 applierV3backend."""

    def __init__(self, server) -> None:
        self.s = server

    # -- dispatch (apply.go:135-249 Apply) -------------------------------------

    def apply(self, r: InternalRaftRequest) -> ApplyResult:
        op = r.op
        try:
            if op == "put":
                return ApplyResult(resp=self.put(r.req))
            if op == "range":
                return ApplyResult(resp=self.range(r.req))
            if op == "delete_range":
                return ApplyResult(resp=self.delete_range(r.req))
            if op == "txn":
                return ApplyResult(resp=self.txn(r.req))
            if op == "compaction":
                return ApplyResult(resp=self.compaction(r.req))
            if op == "lease_grant":
                return ApplyResult(resp=self.lease_grant(r.req))
            if op == "lease_revoke":
                return ApplyResult(resp=self.lease_revoke(r.req))
            if op == "lease_checkpoint":
                return ApplyResult(resp=self.lease_checkpoint(r.req))
            if op == "alarm":
                return ApplyResult(resp=self.alarm(r.req))
            if op == "auth":
                return ApplyResult(resp=self.auth_dispatch(r))
            if op == "cluster_member_attr":
                self.s.cluster.update_member_attr(
                    r.req["id"], r.req["name"], r.req["client_urls"]
                )
                return ApplyResult(resp=None)
            return ApplyResult(err=ValueError(f"unknown apply op {op!r}"))
        except Exception as e:  # noqa: BLE001 — applied errors go to the waiter
            return ApplyResult(err=e)

    def _header(self) -> ResponseHeader:
        return self.s.response_header()

    # -- kv ops ----------------------------------------------------------------

    def put(self, p: PutRequest, txn=None) -> PutResponse:
        """ref: apply.go:251-332 Put."""
        mmet.put_total.inc()
        resp = PutResponse(header=self._header())
        owned = txn is None
        if owned:
            txn = self.s.kv.write()
            txn.__enter__()
        try:
            prev: Optional[KeyValue] = None
            if p.prev_kv or p.ignore_value or p.ignore_lease:
                rr = txn.range(p.key, None)
                prev = rr.kvs[0] if rr.kvs else None
            val, lease = p.value, p.lease
            if p.ignore_value or p.ignore_lease:
                if prev is None:
                    raise KeyError("etcdserver: key not found")
                if p.ignore_value:
                    val = prev.value
                if p.ignore_lease:
                    lease = prev.lease
            if lease != NoLease and self.s.lessor is not None:
                if self.s.lessor.lookup(lease) is None:
                    raise LeaseNotFound(str(lease))
            txn.put(p.key, val, lease)
            if p.prev_kv and prev is not None:
                resp.prev_kv = prev
        finally:
            if owned:
                txn.__exit__(None, None, None)
        resp.header.revision = self.s.kv.rev()
        return resp

    def delete_range(
        self, dr: DeleteRangeRequest, txn=None
    ) -> DeleteRangeResponse:
        """ref: apply.go DeleteRange."""
        mmet.delete_total.inc()
        resp = DeleteRangeResponse(header=self._header())
        owned = txn is None
        if owned:
            txn = self.s.kv.write()
            txn.__enter__()
        try:
            end = _resolve_end(dr.range_end)
            if dr.prev_kv:
                rr = txn.range(dr.key, end, RangeOptions(limit=0))
                resp.prev_kvs = rr.kvs
            resp.deleted = txn.delete_range(dr.key, end)
        finally:
            if owned:
                txn.__exit__(None, None, None)
        resp.header.revision = self.s.kv.rev()
        return resp

    def range(self, rreq: RangeRequest, txn=None) -> RangeResponse:
        """ref: apply.go:334-439 Range."""
        mmet.range_total.inc()
        resp = RangeResponse(header=self._header())
        end = _resolve_end(rreq.range_end)

        limit = rreq.limit
        if (
            rreq.sort_order != SortOrder.NONE
            or rreq.min_mod_revision != 0
            or rreq.max_mod_revision != 0
            or rreq.min_create_revision != 0
            or rreq.max_create_revision != 0
        ):
            limit = 0  # fetch everything, filter/sort below (apply.go:354-360)
        opts = RangeOptions(
            limit=limit + 1 if limit > 0 else 0,
            rev=rreq.revision,
            count_only=rreq.count_only,
        )
        src = txn if txn is not None else self.s.kv
        rr = src.range(rreq.key, end, opts)
        kvs = rr.kvs

        def keep(kv: KeyValue) -> bool:
            if rreq.min_mod_revision and kv.mod_revision < rreq.min_mod_revision:
                return False
            if rreq.max_mod_revision and kv.mod_revision > rreq.max_mod_revision:
                return False
            if rreq.min_create_revision and kv.create_revision < rreq.min_create_revision:
                return False
            if rreq.max_create_revision and kv.create_revision > rreq.max_create_revision:
                return False
            return True

        filtered = rreq.min_mod_revision or rreq.max_mod_revision or \
            rreq.min_create_revision or rreq.max_create_revision
        if filtered:
            kvs = [kv for kv in kvs if keep(kv)]

        if rreq.sort_order != SortOrder.NONE:
            keyfn = {
                SortTarget.KEY: lambda kv: kv.key,
                SortTarget.VERSION: lambda kv: kv.version,
                SortTarget.CREATE: lambda kv: kv.create_revision,
                SortTarget.MOD: lambda kv: kv.mod_revision,
                SortTarget.VALUE: lambda kv: kv.value,
            }[rreq.sort_target]
            kvs = sorted(
                kvs, key=keyfn, reverse=rreq.sort_order == SortOrder.DESCEND
            )

        if rreq.limit > 0 and len(kvs) > rreq.limit:
            kvs = kvs[: rreq.limit]
            resp.more = True

        if rreq.keys_only:
            kvs = [
                KeyValue(
                    key=kv.key,
                    create_revision=kv.create_revision,
                    mod_revision=kv.mod_revision,
                    version=kv.version,
                    lease=kv.lease,
                )
                for kv in kvs
            ]
        resp.kvs = kvs
        resp.count = rr.count if not filtered else len(kvs)
        resp.header.revision = rr.rev
        return resp

    # -- txn (apply.go:441-680) ------------------------------------------------

    def txn(self, tr: TxnRequest) -> TxnResponse:
        mmet.txn_total.inc()
        is_write = _is_txn_write(tr)
        if is_write:
            txn = self.s.kv.write()
            txn.__enter__()
        else:
            txn = None
        try:
            succeeded = all(self._apply_compare(c, txn) for c in tr.compare)
            reqs = tr.success if succeeded else tr.failure
            resps = [self._apply_txn_op(op, txn) for op in reqs]
        finally:
            if txn is not None:
                txn.__exit__(None, None, None)
        resp = TxnResponse(
            header=self._header(), succeeded=succeeded, responses=resps
        )
        resp.header.revision = self.s.kv.rev()
        return resp

    def _apply_compare(self, c: Compare, txn) -> bool:
        """ref: apply.go applyCompare."""
        end = _resolve_end(c.range_end)
        src = txn if txn is not None else self.s.kv
        rr = src.range(c.key, end, RangeOptions())
        if not rr.kvs:
            if c.target == CompareTarget.VALUE:
                # Missing key never satisfies a VALUE compare.
                return False
            return _compare_kv(c, KeyValue())
        return all(_compare_kv(c, kv) for kv in rr.kvs)

    def _apply_txn_op(self, op: RequestOp, txn) -> ResponseOp:
        if op.request_range is not None:
            return ResponseOp(response_range=self.range(op.request_range, txn))
        if op.request_put is not None:
            return ResponseOp(response_put=self.put(op.request_put, txn))
        if op.request_delete_range is not None:
            return ResponseOp(
                response_delete_range=self.delete_range(op.request_delete_range, txn)
            )
        if op.request_txn is not None:
            # Nested txn shares the outer write txn (apply.go applyTxn).
            sub = op.request_txn
            succeeded = all(self._apply_compare(c, txn) for c in sub.compare)
            reqs = sub.success if succeeded else sub.failure
            resps = [self._apply_txn_op(o, txn) for o in reqs]
            return ResponseOp(
                response_txn=TxnResponse(
                    header=self._header(), succeeded=succeeded, responses=resps
                )
            )
        return ResponseOp()

    # -- maintenance ops -------------------------------------------------------

    def compaction(self, creq: CompactionRequest) -> CompactionResponse:
        resp = CompactionResponse(header=self._header())
        self.s.kv.compact(creq.revision)
        resp.header.revision = self.s.kv.rev()
        return resp

    def lease_grant(self, lg: LeaseGrantRequest) -> LeaseGrantResponse:
        lease = self.s.lessor.grant(lg.id, lg.ttl)
        return LeaseGrantResponse(
            header=self._header(), id=lease.id, ttl=lease.ttl
        )

    def lease_revoke(self, lr: LeaseRevokeRequest) -> LeaseRevokeResponse:
        try:
            self.s.lessor.revoke(lr.id)
        except LeaseNotFoundError:
            raise LeaseNotFound(str(lr.id))
        return LeaseRevokeResponse(header=self._header())

    def lease_checkpoint(self, lc: LeaseCheckpointRequest):
        for cp in lc.checkpoints:
            try:
                self.s.lessor.checkpoint(cp.id, cp.remaining_ttl)
            except LeaseNotFoundError:
                pass
        return None

    def alarm(self, ar: AlarmRequest) -> AlarmResponse:
        """ref: apply.go Alarm → v3alarm store."""
        resp = AlarmResponse(header=self._header())
        if ar.action == AlarmAction.GET:
            resp.alarms = self.s.alarms.get(ar.alarm)
        elif ar.action == AlarmAction.ACTIVATE:
            m = self.s.alarms.activate(ar.member_id, ar.alarm)
            if m is not None:
                resp.alarms = [m]
        elif ar.action == AlarmAction.DEACTIVATE:
            m = self.s.alarms.deactivate(ar.member_id, ar.alarm)
            if m is not None:
                resp.alarms = [m]
        return resp

    # -- auth (apply_auth dispatch over AuthStore) -----------------------------

    def auth_dispatch(self, r: InternalRaftRequest):
        a: AuthRequest = r.req
        st = self.s.auth_store
        op = a.op
        if op == "enable":
            st.auth_enable()
        elif op == "disable":
            st.auth_disable()
        elif op == "user_add":
            st.user_add(a.name, a.password, no_password=a.no_password)
        elif op == "user_delete":
            st.user_delete(a.name)
        elif op == "user_change_password":
            st.user_change_password(a.name, a.password)
        elif op == "user_grant_role":
            st.user_grant_role(a.name, a.role)
        elif op == "user_revoke_role":
            st.user_revoke_role(a.name, a.role)
        elif op == "role_add":
            st.role_add(a.role)
        elif op == "role_delete":
            st.role_delete(a.role)
        elif op == "role_grant_permission":
            st.role_grant_permission(
                a.role,
                Permission(PermissionType(a.perm_type), a.key, a.range_end),
            )
        elif op == "role_revoke_permission":
            st.role_revoke_permission(a.role, a.key, a.range_end)
        else:
            raise ValueError(f"unknown auth op {op!r}")
        return {"revision": st.revision()}


def _resolve_end(range_end: bytes) -> Optional[bytes]:
    """etcd range_end semantics (ref: rpc.proto RangeRequest doc):
    b"" → the single key; b"\\x00" → open end (every key ≥ key, the
    'range over all keys ≥ key' sentinel); else literal exclusive end.
    Internally None = single key, b"" = open end."""
    if not range_end:
        return None
    if range_end == b"\x00":
        return b""
    return range_end


def _is_txn_write(tr: TxnRequest) -> bool:
    for ops in (tr.success, tr.failure):
        for op in ops:
            if op.request_put is not None or op.request_delete_range is not None:
                return True
            if op.request_txn is not None and _is_txn_write(op.request_txn):
                return True
    return False


def _compare_kv(c: Compare, kv: KeyValue) -> bool:
    """ref: apply.go compareKV."""
    if c.target == CompareTarget.VALUE:
        result = _cmp(kv.value, c.value)
    elif c.target == CompareTarget.VERSION:
        result = _cmp(kv.version, c.version)
    elif c.target == CompareTarget.CREATE:
        result = _cmp(kv.create_revision, c.create_revision)
    elif c.target == CompareTarget.MOD:
        result = _cmp(kv.mod_revision, c.mod_revision)
    elif c.target == CompareTarget.LEASE:
        result = _cmp(kv.lease, c.lease)
    else:
        return False
    if c.result == CompareResult.EQUAL:
        return result == 0
    if c.result == CompareResult.NOT_EQUAL:
        return result != 0
    if c.result == CompareResult.GREATER:
        return result > 0
    if c.result == CompareResult.LESS:
        return result < 0
    return False


def _cmp(a, b) -> int:
    return (a > b) - (a < b)


# -- decorators ----------------------------------------------------------------


class AuthApplier:
    """Apply-time permission re-check (ref: apply_auth.go). The raft
    proposal carries the author's username+auth_revision; if auth state
    moved on since, the request fails with AuthOldRevision and the
    client retries with a fresh token."""

    def __init__(self, base: ApplierBackend, auth_store) -> None:
        self.base = base
        self.st = auth_store

    def apply(self, r: InternalRaftRequest) -> ApplyResult:
        info = AuthInfo(username=r.username, revision=r.auth_revision)
        try:
            if r.op == "put":
                self.st.is_put_permitted(info if r.username else None, r.req.key)
            elif r.op == "delete_range":
                self.st.is_delete_range_permitted(
                    info if r.username else None, r.req.key, r.req.range_end
                )
            elif r.op == "range":
                self.st.is_range_permitted(
                    info if r.username else None, r.req.key, r.req.range_end
                )
            elif r.op == "txn":
                self._check_txn(info if r.username else None, r.req)
            elif r.op == "auth" and r.req.op not in ("enable",):
                # Admin ops require root once auth is on (apply_auth.go).
                if self.st.is_auth_enabled():
                    self.st.is_admin_permitted(info if r.username else None)
        except Exception as e:  # noqa: BLE001
            return ApplyResult(err=e)
        return self.base.apply(r)

    def _check_txn(self, info, tr: TxnRequest) -> None:
        """ref: apply_auth.go checkTxnAuth."""
        for c in tr.compare:
            self.st.is_range_permitted(info, c.key, c.range_end)
        for ops in (tr.success, tr.failure):
            for op in ops:
                if op.request_range is not None:
                    self.st.is_range_permitted(
                        info, op.request_range.key, op.request_range.range_end
                    )
                elif op.request_put is not None:
                    self.st.is_put_permitted(info, op.request_put.key)
                elif op.request_delete_range is not None:
                    self.st.is_delete_range_permitted(
                        info,
                        op.request_delete_range.key,
                        op.request_delete_range.range_end,
                    )
                elif op.request_txn is not None:
                    self._check_txn(info, op.request_txn)


class QuotaApplier:
    """Backend-size write fence (ref: apply.go:974 quotaApplier +
    storage/quota.go). Oversize writes fail with NoSpace and the server
    raises the NOSPACE alarm through raft."""

    def __init__(self, base, server) -> None:
        self.base = base
        self.s = server

    def apply(self, r: InternalRaftRequest) -> ApplyResult:
        if r.op in ("put", "txn", "lease_grant"):
            if not self.s.quota_available(r):
                self.s.maybe_raise_nospace_alarm()
                return ApplyResult(err=NoSpaceError())
        return self.base.apply(r)


class AlarmApplier:
    """Write fence while an alarm is active
    (ref: server.go checkAlarms + applierV3Capped/corrupt)."""

    WRITE_OPS = {"put", "delete_range", "txn", "lease_grant"}

    def __init__(self, base, server) -> None:
        self.base = base
        self.s = server

    def apply(self, r: InternalRaftRequest) -> ApplyResult:
        from .api import AlarmType

        active = self.s.alarms.active_types()
        if AlarmType.CORRUPT in active:
            # Alarm ops must pass the fence or DEACTIVATE could never
            # disarm it (ref: corrupt.go applierV3Corrupt wraps only
            # KV/lease ops; Alarm goes to the base applier).
            if r.op == "alarm":
                return self.base.apply(r)
            return ApplyResult(err=CorruptError())
        if AlarmType.NOSPACE in active and r.op in self.WRITE_OPS:
            if not (r.op == "txn" and not _is_txn_write(r.req)):
                return ApplyResult(err=NoSpaceError())
        return self.base.apply(r)
