"""Server metric set (ref: server/etcdserver/metrics.go) — same metric
names so dashboards port over.

Like the reference's prometheus default registry, metrics are
process-global: one member per process is the deployment model. In-proc
multi-member test clusters share the registry, so per-member gauges
(is_leader/has_leader) reflect the last member that wrote them; assert
on monotonic counters in such harnesses."""

from __future__ import annotations

from ..pkg import metrics as m

has_leader = m.gauge(
    "etcd_server_has_leader", "Whether or not a leader exists. 1 is existence, 0 is not."
)
is_leader = m.gauge(
    "etcd_server_is_leader", "Whether or not this member is a leader. 1 if is, 0 otherwise."
)
leader_changes = m.counter(
    "etcd_server_leader_changes_seen_total", "The number of leader changes seen."
)
proposals_committed = m.gauge(
    "etcd_server_proposals_committed_total", "The total number of consensus proposals committed."
)
proposals_applied = m.gauge(
    "etcd_server_proposals_applied_total", "The total number of consensus proposals applied."
)
proposals_pending = m.gauge(
    "etcd_server_proposals_pending", "The current number of pending proposals to commit."
)
proposals_failed = m.counter(
    "etcd_server_proposals_failed_total", "The total number of failed proposals seen."
)
slow_read_indexes = m.counter(
    "etcd_server_slow_read_indexes_total", "The total number of pending read indexes not in sync with leader's or timed out read index requests."
)
read_indexes_failed = m.counter(
    "etcd_server_read_indexes_failed_total", "The total number of failed read indexes seen."
)
slow_applies = m.counter(
    "etcd_server_slow_apply_total", "The total number of slow apply requests (likely overloaded from slow disk)."
)
heartbeat_send_failures = m.counter(
    "etcd_server_heartbeat_send_failures_total", "The total number of leader heartbeat send failures (likely overloaded from slow disk)."
)
snapshot_apply_in_progress = m.gauge(
    "etcd_server_snapshot_apply_in_progress_total", "1 if the server is applying the incoming snapshot. 0 if none."
)
learner_promote_succeed = m.counter(
    "etcd_server_learner_promote_successes", "The total number of successful learner promotions while this member is leader."
)
apply_duration = m.histogram(
    "etcd_server_apply_duration_seconds", "The latency distributions of v2 apply called by backend.",
)

client_grpc_sent_bytes = m.counter(
    "etcd_network_client_grpc_sent_bytes_total", "The total number of bytes sent to grpc clients."
)
client_grpc_received_bytes = m.counter(
    "etcd_network_client_grpc_received_bytes_total", "The total number of bytes received from grpc clients."
)

lease_granted = m.counter(
    "etcd_debugging_lease_granted_total", "The total number of granted leases."
)
lease_revoked = m.counter(
    "etcd_debugging_lease_revoked_total", "The total number of revoked leases."
)
lease_renewed = m.counter(
    "etcd_debugging_lease_renewed_total", "The number of renewed leases seen by the leader."
)
