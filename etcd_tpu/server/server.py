"""EtcdServer: the replicated server core
(ref: server/etcdserver/server.go, raft.go, v3_server.go).

One member = one EtcdServer: raft Node + WAL/snap + backend-backed
subsystems (mvcc watchable KV, lessor, auth, alarms, membership) wired
the reference's way:

* **write path** (v3_server.go:672 processInternalRaftRequestOnce):
  auth-check → id → wait.register → propose → raft commit → applier
  chain (exactly once via consistent index) → wait.trigger unblocks the
  caller with the applied response;
* **read path** (v3_server.go:738 linearizableReadLoop): batch
  ReadIndex rounds — confirm leadership with a heartbeat quorum, wait
  until applied_index ≥ confirmed commit index, serve from mvcc;
* **Ready loop** (etcdserver/raft.go:158-315): apply is scheduled
  async on a FIFO scheduler; the leader sends messages *before* the
  WAL fsync (raft thesis 10.2.1), followers after; snapshot file
  persists before the WAL marker;
* **leadership changes** promote/demote the lessor (primary-only lease
  expiry) and gate lease renew/timetolive on the primary;
* **expired leases** surface from the lessor and are revoked through
  raft proposals (server.go:1120-1165 run.lessor expiry case);
* **snapshots** carry the whole backend (the reference streams the
  bbolt .snap.db and reopens it on the receiver — applySnapshot
  server.go:925; here the sqlite file rides the raft snapshot message).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..auth.store import AuthInfo, AuthStore
from ..auth.simple_token import SimpleTokenProvider
from ..lease.lessor import (
    Lessor, LeaseItem, LeaseNotFoundError, NoLease, NotPrimaryError,
)
from ..pkg import failpoint
from ..pkg.idutil import Generator
from ..pkg.schedule import FIFOScheduler
from ..pkg.wait import Wait, WaitTime
from ..raft.node import Node, Peer
from ..raft.raft import Config, NONE, StateType
from ..raft.rawnode import Ready
from ..raft.storage import MemoryStorage
from ..raft.types import (
    ConfChange,
    ConfState,
    ConfChangeType,
    ConfChangeV2,
    Entry,
    EntryType,
    Message,
    MessageType,
    Snapshot,
    is_empty_hard_state,
    is_empty_snap,
)
from ..storage import backend as bk
from ..storage.mvcc.watchable import WatchableStore
from ..storage.snap import NoSnapshotError, Snapshotter
from ..storage.storage import ServerStorage
from ..storage.wal import WAL, WalSnapshot
from .alarms import AlarmStore
from .api import (
    AlarmAction,
    AlarmRequest,
    AlarmType,
    AuthRequest,
    CompactionRequest,
    DeleteRangeRequest,
    InternalRaftRequest,
    LeaseCheckpoint,
    LeaseCheckpointRequest,
    LeaseGrantRequest,
    LeaseRevokeRequest,
    PutRequest,
    RangeRequest,
    RangeResponse,
    ResponseHeader,
    TxnRequest,
)
from .apply import (
    AlarmApplier,
    ApplierBackend,
    ApplyResult,
    AuthApplier,
    QuotaApplier,
)
from .cindex import ConsistentIndex
from .membership import Member, RaftCluster
from . import metrics as smet

DEFAULT_SNAPSHOT_COUNT = 100000  # ref: server.go:73
DEFAULT_SNAPSHOT_CATCHUP_ENTRIES = 5000  # ref: server.go:80
MAX_GAP_BETWEEN_APPLY_AND_COMMIT = 5000  # ref: v3_server.go:36
DEFAULT_QUOTA_BYTES = 2 * 1024 * 1024 * 1024  # ref: storage/quota.go
READ_INDEX_RETRY_TIME = 0.5  # ref: v3_server.go:44


class StoppedError(Exception):
    """ref: etcdserver.ErrStopped."""


class TimeoutError_(Exception):
    """ref: etcdserver.ErrTimeout."""


# Shared across layers (client failover matches by class name).
from ..pkg.errors import LearnerNotReadyError, NotLeaderError  # noqa: E402


class TooManyRequestsError(Exception):
    """ref: etcdserver.ErrTooManyRequests (apply/commit gap backpressure)."""


class MemberRemovedError(Exception):
    pass


class RequestTooLargeError(Exception):
    """ref: rpctypes.ErrRequestTooLarge (v3_server.go size check)."""


@dataclass
class ServerConfig:
    member_id: int = 1
    cluster_id: int = 0x1000
    peers: List[int] = field(default_factory=lambda: [1])
    data_dir: str = ""
    network: Any = None  # transport with send(from_id, msgs) + register()
    join: bool = False
    snapshot_count: int = DEFAULT_SNAPSHOT_COUNT
    snapshot_catchup_entries: int = DEFAULT_SNAPSHOT_CATCHUP_ENTRIES
    quota_bytes: int = DEFAULT_QUOTA_BYTES
    tick_interval: float = 0.05
    election_tick: int = 10
    heartbeat_tick: int = 1
    auto_compaction_mode: str = ""  # ""|periodic|revision
    auto_compaction_retention: float = 0.0
    lease_min_ttl: int = 1
    lease_checkpoint_interval: float = 300.0
    pre_vote: bool = True
    request_timeout: float = 7.0
    max_request_bytes: int = 1536 * 1024  # ref: embed/config.go DefaultMaxRequestBytes
    auth_token: str = "simple"  # "simple" | "hmac:<key>" | "jwt,sign-key=<k>[,sign-method=HS256][,ttl=5m]" (ref: --auth-token)
    # Corruption checking (ref: corrupt.go; --experimental-initial-
    # corrupt-check / --experimental-corrupt-check-time). The fetcher
    # resolves a peer id to its hash-KV; None disables both checks.
    peer_hash_fetcher: Any = None
    initial_corrupt_check: bool = False
    corrupt_check_time: float = 0.0  # seconds; 0 → no periodic monitor
    # TLSInfo for member→member calls against peers' CLIENT listeners
    # (renew forwarding); None in plaintext clusters.
    client_tls_info: Any = None
    # Raft implementation behind the Node contract: "host" = the
    # reference-shaped Python core, "tpu" = the batched device engine
    # (requires dense member ids 1..R; ref: SURVEY §7.6
    # --experimental-raft-backend plumbing at bootstrapRaft).
    raft_backend: str = "host"
    # tpu backend only: provisioned replica slots (compiled shape).
    # 0 = len(peers). Member-adds beyond this capacity are rejected;
    # provision headroom when the cluster is expected to grow.
    replica_capacity: int = 0


@dataclass
class _ApplyTask:
    entries: List[Entry]
    snapshot: Snapshot
    persisted: threading.Event  # snapshot durable on disk


class EtcdServer:
    def __init__(self, cfg: ServerConfig) -> None:
        self.cfg = cfg
        self.id = cfg.member_id
        self.cluster_id = cfg.cluster_id
        self.network = cfg.network

        self.member_dir = os.path.join(cfg.data_dir, f"member-{self.id}")
        self.wal_dir = os.path.join(self.member_dir, "wal")
        self.snap_dir = os.path.join(self.member_dir, "snap")
        self.db_path = os.path.join(self.member_dir, "db")
        os.makedirs(self.snap_dir, exist_ok=True)

        self._stopped = threading.Event()
        self._applied_index = 0
        self._committed_index = 0
        self._term = 0
        self._lead = NONE
        self._lead_lock = threading.Lock()
        self._fwd_lock = threading.Lock()
        self._fwd_clients: Dict[str, object] = {}  # leader ep -> Client

        self.w = Wait()
        self.apply_wait = WaitTime()
        self.idgen = Generator(self.id & 0xFF)
        self.sched = FIFOScheduler("apply")
        self.first_commit_in_term = threading.Event()
        self.leader_changed = threading.Event()

        self._read_mu = threading.Lock()
        self._read_notifier: Optional[_Notifier] = None
        self._read_waitc = threading.Event()
        self._read_states: List = []
        self._read_states_cv = threading.Condition()

        self._open_backend_stack()
        self._boot_raft()

        self.applier = AlarmApplier(
            QuotaApplier(AuthApplier(ApplierBackend(self), self.auth_store), self),
            self,
        )

        # Lease plumbing: checkpoints + expiry both ride raft.
        self.lessor.checkpointer = self._lease_checkpoint_via_raft
        self.lessor.range_deleter = lambda: _LeaseDeleterTxn(self)

        # Election/lock services over the loopback client
        # (ref: embed/etcd.go registering v3election/v3lock on v3client).
        from .v3election import ElectionServer
        from .v3lock import LockServer

        self.election_server = ElectionServer(self)
        self.lock_server = LockServer(self)

        self.compactor = None
        if cfg.auto_compaction_mode:
            from .compactor import new_compactor

            self.compactor = new_compactor(
                cfg.auto_compaction_mode,
                cfg.auto_compaction_retention,
                self.kv.rev,
                lambda rev: self.compact(CompactionRequest(revision=rev)),
            )
            self.compactor.run()

        # Corruption checking (ref: server.go:558-563 — initial check
        # before serving, then the periodic KV-hash monitor).
        self.corruption_checker = None
        if cfg.peer_hash_fetcher is not None:
            from .corrupt import CorruptionChecker

            self.corruption_checker = CorruptionChecker(
                self, cfg.peer_hash_fetcher)
            if cfg.initial_corrupt_check:
                try:
                    self.corruption_checker.initial_check()
                except Exception:
                    # Refuse to serve, but release what's open (the
                    # loops below haven't started yet).
                    if self.compactor is not None:
                        self.compactor.stop()
                    self.node.stop()
                    self.sched.stop()
                    self.kv.stop_sync_loop()
                    self.lessor.stop()
                    self.wal.close()
                    self.be.close()
                    raise
            if cfg.corrupt_check_time > 0:
                self.corruption_checker.start_periodic(cfg.corrupt_check_time)

        self.network.register(
            self.id, self._receive_message,
            reporter=lambda vid, failure: self.node.report_snapshot(
                vid, failure),
        )
        self._ready_thread = threading.Thread(
            target=self._ready_loop, daemon=True, name=f"ready-{self.id}"
        )
        self._threads = [
            threading.Thread(target=self._tick_loop, daemon=True),
            self._ready_thread,
            threading.Thread(target=self._linearizable_read_loop, daemon=True),
            threading.Thread(target=self._expired_lease_loop, daemon=True),
        ]
        for t in self._threads:
            t.start()

    # -- boot ------------------------------------------------------------------

    def _open_backend_stack(self, db_path: Optional[str] = None) -> None:
        """Open backend + all stores over it (boot & snapshot recovery)."""
        self.be = bk.open_backend(db_path or self.db_path)
        self.cindex = ConsistentIndex(self.be)
        self.lessor = Lessor(
            self.be,
            min_lease_ttl=self.cfg.lease_min_ttl,
            checkpoint_interval=self.cfg.lease_checkpoint_interval,
            loop_interval=min(0.5, self.cfg.tick_interval * 4),
        )
        self.kv = WatchableStore(self.be, self.lessor)
        self.kv.start_sync_loop()
        spec = self.cfg.auth_token
        if spec.startswith("hmac:"):
            from ..auth.hmac_token import HMACTokenProvider

            provider = HMACTokenProvider(spec[len("hmac:"):].encode())
        elif spec == "jwt" or spec.startswith("jwt,"):
            from ..auth.jwt_token import JWTTokenProvider

            provider = JWTTokenProvider.from_opts(spec[len("jwt,"):] if
                                                  "," in spec else "")
        else:
            provider = SimpleTokenProvider()
        self.auth_store = AuthStore(self.be, token_provider=provider)
        self.alarms = AlarmStore(self.be)
        self.cluster = RaftCluster(self.cluster_id, self.be)
        # Legacy v2 store: in-memory, rebuilt by replaying v2 ops from
        # the WAL (ref: api/v2store; the deprecation-path subsystem).
        from ..v2store.store import V2Store

        self.v2store = V2Store()

    def _boot_raft(self) -> None:
        """Cold/warm start (ref: etcdserver/bootstrap.go:52-119)."""
        self.raft_storage = MemoryStorage()
        self.snapshotter = Snapshotter(self.snap_dir)
        self.confstate = None

        old_wal = WAL.exists(self.wal_dir)
        snap = Snapshot()
        hs = None
        ents: List[Entry] = []
        if old_wal:
            try:
                snap = self.snapshotter.load()
            except NoSnapshotError:
                snap = Snapshot()
            self.wal = WAL.open(self.wal_dir)
            walsnap = WalSnapshot(index=snap.metadata.index, term=snap.metadata.term)
            _meta, hs, ents = self.wal.read_all(walsnap)
            if not is_empty_snap(snap):
                if self.cfg.raft_backend != "tpu":  # device holds the log
                    self.raft_storage.apply_snapshot(snap)
                self.confstate = snap.metadata.conf_state
                try:
                    v2blob = json.loads(snap.data.decode()).get("v2")
                    if v2blob:
                        self.v2store.recovery(v2blob)
                except (ValueError, KeyError):
                    pass  # pre-v2 snapshot format
            if self.cfg.raft_backend != "tpu":
                self.raft_storage.set_hard_state(hs)
                self.raft_storage.append(ents)
            # Raft replays ALL committed entries after the snapshot so
            # conf changes rebuild its config; the consistent-index
            # guard dedupes backend effects (server.go:1815-1827) —
            # applied starts at the snapshot point, NOT the cindex.
            self._applied_index = snap.metadata.index
        else:
            self.wal = WAL.create(self.wal_dir, metadata=self.id.to_bytes(8, "big"))

        self.storage = ServerStorage(self.wal, self.snapshotter)
        if self.cfg.raft_backend == "tpu":
            self._boot_raft_tpu(old_wal, snap, hs, ents)
            return

        raft_cfg = Config(
            id=self.id,
            election_tick=self.cfg.election_tick,
            heartbeat_tick=self.cfg.heartbeat_tick,
            storage=self.raft_storage,
            applied=self._applied_index,
            max_size_per_msg=1024 * 1024,  # ref: etcdserver/raft.go:33-40
            max_inflight_msgs=512,
            max_uncommitted_entries_size=1 << 30,
            check_quorum=True,
            pre_vote=self.cfg.pre_vote,
        )
        if old_wal or self.cfg.join:
            self.node = Node.restart(raft_cfg)
        else:
            peers = [
                Peer(
                    id=p,
                    context=Member(id=p, name=f"m{p}").marshal(),
                )
                for p in self.cfg.peers
            ]
            self.node = Node.start(raft_cfg, peers)

    def _boot_raft_tpu(self, old_wal: bool, snap: Snapshot, hs,
                       ents: List[Entry]) -> None:
        """Construct the batched device engine behind the same Node
        contract — the server-side `--raft-backend=tpu` path at the
        single raft-construction site (ref: etcdserver/bootstrap.go:
        473-536 bootstrapRaft; SURVEY §7.6)."""
        from ..batched.node import BatchedNode
        from ..batched.rawnode import RowRestore

        joiner_boot = self.cfg.join and not old_wal
        if not old_wal:
            # Fresh boot: the host path seeds the member registry via
            # bootstrap ConfChange entries (Node.start); the batched
            # engine boots with membership as initial state, so seed
            # the registry directly with the same Member contexts. A
            # JOINER seeds everyone but itself — its own membership
            # (registry entry AND device vote mask) arrives only via
            # the admitting ConfChange in the replicated log, so it
            # cannot campaign or count its own vote before admission
            # (ref: etcdserver/bootstrap.go:487-536; operators pass the
            # current member list via --initial-cluster, exactly what
            # `etcdctl member add` prints).
            for p in self.cfg.peers:
                if p == self.id and self.cfg.join:
                    continue
                if self.cluster.member(p) is None:
                    self.cluster.add_member(Member(id=p, name=f"m{p}"))

        restore = None
        if old_wal and hs is not None:
            base = snap.metadata.index
            restore = RowRestore(
                term=hs.term,
                vote=hs.vote,
                commit=hs.commit,
                applied=base,
                snap_index=base,
                snap_term=snap.metadata.term,
                entries=[
                    (e.index, e.term, e.data, int(e.type))
                    for e in ents
                    if e.index > base
                ],
                conf_state=(snap.metadata.conf_state
                            if snap.metadata.index > 0 else None),
            )
        # Device ring must cover the un-snapshotted tail (snapshots
        # every snapshot_count entries plus catch-up margin).
        window = 1 << max(6, (2 * self.cfg.snapshot_count + 64).bit_length())
        window = min(window, 1 << 15)
        self.cfg.snapshot_count = min(self.cfg.snapshot_count, window // 4)
        # Unconditional: a catch-up margin wider than the ring would pin
        # the floor and eventually stall proposals on ring headroom.
        self.cfg.snapshot_catchup_entries = min(
            self.cfg.snapshot_catchup_entries, window // 8)
        self.node = BatchedNode(
            node_id=self.id,
            peers=self.cfg.peers,
            election_tick=self.cfg.election_tick,
            heartbeat_tick=self.cfg.heartbeat_tick,
            window=window,
            pre_vote=self.cfg.pre_vote,
            restore=restore,
            boot_conf_state=(
                ConfState(voters=[p for p in self.cfg.peers
                                  if p != self.id])
                if joiner_boot else None
            ),
            capacity=self.cfg.replica_capacity,
        )
        if restore is not None and not is_empty_snap(snap):
            # Seed the node's app snapshot so lagging followers can be
            # served immediately after restart (the host path restores
            # it into MemoryStorage); the ring floor is already at the
            # snapshot index, so this only attaches the app state.
            self.node.compact(snap.metadata.index, snap)

    # -- loops -----------------------------------------------------------------

    def _tick_loop(self) -> None:
        # Slow-tick detector: a delayed heartbeat tick usually means the
        # loop thread was starved (slow disk / GC) — the reference's
        # heartbeat contention detector (etcdserver/raft.go:132-134).
        from ..pkg.contention import TimeoutDetector

        td = TimeoutDetector(2 * self.cfg.tick_interval)
        while not self._stopped.wait(self.cfg.tick_interval):
            ok, _ = td.observe(0)
            if not ok and self.is_leader():
                smet.heartbeat_send_failures.inc()
            self.node.tick()

    def _receive_message(self, m: Message) -> None:
        if self.cluster.is_removed(m.from_):
            return  # ref: server.go:690 Process rejects removed members
        try:
            self.node.step(m)
        except Exception:  # noqa: BLE001
            pass

    def _ready_loop(self) -> None:
        """ref: etcdserver/raft.go:158-315 raftNode.start."""
        try:
            self._ready_loop_inner()
        except failpoint.FailpointPanic:
            # gofail-style panic: the ready loop "crashes" — no cleanup,
            # no WAL flush (the reference's panic() kills the process;
            # ref: etcdserver/raft.go:222-265 gofail sites). The chaos
            # harness detects the dead thread and kill()s + restarts the
            # member; stop()/kill() still runs the full teardown, so the
            # stopped flag is deliberately NOT set here.
            return

    def _ready_loop_inner(self) -> None:
        islead = False
        while not self._stopped.is_set():
            rd = self.node.ready(timeout=0.1)
            if rd is None:
                continue
            if rd.soft_state is not None:
                islead = rd.soft_state.raft_state == StateType.StateLeader
                self._update_leadership(rd.soft_state)
            if rd.read_states:
                with self._read_states_cv:
                    self._read_states.extend(rd.read_states)
                    self._read_states_cv.notify_all()
            persisted = threading.Event()
            task = _ApplyTask(
                entries=rd.committed_entries,
                snapshot=rd.snapshot,
                persisted=persisted,
            )
            self._update_committed_index(task)
            self.sched.schedule(lambda t=task: self._apply_all(t))
            if islead:
                # Leader parallel-send: before fsync (raft thesis 10.2.1,
                # etcdserver/raft.go:218-224).
                failpoint.fp("raftBeforeLeaderSend")
                self.network.send(self.id, self._process_messages(rd.messages))
            if not is_empty_snap(rd.snapshot):
                failpoint.fp("raftBeforeSaveSnap")
                self.storage.save_snap(rd.snapshot)
                failpoint.fp("raftAfterSaveSnap")
            failpoint.fp("raftBeforeSave")
            self.wal.save(rd.hard_state, rd.entries, rd.must_sync)
            failpoint.fp("raftAfterSave")
            if not is_empty_snap(rd.snapshot):
                failpoint.fp("raftBeforeApplySnap")
                if self.cfg.raft_backend != "tpu":  # device holds the log
                    self.raft_storage.apply_snapshot(rd.snapshot)
                failpoint.fp("raftAfterApplySnap")
            persisted.set()
            if rd.entries and self.cfg.raft_backend != "tpu":
                self.raft_storage.append(rd.entries)
            if not islead:
                failpoint.fp("raftBeforeFollowerSend")
                self.network.send(self.id, self._process_messages(rd.messages))
            failpoint.fp("raftBeforeAdvance")
            self.node.advance()

    def _process_messages(self, msgs: List[Message]) -> List[Message]:
        """Drop messages to removed members (ref: raft.go:330-373)."""
        out = []
        for m in msgs:
            if self.cluster.is_removed(m.to):
                continue
            out.append(m)
        return out

    def _update_committed_index(self, task: _ApplyTask) -> None:
        ci = 0
        if task.entries:
            ci = task.entries[-1].index
        if task.snapshot.metadata.index > ci:
            ci = task.snapshot.metadata.index
        if ci > self._committed_index:
            self._committed_index = ci
            smet.proposals_committed.set(ci)

    def _update_leadership(self, soft_state) -> None:
        """ref: server.go raftReadyHandler updateLeadership."""
        with self._lead_lock:
            prev = self._lead
            self._lead = soft_state.lead
        if prev != soft_state.lead:
            self.leader_changed.set()
            self.leader_changed = threading.Event()
            if soft_state.lead != NONE:
                smet.leader_changes.inc()
        smet.has_leader.set(1 if soft_state.lead != NONE else 0)
        smet.is_leader.set(
            1 if soft_state.raft_state == StateType.StateLeader else 0
        )
        if soft_state.raft_state == StateType.StateLeader:
            if not self.lessor.is_primary():
                self.lessor.promote(
                    extend=self.cfg.election_tick * self.cfg.tick_interval
                )
            if self.compactor is not None:
                self.compactor.resume()
        else:
            if self.lessor.is_primary():
                self.lessor.demote()
            if self.compactor is not None:
                self.compactor.pause()

    # -- apply -----------------------------------------------------------------

    def _apply_all(self, task: _ApplyTask) -> None:
        """ref: server.go:903 applyAll."""
        t0 = time.monotonic()
        if not self._apply_snapshot(task):
            # Stop-aborted while waiting for snapshot persistence: the
            # entries after the snapshot cannot apply either (applied
            # never reached snap.index); abandon the whole task.
            return
        self._apply_entries(task)
        dt = time.monotonic() - t0
        smet.apply_duration.observe(dt)
        if dt > 0.1:  # warnApplyDuration (server.go:83)
            smet.slow_applies.inc()
        self.apply_wait.trigger(self._applied_index)
        self._maybe_trigger_snapshot()

    def _apply_snapshot(self, task: _ApplyTask) -> bool:
        """Receive a full-state snapshot: reopen the backend from the
        shipped db (ref: server.go:925-1040 applySnapshot). Returns
        False when aborted by stop (the rest of the task must not
        apply)."""
        if is_empty_snap(task.snapshot):
            return True
        snap = task.snapshot
        if snap.metadata.index <= self._applied_index:
            raise RuntimeError(
                f"snapshot index [{snap.metadata.index}] should > "
                f"applied index [{self._applied_index}]"
            )
        smet.snapshot_apply_in_progress.set(1)
        try:
            # Snapshot must be durable before opening it. A ready loop
            # that crashed mid-persist (failpoint panic) never sets the
            # event; bail on stop so teardown's scheduler join cannot
            # deadlock — not applying an unpersisted snapshot is exactly
            # crash semantics.
            while not task.persisted.wait(0.05):
                if self._stopped.is_set():
                    return False
            payload = json.loads(snap.data.decode())
            db_bytes = bytes.fromhex(payload["db"])
            newdb = os.path.join(
                self.member_dir, f"db.snap.{snap.metadata.index}"
            )
            with open(newdb, "wb") as f:
                f.write(db_bytes)
                f.flush()
                os.fsync(f.fileno())
            # Tear down stores over the old backend, swap the file, reopen.
            self.kv.stop_sync_loop()
            self.lessor.stop()
            self.be.close()
            os.replace(newdb, self.db_path)
            self._open_backend_stack()
            self.lessor.checkpointer = self._lease_checkpoint_via_raft
            self.lessor.range_deleter = lambda: _LeaseDeleterTxn(self)
            self.confstate = snap.metadata.conf_state
            self._applied_index = snap.metadata.index
            self._term = max(self._term, snap.metadata.term)
            self.cindex.set_consistent_index(self._applied_index, self._term)
            if "v2" in payload:
                self.v2store.recovery(payload["v2"])
        finally:
            smet.snapshot_apply_in_progress.set(0)

    def _apply_entries(self, task: _ApplyTask) -> None:
        if not task.entries:
            return
        first = task.entries[0].index
        if first > self._applied_index + 1:
            raise RuntimeError(
                f"first committed entry index {first} > applied+1 "
                f"{self._applied_index + 1}"
            )
        ents = [e for e in task.entries if e.index > self._applied_index]
        for e in ents:
            if e.type == EntryType.EntryNormal:
                self._apply_entry_normal(e)
            elif e.type in (EntryType.EntryConfChange, EntryType.EntryConfChangeV2):
                self._apply_conf_change_entry(e)
            self._applied_index = e.index
            self._term = max(self._term, e.term)
        smet.proposals_applied.set(self._applied_index)

    def _apply_entry_normal(self, e: Entry) -> None:
        """ref: server.go:1811-1913 applyEntryNormal."""
        # Consistent-index guard: skip entries already reflected in the
        # backend (replay after restart, server.go:1815-1827). Only
        # advance the index — writing it back on a replayed old entry
        # would reset the guard.
        should_apply = e.index > self.cindex.consistent_index()
        if should_apply:
            self.cindex.set_consistent_index(e.index, e.term)
        if not e.data:
            # Empty entry at term start: first commit notification +
            # lessor primary refresh (server.go:1835-1844).
            self.first_commit_in_term.set()
            self.first_commit_in_term = threading.Event()
            if self.is_leader():
                self.lessor.promote(
                    extend=self.cfg.election_tick * self.cfg.tick_interval
                )
            return
        req = InternalRaftRequest.unmarshal(e.data)
        if req.op == "v2":
            # v2 ops rebuild the in-memory v2 store on every replay —
            # it is NOT backend-backed, so the consistent-index guard
            # does not apply (ref: server.go applyV2Request; the
            # reference replays the v2 store from WAL + snapshot).
            result = self._apply_v2(req)
            if should_apply and req.id != 0:
                self.w.trigger(req.id, result)
            return
        if not should_apply:
            return
        result = self.applier.apply(req)
        if req.id != 0:
            self.w.trigger(req.id, result)

    def _apply_conf_change_entry(self, e: Entry) -> None:
        """ref: server.go:1915-1985 applyConfChange."""
        should_apply = e.index > self.cindex.consistent_index()
        if should_apply:
            self.cindex.set_consistent_index(e.index, e.term)
        if e.type == EntryType.EntryConfChange:
            cc = ConfChange.unmarshal(e.data)
            ccid, typ, nid, ctx = cc.id, cc.type, cc.node_id, cc.context
        else:
            ccv2 = ConfChangeV2.unmarshal(e.data)
            cc = ccv2
            ccid = 0
            typ = ccv2.changes[0].type if ccv2.changes else None
            nid = ccv2.changes[0].node_id if ccv2.changes else 0
            ctx = ccv2.context
        self.confstate = self.node.apply_conf_change(cc)
        if not should_apply:
            if ccid != 0:
                self.w.trigger(ccid, ApplyResult(resp=None))
            return
        removed_self = False
        if typ == ConfChangeType.ConfChangeAddNode:
            existing = self.cluster.member(nid)
            if existing is not None:
                # AddNode for a member we already track is a learner
                # promotion (ref: server.go:1938 promoteMember — the
                # wire carries promotion as AddNode on an existing id).
                if existing.is_learner:
                    self.cluster.promote_member(nid)
            elif not self.cluster.is_removed(nid):
                m = Member.unmarshal(ctx) if ctx else Member(id=nid, name=f"m{nid}")
                try:
                    self.cluster.add_member(m)
                except Exception:  # noqa: BLE001 — already present on replay
                    pass
        elif typ == ConfChangeType.ConfChangeAddLearnerNode:
            if self.cluster.member(nid) is None and not self.cluster.is_removed(nid):
                m = Member.unmarshal(ctx) if ctx else Member(id=nid, name=f"m{nid}")
                m.is_learner = True
                try:
                    self.cluster.add_member(m)
                except Exception:  # noqa: BLE001
                    pass
        elif typ == ConfChangeType.ConfChangeRemoveNode:
            self.cluster.remove_member(nid)
            if nid == self.id:
                removed_self = True
        if ccid != 0:
            self.w.trigger(ccid, ApplyResult(resp=self.confstate))
        if removed_self:
            threading.Thread(target=self.stop, daemon=True).start()

    # -- snapshot trigger ------------------------------------------------------

    def _maybe_trigger_snapshot(self) -> None:
        """ref: server.go:1096-1113 triggerSnapshot."""
        if self._applied_index - self._snapshot_index() <= self.cfg.snapshot_count:
            return
        self._snapshot()

    def _snapshot_index(self) -> int:
        if self.cfg.raft_backend == "tpu":
            return int(self.node.rn.m_snap[0])  # device ring floor
        try:
            return self.raft_storage.snapshot().metadata.index
        except Exception:  # noqa: BLE001
            return 0

    def _snapshot(self) -> None:
        """Serialize the backend into the raft snapshot
        (ref: server.go:1993-2070 snapshot; the reference ships the bbolt
        file the same way via snap.Message)."""
        self.be.force_commit()
        tmp = os.path.join(self.member_dir, "db.snapshot.tmp")
        self.be.snapshot_to(tmp)
        with open(tmp, "rb") as f:
            db_bytes = f.read()
        os.remove(tmp)
        data = json.dumps({
            "db": db_bytes.hex(),
            # The v2 store rides the snapshot (the reference serializes
            # it into .snap files, snapshot_merge.go) so pre-snapshot
            # v2 state survives log compaction and restarts.
            "v2": self.v2store.save(),
        }).encode()
        if self.cfg.raft_backend == "tpu":
            snap = self.node.create_snapshot(
                self._applied_index, self.confstate, data
            )
            self.storage.save_snap(snap)
            # Keep the catch-up margin below the ring floor so a
            # slightly-lagging follower gets log entries, not a full
            # state transfer (ref: server.go:80 CatchUpEntries; the
            # attached app snapshot at applied still covers any floor).
            compact_index = max(
                1, self._applied_index - self.cfg.snapshot_catchup_entries
            )
            self.node.compact(compact_index, snap)
            self.storage.release(snap)
            return
        snap = self.raft_storage.create_snapshot(
            self._applied_index, self.confstate, data
        )
        self.storage.save_snap(snap)
        compact_index = max(1, self._applied_index - self.cfg.snapshot_catchup_entries)
        try:
            self.raft_storage.compact(compact_index)
        except Exception:  # noqa: BLE001 — already compacted
            pass
        self.storage.release(snap)

    # -- write path ------------------------------------------------------------

    def _auth_info_from_token(self, token: Optional[str]) -> Optional[AuthInfo]:
        if not token or not self.auth_store.is_auth_enabled():
            return None
        return self.auth_store.auth_info_from_token(token)

    def process_internal_raft_request(
        self, op: str, req: Any, token: Optional[str] = None
    ) -> ApplyResult:
        """ref: v3_server.go:672-733 processInternalRaftRequestOnce."""
        if self._stopped.is_set():
            raise StoppedError()
        ai = self._committed_index - self._applied_index
        if ai > MAX_GAP_BETWEEN_APPLY_AND_COMMIT:
            raise TooManyRequestsError()
        info = self._auth_info_from_token(token)
        r = InternalRaftRequest(
            id=self.idgen.next(),
            op=op,
            req=req,
            username=info.username if info else "",
            auth_revision=info.revision if info else 0,
        )
        data = r.marshal()
        if len(data) > self.cfg.max_request_bytes:
            raise RequestTooLargeError()
        waiter = self.w.register(r.id)
        smet.proposals_pending.inc()
        try:
            self.node.propose(data, timeout=self.cfg.request_timeout)
            result = waiter.wait(timeout=self.cfg.request_timeout)
        except TimeoutError:
            self.w.trigger(r.id, None)  # deregister
            smet.proposals_failed.inc()
            raise TimeoutError_()
        finally:
            smet.proposals_pending.dec()
        if result is None:
            raise StoppedError()
        if result.err is not None:
            raise result.err
        return result

    # -- public KV API (v3_server.go:99-222) -----------------------------------

    def put(self, req: PutRequest, token: Optional[str] = None):
        return self.process_internal_raft_request("put", req, token).resp

    def delete_range(self, req: DeleteRangeRequest, token: Optional[str] = None):
        return self.process_internal_raft_request("delete_range", req, token).resp

    def txn(self, req: TxnRequest, token: Optional[str] = None):
        from .apply import _is_txn_write

        if _is_txn_write(req):
            return self.process_internal_raft_request("txn", req, token).resp
        # Read-only txn: serve locally after a read-index barrier.
        self.linearizable_read_notify()
        info = self._auth_info_from_token(token)
        if info is not None:
            AuthApplier(ApplierBackend(self), self.auth_store)._check_txn(info, req)
        return ApplierBackend(self).txn(req)

    def range(self, req: RangeRequest, token: Optional[str] = None) -> RangeResponse:
        """ref: v3_server.go:99-137 Range."""
        if not req.serializable:
            self.linearizable_read_notify()
        info = self._auth_info_from_token(token)
        if info is not None:
            self.auth_store.is_range_permitted(info, req.key, req.range_end)
        return ApplierBackend(self).range(req)

    def compact(self, req: CompactionRequest, token: Optional[str] = None):
        result = self.process_internal_raft_request("compaction", req, token)
        if req.physical:
            self.be.force_commit()
        return result.resp

    # -- lease API (v3_server.go:224-331) --------------------------------------

    def lease_grant(self, ttl: int, lease_id: int = 0, token: Optional[str] = None):
        if lease_id == 0:
            lease_id = self.idgen.next() & 0x7FFFFFFFFFFFFFFF
        req = LeaseGrantRequest(ttl=ttl, id=lease_id)
        resp = self.process_internal_raft_request("lease_grant", req, token).resp
        smet.lease_granted.inc()
        return resp

    def lease_revoke(self, lease_id: int, token: Optional[str] = None):
        req = LeaseRevokeRequest(id=lease_id)
        resp = self.process_internal_raft_request("lease_revoke", req, token).resp
        smet.lease_revoked.inc()
        return resp

    def publish(self, name: str, client_urls: List[str]) -> None:
        """Replicate this member's attributes (name + serving client
        URLs) so peers can resolve each other's client endpoints — the
        renew-forwarding path depends on it (ref: server.go:2097
        publishV3, retried until the proposal applies)."""
        def loop() -> None:
            req = {"id": self.id, "name": name,
                   "client_urls": list(client_urls)}
            while not self._stopped.is_set():
                try:
                    self.process_internal_raft_request(
                        "cluster_member_attr", req)
                    return
                except Exception:  # noqa: BLE001 — no leader yet etc.
                    if self._stopped.wait(1.0):
                        return

        t = threading.Thread(target=loop, daemon=True,
                             name=f"publish-{self.id:x}")
        t.start()
        self._threads.append(t)

    def lease_renew(self, lease_id: int, local_only: bool = False) -> int:
        """Keepalive: the expiry clock lives on the primary lessor, so a
        follower forwards the renew to the leader instead of bouncing
        the client (ref: v3_server.go:244-270 LeaseRenew → leasehttp
        RenewHTTP against the leader). ``local_only`` marks an
        already-forwarded request — one hop max, a stale-leader target
        answers NotLeader rather than forwarding again."""
        if not self.lessor.is_primary():
            if local_only:
                raise NotLeaderError()
            return self._forward_lease_renew(lease_id)
        try:
            ttl = self.lessor.renew(lease_id)
        except NotPrimaryError as exc:
            # Demoted between the is_primary check and the renew: the
            # caller should chase the new leader, not see a lease error.
            raise NotLeaderError() from exc
        smet.lease_renewed.inc()
        return ttl

    def _forward_lease_renew(self, lease_id: int) -> int:
        """One-hop renew forward to the current leader's client URL."""
        lead = self.leader()
        m = self.cluster.member(lead) if lead != NONE else None
        if m is None or not m.client_urls:
            raise NotLeaderError()
        ep = m.client_urls[0]
        from ..client.client import ClientError
        try:
            c = self._leader_fwd_client(ep)
            resp = c._request(
                "LeaseKeepAlive",
                {"id": lease_id, "local_only": True}, timeout=2.0)
            return resp["ttl"]
        except Exception as exc:  # noqa: BLE001 — surface as NotLeader
            app_level = isinstance(exc, ClientError) and exc.etype not in (
                "ConnectionError", "Timeout", "Closed")
            if not app_level:
                # Transport-level failure: the cached channel is suspect.
                # Application errors rode a healthy connection; keep it.
                with self._fwd_lock:
                    cli = self._fwd_clients.pop(ep, None)
                if cli is not None:
                    try:
                        cli.close()
                    except Exception:  # noqa: BLE001
                        pass
            if isinstance(exc, ClientError) and exc.etype in (
                    "LeaseNotFoundError", "LeaseExpiredError"):
                raise LeaseNotFoundError(str(lease_id)) from exc
            raise NotLeaderError() from exc

    def _leader_fwd_client(self, ep: str):
        """Cached member→leader client channel for renew forwarding.
        Dials outside _fwd_lock so a slow/unreachable leader cannot
        serialize every concurrent renew behind one connect."""
        from ..client.client import Client
        from ..embed.config import parse_urls
        with self._fwd_lock:
            c = self._fwd_clients.get(ep)
        if c is not None:
            return c
        host, port = parse_urls(ep)[0]
        tls = self.cfg.client_tls_info if ep.startswith("https") else None
        c = Client([(host, port)], dial_timeout=1.0,
                   request_timeout=2.0, tls_info=tls)
        with self._fwd_lock:
            prev = self._fwd_clients.get(ep)
            if prev is not None:  # raced: keep the first, drop ours
                winner, loser = prev, c
            else:
                self._fwd_clients[ep] = c
                winner, loser = c, None
        if loser is not None:
            try:
                loser.close()
            except Exception:  # noqa: BLE001
                pass
        return winner

    def lease_time_to_live(self, lease_id: int, keys: bool = False):
        lease = self.lessor.lookup(lease_id)
        if lease is None:
            return None
        rem = lease.remaining()
        return {
            "id": lease_id,
            "ttl": int(rem) if rem != float("inf") else lease.ttl,
            "granted_ttl": lease.ttl,
            "keys": lease.keys() if keys else [],
        }

    def lease_leases(self) -> List[int]:
        return [l.id for l in self.lessor.leases()]

    def _lease_checkpoint_via_raft(self, lease_id: int, remaining: int) -> None:
        req = LeaseCheckpointRequest(
            checkpoints=[LeaseCheckpoint(id=lease_id, remaining_ttl=remaining)]
        )
        try:
            self.process_internal_raft_request("lease_checkpoint", req)
        except Exception:  # noqa: BLE001 — best-effort
            pass

    def _expired_lease_loop(self) -> None:
        """ref: server.go run() lessor expiry case → LeaseRevoke."""
        while not self._stopped.is_set():
            leases = self.lessor.expired_leases(timeout=0.2)
            for lease in leases:
                if self._stopped.is_set():
                    return
                try:
                    self.lease_revoke(lease.id)
                except Exception:  # noqa: BLE001 — retried by the lessor
                    pass

    # -- linearizable reads (v3_server.go:738-905) -----------------------------

    def linearizable_read_notify(self, timeout: Optional[float] = None) -> None:
        """Block until a read-index round that started after this call
        confirms (ref: v3_server.go:896-905)."""
        timeout = timeout or self.cfg.request_timeout
        with self._read_mu:
            if self._read_notifier is None:
                self._read_notifier = _Notifier()
            nc = self._read_notifier
        self._read_waitc.set()
        err = nc.wait(timeout)
        if err is not None:
            raise err

    def _linearizable_read_loop(self) -> None:
        while not self._stopped.is_set():
            if not self._read_waitc.wait(timeout=0.2):
                continue
            self._read_waitc.clear()
            with self._read_mu:
                nr = self._read_notifier
                self._read_notifier = _Notifier()
            if nr is None:
                continue
            try:
                confirmed = self._request_current_index()
                # Wait for apply to catch up to the confirmed index.
                self.apply_wait.wait(confirmed).wait(
                    timeout=self.cfg.request_timeout
                )
                nr.notify(None)
            except Exception as e:  # noqa: BLE001
                nr.notify(e)

    def _request_current_index(self) -> int:
        """ref: v3_server.go:795-874 requestCurrentIndex."""
        rctx = os.urandom(8)
        self.node.read_index(rctx)
        deadline = time.monotonic() + self.cfg.request_timeout
        retry_at = time.monotonic() + READ_INDEX_RETRY_TIME
        while time.monotonic() < deadline:
            with self._read_states_cv:
                states, self._read_states = self._read_states, []
                if not states:
                    self._read_states_cv.wait(timeout=0.05)
                    states, self._read_states = self._read_states, []
            for rs in states:
                if rs.request_ctx == rctx:
                    return rs.index
            if time.monotonic() >= retry_at:
                # Leader may have changed or dropped it; re-request.
                smet.slow_read_indexes.inc()
                self.node.read_index(rctx)
                retry_at = time.monotonic() + READ_INDEX_RETRY_TIME
        smet.read_indexes_failed.inc()
        raise TimeoutError_("read index not confirmed")

    # -- auth API (replicated; v3_server.go AuthEnable etc.) -------------------

    def auth_enable(self, token: Optional[str] = None):
        return self.process_internal_raft_request(
            "auth", AuthRequest(op="enable"), token
        ).resp

    def auth_disable(self, token: Optional[str] = None):
        return self.process_internal_raft_request(
            "auth", AuthRequest(op="disable"), token
        ).resp

    def authenticate(self, name: str, password: str) -> str:
        """Token mint (reference replicates Authenticate for simple-token
        state; our token providers are node-local, so check+assign is
        local — clients stick to one endpoint for simple tokens)."""
        return self.auth_store.authenticate(name, password)

    def auth_op(self, req: AuthRequest, token: Optional[str] = None):
        return self.process_internal_raft_request("auth", req, token).resp

    # -- alarms / maintenance --------------------------------------------------

    def alarm(self, req: AlarmRequest, token: Optional[str] = None):
        if req.action == AlarmAction.GET:
            from .api import AlarmResponse

            return AlarmResponse(
                header=self.response_header(), alarms=self.alarms.get(req.alarm)
            )
        return self.process_internal_raft_request("alarm", req, token).resp

    def quota_available(self, r: InternalRaftRequest) -> bool:
        """ref: storage/quota.go backendQuota.Available."""
        # Cost model: current size + a coarse per-request overhead.
        cost = 512
        if r.op == "put":
            cost += len(r.req.key) + len(r.req.value)
        return self.be.size() + cost < self.cfg.quota_bytes

    def maybe_raise_nospace_alarm(self) -> None:
        if AlarmType.NOSPACE in self.alarms.active_types():
            return

        def _raise() -> None:
            try:
                self.alarm(
                    AlarmRequest(
                        action=AlarmAction.ACTIVATE,
                        member_id=self.id,
                        alarm=AlarmType.NOSPACE,
                    )
                )
            except Exception:  # noqa: BLE001
                pass

        threading.Thread(target=_raise, daemon=True).start()

    def hash_kv(self, rev: int = 0):
        return self.kv.hash_kv(rev)

    # -- v2 legacy surface (ref: etcdserver/apply_v2.go, v2store) --------------

    def _apply_v2(self, r: InternalRaftRequest):
        """Interpret a committed v2 op against the in-memory v2 store
        (ref: apply_v2.go applierV2 Put/Post/Delete/QGet)."""
        q = dict(r.req)
        st = self.v2store
        if "expire_at" in q:
            # Remaining TTL at apply time; non-positive applies as an
            # immediately-expirable sliver (the key was already dead).
            q["ttl"] = max(q["expire_at"] - time.time(), 1e-6)
        try:
            method = q["method"]
            path = q["path"]
            if method == "set":
                ev = st.set(path, dir_=q.get("dir", False),
                            value=q.get("value", ""), ttl=q.get("ttl"))
            elif method == "create":
                ev = st.create(path, dir_=q.get("dir", False),
                               value=q.get("value", ""), ttl=q.get("ttl"),
                               unique=q.get("unique", False))
            elif method == "update":
                ev = st.update(path, value=q.get("value", ""),
                               ttl=q.get("ttl"))
            elif method == "cas":
                ev = st.compare_and_swap(
                    path, q.get("prev_value"), q.get("prev_index", 0),
                    q.get("value", ""), ttl=q.get("ttl"))
            elif method == "cad":
                ev = st.compare_and_delete(
                    path, q.get("prev_value"), q.get("prev_index", 0))
            elif method == "delete":
                ev = st.delete(path, recursive=q.get("recursive", False),
                               dir_=q.get("dir", False))
            else:
                raise ValueError(f"unknown v2 method {method!r}")
            return ApplyResult(resp=ev)
        except Exception as e:  # noqa: BLE001 — V2Error travels to waiter
            return ApplyResult(err=e)

    def v2_write(self, method: str, path: str, **kwargs):
        """Replicated v2 mutation: proposed through raft like every
        other write (ref: v2http → etcdserver Do → raft)."""
        req = {"method": method, "path": path}
        req.update({k: v for k, v in kwargs.items() if v is not None})
        # TTLs replicate as ABSOLUTE expiration set at proposal time
        # (ref: v2http sets Expiration before etcdserver.Do), so WAL
        # replay cannot resurrect long-expired keys.
        if req.get("ttl") is not None:
            req["expire_at"] = time.time() + float(req.pop("ttl"))
        out = self.process_internal_raft_request("v2", req, None)
        return out.resp

    def v2_get(self, path: str, recursive: bool = False,
               sorted_: bool = False):
        """Local read of the v2 store (the reference's default
        non-quorum GET path)."""
        return self.v2store.get(path, recursive=recursive, sorted_=sorted_)

    def defrag(self) -> None:
        self.be.defrag()

    # -- membership ops (server.go:1265-1537) ----------------------------------

    def add_member(self, member: Member, timeout: Optional[float] = None):
        cc = ConfChange(
            id=self.idgen.next(),
            type=(
                ConfChangeType.ConfChangeAddLearnerNode
                if member.is_learner
                else ConfChangeType.ConfChangeAddNode
            ),
            node_id=member.id,
            context=member.marshal(),
        )
        return self._propose_conf_change(cc, timeout)

    def remove_member(self, mid: int, timeout: Optional[float] = None):
        cc = ConfChange(
            id=self.idgen.next(),
            type=ConfChangeType.ConfChangeRemoveNode,
            node_id=mid,
        )
        return self._propose_conf_change(cc, timeout)

    # A learner is promotable once its match index covers >= 90% of the
    # leader's (ref: server.go:1473 readyPercent).
    _LEARNER_READY_PERCENT = 0.9

    def _is_learner_ready(self, mid: int) -> None:
        """Catch-up gate for promotion (ref: server.go:1446
        isLearnerReady): from the leader's progress view, the learner's
        match index must cover >= readyPercent of the leader's own
        match. Raises LearnerNotReadyError while the learner is still
        catching up, NotLeaderError when this member has no progress
        view (only the leader tracks match indexes)."""
        st = self.node.status()
        if not st.progress:
            if self.is_leader():
                # Leader on a backend whose status() carries no
                # per-peer progress view (the batched/tpu node tracks
                # match on device only): nothing to gate on — allow,
                # as before the gate existed. Raising NotLeaderError
                # here would make promotion permanently impossible
                # (clients fail over member-by-member forever).
                return
            # Follower: only the leader tracks match indexes.
            raise NotLeaderError()
        learner_match = st.progress[mid].match if mid in st.progress else 0
        leader_match = st.progress[st.id].match if st.id in st.progress else 0
        if leader_match == 0 or (
            float(learner_match)
            < float(leader_match) * self._LEARNER_READY_PERCENT
        ):
            raise LearnerNotReadyError(
                f"learner {mid:x} match {learner_match} has not caught "
                f"up to leader match {leader_match} "
                f"(need >= {self._LEARNER_READY_PERCENT:.0%})")

    def promote_member(self, mid: int, timeout: Optional[float] = None):
        """Learner → voter, gated on readiness (server.go:1446 isLearnerReady)."""
        m = self.cluster.member(mid)
        if m is None or not m.is_learner:
            raise ValueError(f"member {mid} is not a learner")
        self._is_learner_ready(mid)
        cc = ConfChange(
            id=self.idgen.next(),
            type=ConfChangeType.ConfChangeAddNode,
            node_id=mid,
            context=json.dumps({"promote": True, **json.loads(m.marshal())}).encode(),
        )
        result = self._propose_conf_change(cc, timeout)
        self.cluster.promote_member(mid)
        if self.is_leader():
            smet.learner_promote_succeed.inc()
        return result

    def _propose_conf_change(self, cc: ConfChange, timeout: Optional[float]):
        waiter = self.w.register(cc.id)
        self.node.propose_conf_change(
            cc, timeout=timeout or self.cfg.request_timeout
        )
        result = waiter.wait(timeout=timeout or self.cfg.request_timeout)
        if result is None:
            raise TimeoutError_()
        return result.resp

    # -- introspection ---------------------------------------------------------

    def response_header(self) -> ResponseHeader:
        return ResponseHeader(
            cluster_id=self.cluster_id,
            member_id=self.id,
            revision=self.kv.rev(),
            raft_term=self._term,
        )

    def is_leader(self) -> bool:
        with self._lead_lock:
            return self._lead == self.id

    def leader(self) -> int:
        with self._lead_lock:
            return self._lead

    def applied_index(self) -> int:
        return self._applied_index

    def committed_index(self) -> int:
        return self._committed_index

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self.network.unregister(self.id)
        if self.corruption_checker is not None:
            self.corruption_checker.stop()
        if self.compactor is not None:
            self.compactor.stop()
        self.node.stop()
        with self._fwd_lock:
            fwd, self._fwd_clients = list(self._fwd_clients.values()), {}
        for c in fwd:
            try:
                c.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=5)
        self.sched.stop()
        self.kv.stop_sync_loop()
        self.lessor.stop()
        self.wal.close()
        self.be.close()


class _Notifier:
    """One read-round completion broadcast (ref: v3_server.go notifier)."""

    def __init__(self) -> None:
        self._ev = threading.Event()
        self._err: Optional[Exception] = None

    def notify(self, err: Optional[Exception]) -> None:
        self._err = err
        self._ev.set()

    def wait(self, timeout: float) -> Optional[Exception]:
        if not self._ev.wait(timeout=timeout):
            return TimeoutError_("linearizable read timeout")
        return self._err


class _LeaseDeleterTxn:
    """Lease revoke deletes attached keys through a normal mvcc write txn
    (ref: server.go:594-600 lessor.SetRangeDeleter with kv.Write)."""

    def __init__(self, server: EtcdServer) -> None:
        self.s = server
        self._txn = server.kv.write()
        self._txn.__enter__()

    def delete_range(self, key: bytes, end: Optional[bytes]) -> None:
        self._txn.delete_range(key, end)

    def end(self) -> None:
        self._txn.__exit__(None, None, None)
