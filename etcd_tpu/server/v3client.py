"""In-process client over a live EtcdServer.

The reference builds its server-side election/lock services on the
*client* concurrency recipes by wrapping the server in a loopback
clientv3 (ref: server/etcdserver/api/v3client/v3client.go:24-60 New).
``LocalClient`` is that loopback: it duck-types the subset of
``etcd_tpu.client.client.Client`` the recipes use — KV ops, watch,
lease — but calls straight into the server's apply path with no
sockets or frames in between.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional, Tuple

from . import api as sapi


class LocalWatchHandle:
    """WatchHandle contract (get/cancel) over a server-side WatchStream."""

    def __init__(self, kv, key: bytes, range_end: Optional[bytes], start_rev: int):
        self._ws = kv.new_watch_stream()
        end = range_end if range_end else None
        if end == b"\x00":
            end = b""  # open-end sentinel, same as the RPC surface
        self.watch_id = self._ws.watch(key, end, start_rev=start_rev)
        self._closed = False

    def get(self, timeout: Optional[float] = None):
        if self._closed:
            return None
        resp = self._ws.poll(timeout=timeout)
        if resp is None:
            return None
        return resp.revision, list(resp.events)

    def events(self, timeout: float = 5.0):
        out = self.get(timeout=timeout)
        return out[1] if out else []

    def cancel(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._ws.close()
            except Exception:
                pass


class LocalClient:
    """Loopback client: the concurrency-recipe surface of ``Client``
    served by direct EtcdServer calls (ref: v3client.go New)."""

    def __init__(self, server, token: Optional[str] = None) -> None:
        self.s = server
        self.token = token

    # -- KV --------------------------------------------------------------------

    def put(self, key: bytes, value: bytes, lease: int = 0,
            prev_kv: bool = False, ignore_lease: bool = False) -> sapi.PutResponse:
        req = sapi.PutRequest(key=key, value=value, lease=lease,
                              prev_kv=prev_kv, ignore_lease=ignore_lease)
        return self.s.put(req, token=self.token)

    def get(self, key: bytes, range_end: Optional[bytes] = None, revision: int = 0,
            limit: int = 0, serializable: bool = False, count_only: bool = False,
            keys_only: bool = False,
            sort_order: sapi.SortOrder = sapi.SortOrder.NONE,
            sort_target: sapi.SortTarget = sapi.SortTarget.KEY) -> sapi.RangeResponse:
        req = sapi.RangeRequest(
            key=key, range_end=range_end or b"", revision=revision, limit=limit,
            serializable=serializable, count_only=count_only, keys_only=keys_only,
            sort_order=sort_order, sort_target=sort_target)
        return self.s.range(req, token=self.token)

    def delete(self, key: bytes, range_end: Optional[bytes] = None,
               prev_kv: bool = False) -> sapi.DeleteRangeResponse:
        req = sapi.DeleteRangeRequest(key=key, range_end=range_end or b"",
                                      prev_kv=prev_kv)
        return self.s.delete_range(req, token=self.token)

    def txn(self, txn_req: sapi.TxnRequest) -> sapi.TxnResponse:
        return self.s.txn(txn_req, token=self.token)

    # -- watch -----------------------------------------------------------------

    def watch(self, key: bytes, range_end: Optional[bytes] = None,
              start_rev: int = 0) -> LocalWatchHandle:
        return LocalWatchHandle(self.s.kv, key, range_end, start_rev)

    # -- lease -----------------------------------------------------------------

    def lease_grant(self, ttl: int, lease_id: int = 0) -> sapi.LeaseGrantResponse:
        return self.s.lease_grant(ttl=ttl, lease_id=lease_id, token=self.token)

    def lease_revoke(self, lease_id: int) -> sapi.LeaseRevokeResponse:
        return self.s.lease_revoke(lease_id, token=self.token)

    def lease_keep_alive_once(self, lease_id: int) -> int:
        return self.s.lease_renew(lease_id)

    def lease_keep_alive(self, lease_id: int,
                         interval: Optional[float] = None) -> Callable[[], None]:
        stop = threading.Event()
        ttl = max(1, interval or 1)

        def loop() -> None:
            while not stop.wait(ttl):
                try:
                    self.s.lease_renew(lease_id)
                except Exception:
                    return

        t = threading.Thread(target=loop, daemon=True,
                             name=f"local-keepalive-{lease_id:x}")
        t.start()
        return stop.set
