"""Server-side election service layered on the concurrency recipes
through the in-process loopback client
(ref: server/etcdserver/api/v3election/v3election.go:26-80 —
Campaign/Proclaim/Leader/Resign/Observe over concurrency.Election).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional

from ..client.concurrency import Election, Session
from ..client.util import prefix_end
from . import api as sapi
from .v3client import LocalClient


class ElectionNoLeaderError(Exception):
    """ref: api/v3rpc/rpctypes/error.go ErrGRPCElectionNoLeader."""


class ElectionNotLeaderError(Exception):
    """ref: rpctypes ErrGRPCElectionNotLeader."""


@dataclass
class LeaderKey:
    """ref: api/v3electionpb LeaderKey — proof of leadership: the
    election name, the owner's key, its create revision, and the
    session lease backing it."""

    name: bytes
    key: bytes
    rev: int
    lease: int


class ElectionServer:
    """ref: v3election.go electionServer — each RPC builds a Session
    around the caller's lease (no server-side keepalive: the caller
    owns the lease lifetime, v3election.go:33-40) and drives the
    Election recipe."""

    def __init__(self, server) -> None:
        self.s = server

    def _client(self, token: Optional[str]) -> LocalClient:
        return LocalClient(self.s, token=token)

    def campaign(self, name: bytes, lease: int, value: bytes,
                 timeout: Optional[float] = None,
                 token: Optional[str] = None) -> LeaderKey:
        """Blocks until this lease owns the election (v3election.go:42-58)."""
        c = self._client(token)
        sess = Session.from_lease(c, lease)
        e = Election(sess, name.decode())
        e.campaign(value, timeout=timeout)
        assert e.leader_key is not None
        return LeaderKey(name=name, key=e.leader_key, rev=e.leader_rev,
                         lease=lease)

    def proclaim(self, leader: LeaderKey, value: bytes,
                 token: Optional[str] = None) -> None:
        """Overwrite the leader value iff the caller still holds the
        election (guarded on create-revision, v3election.go:60-66 →
        election.go Proclaim txn)."""
        c = self._client(token)
        resp = c.txn(sapi.TxnRequest(
            compare=[sapi.Compare(
                result=sapi.CompareResult.EQUAL,
                target=sapi.CompareTarget.CREATE,
                key=leader.key,
                create_revision=leader.rev,
            )],
            success=[sapi.RequestOp(request_put=sapi.PutRequest(
                key=leader.key, value=value, ignore_lease=True))],
        ))
        if not resp.succeeded:
            raise ElectionNotLeaderError("not leader")

    def resign(self, leader: LeaderKey, token: Optional[str] = None) -> None:
        """Delete the ownership key iff still held (election.go Resign)."""
        c = self._client(token)
        c.txn(sapi.TxnRequest(
            compare=[sapi.Compare(
                result=sapi.CompareResult.EQUAL,
                target=sapi.CompareTarget.CREATE,
                key=leader.key,
                create_revision=leader.rev,
            )],
            success=[sapi.RequestOp(request_delete_range=sapi.DeleteRangeRequest(
                key=leader.key))],
        ))

    def leader(self, name: bytes, token: Optional[str] = None) -> sapi.KeyValue:
        """Current leader kv = lowest create-revision under the prefix
        (v3election.go:68-74 → election.go Leader)."""
        kv = self._leader_kv(name, token)
        if kv is None:
            raise ElectionNoLeaderError("no leader")
        return kv

    def _leader_kv(self, name: bytes,
                   token: Optional[str]) -> Optional[sapi.KeyValue]:
        kv, _rev = self._leader_kv_at(name, token)
        return kv

    def _leader_kv_at(self, name: bytes, token: Optional[str]):
        """(leader kv or None, revision of the read)."""
        pfx = name.rstrip(b"/") + b"/"
        rr = self._client(token).get(
            pfx, range_end=prefix_end(pfx), limit=1,
            sort_order=sapi.SortOrder.ASCEND,
            sort_target=sapi.SortTarget.CREATE)
        return (rr.kvs[0] if rr.kvs else None), rr.header.revision

    def observe(self, name: bytes, push: Callable[[sapi.KeyValue], bool],
                stopped, token: Optional[str] = None) -> None:
        """Stream leader kvs to ``push`` until it returns False or
        ``stopped`` is set (v3election.go:76-91 → election.go Observe:
        every proclamation of the current leader is an event)."""
        c = self._client(token)
        pfx = name.rstrip(b"/") + b"/"
        last_mod = 0
        while not stopped.is_set():
            kv, read_rev = self._leader_kv_at(name, token)
            if kv is not None and kv.mod_revision > last_mod:
                last_mod = kv.mod_revision
                if not push(kv):
                    return
            # Hold ONE watch across idle polls: tearing it down every
            # interval opens re-establishment gaps under load (events
            # between cancel and re-watch surface only via the next
            # leader-kv poll, delaying pushes unboundedly). Watch from
            # the READ's revision, never "from now" — with no leader, a
            # campaign landing between the read and the watch would
            # otherwise go unseen for as long as the leader stays quiet.
            h = c.watch(pfx, range_end=prefix_end(pfx),
                        start_rev=(kv.mod_revision + 1 if kv
                                   else read_rev + 1))
            try:
                while not stopped.is_set():
                    if h.get(timeout=0.5) is not None:
                        break  # change seen — re-read the leader kv
            finally:
                h.cancel()
