"""Auto-compaction (ref: server/etcdserver/api/v3compactor/).

Periodic mode: every interval, compact to the revision observed
interval-ago (periodic.go — revision window ring). Revision mode: keep
the latest N revisions (revision.go). Both drive the server's Compact
through raft so all members see the same compaction."""

from __future__ import annotations

import threading
import time
from typing import Callable, List


class Compactor:
    def __init__(self, check_interval: float = 60.0) -> None:
        self._stop = threading.Event()
        self._thread: threading.Thread = threading.Thread(
            target=self._run, daemon=True
        )
        self.check_interval = check_interval
        self._paused = threading.Event()

    def run(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval):
            if not self._paused.is_set():
                self._tick()

    def _tick(self) -> None:
        raise NotImplementedError


class PeriodicCompactor(Compactor):
    """Compact to the revision seen `retention` seconds ago
    (ref: v3compactor/periodic.go)."""

    def __init__(
        self,
        retention_s: float,
        rev_fn: Callable[[], int],
        compact_fn: Callable[[int], None],
        check_interval: float = None,  # type: ignore[assignment]
    ) -> None:
        # The reference polls at retention/10 (periodic.go getRetryInterval).
        super().__init__(check_interval or max(retention_s / 10.0, 0.05))
        self.retention = retention_s
        self.rev_fn = rev_fn
        self.compact_fn = compact_fn
        self._window: List[tuple] = []  # (time, rev)
        self._last_compacted = 0

    def _tick(self) -> None:
        now = time.monotonic()
        self._window.append((now, self.rev_fn()))
        cutoff = now - self.retention
        target = None
        while self._window and self._window[0][0] <= cutoff:
            target = self._window.pop(0)[1]
        if target is not None and target > self._last_compacted:
            try:
                self.compact_fn(target)
                self._last_compacted = target
            except Exception:  # noqa: BLE001 — retried next pass
                pass


class RevisionCompactor(Compactor):
    """Keep the latest `retention` revisions (ref: v3compactor/revision.go)."""

    def __init__(
        self,
        retention_revs: int,
        rev_fn: Callable[[], int],
        compact_fn: Callable[[int], None],
        check_interval: float = 5.0,
    ) -> None:
        super().__init__(check_interval)
        self.retention = retention_revs
        self.rev_fn = rev_fn
        self.compact_fn = compact_fn
        self._last_compacted = 0

    def _tick(self) -> None:
        target = self.rev_fn() - self.retention
        if target > self._last_compacted and target > 0:
            try:
                self.compact_fn(target)
                self._last_compacted = target
            except Exception:  # noqa: BLE001
                pass


def new_compactor(mode: str, retention: float, rev_fn, compact_fn) -> Compactor:
    if mode == "periodic":
        return PeriodicCompactor(retention, rev_fn, compact_fn)
    if mode == "revision":
        return RevisionCompactor(int(retention), rev_fn, compact_fn)
    raise ValueError(f"unknown compaction mode {mode!r}")
