"""Cluster membership (ref: server/etcdserver/api/membership/cluster.go).

RaftCluster: the authoritative member set, updated only by applied conf
changes and persisted in the backend members bucket so restarts recover
it without the WAL (cluster.go:44 RaftCluster, storev2.go/store.go dual
persistence — here backend-only, v2store being a deprecation path).
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..storage import backend as bk

MEMBERS_BUCKET = bk.Bucket("members")
REMOVED_BUCKET = bk.Bucket("membersRemoved")
CLUSTER_BUCKET = bk.Bucket("cluster")


@dataclass
class Member:
    id: int = 0
    name: str = ""
    peer_urls: List[str] = field(default_factory=list)
    client_urls: List[str] = field(default_factory=list)
    is_learner: bool = False

    def marshal(self) -> bytes:
        return json.dumps(
            {
                "id": self.id,
                "name": self.name,
                "peer_urls": self.peer_urls,
                "client_urls": self.client_urls,
                "is_learner": self.is_learner,
            }
        ).encode()

    @staticmethod
    def unmarshal(b: bytes) -> "Member":
        d = json.loads(b.decode())
        return Member(
            id=d["id"],
            name=d["name"],
            peer_urls=list(d["peer_urls"]),
            client_urls=list(d["client_urls"]),
            is_learner=d.get("is_learner", False),
        )


class MemberNotFoundError(Exception):
    pass


class MemberExistsError(Exception):
    pass


class MemberRemovedError(Exception):
    """ref: membership.ErrIDRemoved."""


class RaftCluster:
    def __init__(self, cluster_id: int, backend: Optional[bk.Backend] = None) -> None:
        self._lock = threading.RLock()
        self.cid = cluster_id
        self.b = backend
        self.members: Dict[int, Member] = {}
        self.removed: Dict[int, bool] = {}
        if backend is not None:
            tx = backend.batch_tx
            with tx.lock:
                tx.unsafe_create_bucket(MEMBERS_BUCKET)
                tx.unsafe_create_bucket(REMOVED_BUCKET)
                tx.unsafe_create_bucket(CLUSTER_BUCKET)
            self._recover()

    def _recover(self) -> None:
        rt = self.b.read_tx()
        for k, v in rt.range(MEMBERS_BUCKET, b"", b"\xff" * 16, 0):
            m = Member.unmarshal(v)
            self.members[m.id] = m
        for k, _v in rt.range(REMOVED_BUCKET, b"", b"\xff" * 16, 0):
            self.removed[int.from_bytes(k, "big")] = True

    def _persist_member(self, m: Member) -> None:
        if self.b is None:
            return
        tx = self.b.batch_tx
        with tx.lock:
            tx.put(MEMBERS_BUCKET, m.id.to_bytes(8, "big"), m.marshal())

    # -- mutations (conf-change apply path, cluster.go:391-444) ---------------

    def add_member(self, m: Member) -> None:
        with self._lock:
            if m.id in self.removed:
                raise MemberRemovedError(str(m.id))
            if m.id in self.members:
                raise MemberExistsError(str(m.id))
            self.members[m.id] = m
            self._persist_member(m)

    def remove_member(self, mid: int) -> None:
        with self._lock:
            self.members.pop(mid, None)
            self.removed[mid] = True
            if self.b is not None:
                tx = self.b.batch_tx
                with tx.lock:
                    tx.delete(MEMBERS_BUCKET, mid.to_bytes(8, "big"))
                    tx.put(REMOVED_BUCKET, mid.to_bytes(8, "big"), b"\x01")

    def promote_member(self, mid: int) -> None:
        with self._lock:
            m = self.members.get(mid)
            if m is None:
                raise MemberNotFoundError(str(mid))
            m.is_learner = False
            self._persist_member(m)

    def update_member_attr(self, mid: int, name: str, client_urls: List[str]) -> None:
        with self._lock:
            m = self.members.get(mid)
            if m is None:
                return
            m.name = name
            m.client_urls = list(client_urls)
            self._persist_member(m)

    # -- queries ---------------------------------------------------------------

    def member(self, mid: int) -> Optional[Member]:
        with self._lock:
            return self.members.get(mid)

    def member_ids(self) -> List[int]:
        with self._lock:
            return sorted(self.members)

    def voting_member_ids(self) -> List[int]:
        with self._lock:
            return sorted(i for i, m in self.members.items() if not m.is_learner)

    def is_removed(self, mid: int) -> bool:
        with self._lock:
            return mid in self.removed

    def member_list(self) -> List[Member]:
        with self._lock:
            return [self.members[i] for i in sorted(self.members)]
