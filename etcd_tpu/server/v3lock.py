"""Server-side lock service layered on the Mutex recipe through the
loopback client (ref: server/etcdserver/api/v3lock/v3lock.go:28-55 —
Lock builds a session around the caller's lease, locks the mutex, and
returns the ownership key; Unlock deletes it)."""

from __future__ import annotations

from typing import Optional

from ..client.concurrency import Mutex, Session
from .v3client import LocalClient


class LockServer:
    def __init__(self, server) -> None:
        self.s = server

    def lock(self, name: bytes, lease: int,
             timeout: Optional[float] = None,
             token: Optional[str] = None) -> bytes:
        """Blocks until the caller's lease owns ``name``; returns the
        ownership key whose existence is tied to the lease
        (v3lock.go:28-46)."""
        c = LocalClient(self.s, token=token)
        sess = Session.from_lease(c, lease)
        m = Mutex(sess, name.decode())
        m.lock(timeout=timeout)
        return m.my_key

    def unlock(self, key: bytes, token: Optional[str] = None) -> None:
        """v3lock.go:48-55 — delete the ownership key."""
        LocalClient(self.s, token=token).delete(key)
