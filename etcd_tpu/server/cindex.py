"""Consistent index (ref: server/etcdserver/cindex/cindex.go:56-118).

The applied raft index is persisted in the meta bucket *inside the same
backend batch* as the apply's writes (via a backend commit hook), so a
replayed WAL entry whose index ≤ the stored value is skipped — applies
are exactly-once across restarts (guard at server.go:1815-1827).
"""

from __future__ import annotations

import struct
import threading

from ..storage import backend as bk

META_BUCKET = bk.Bucket("meta")
CONSISTENT_INDEX_KEY = b"consistent_index"
TERM_KEY = b"term"


class ConsistentIndex:
    def __init__(self, backend: bk.Backend) -> None:
        self._lock = threading.Lock()
        self._b = backend
        self._index = 0
        self._term = 0
        tx = backend.batch_tx
        with tx.lock:
            tx.unsafe_create_bucket(META_BUCKET)
        v = backend.read_tx().get(META_BUCKET, CONSISTENT_INDEX_KEY)
        if v is not None:
            self._index = struct.unpack(">Q", v)[0]
        t = backend.read_tx().get(META_BUCKET, TERM_KEY)
        if t is not None:
            self._term = struct.unpack(">Q", t)[0]
        # Commit hook: persist in the same batch as buffered applies
        # (ref: server/storage/hooks.go OnPreCommitUnsafe).
        backend.add_hook(self._persist_hook)

    def _persist_hook(self, tx) -> None:
        with self._lock:
            tx.put(META_BUCKET, CONSISTENT_INDEX_KEY, struct.pack(">Q", self._index))
            tx.put(META_BUCKET, TERM_KEY, struct.pack(">Q", self._term))

    def consistent_index(self) -> int:
        with self._lock:
            return self._index

    def set_consistent_index(self, index: int, term: int) -> None:
        with self._lock:
            self._index = index
            self._term = term

    def term(self) -> int:
        with self._lock:
            return self._term
