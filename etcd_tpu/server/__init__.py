"""The replicated server (ref: server/etcdserver/).

EtcdServer ties the raft Node, WAL/snap storage, mvcc, lease, auth and
alarm subsystems together: proposals flow through
``process_internal_raft_request`` (propose → wait-registry → applied
response), reads through the ReadIndex protocol, and every committed
entry through the applier chain exactly once (consistent-index guard).
"""

from .api import *  # noqa: F401,F403
from .server import EtcdServer, ServerConfig  # noqa: F401
