"""Cluster alarms (ref: server/etcdserver/api/v3alarm/alarms.go).

Raised/cleared via raft so all members agree; persisted in the alarm
bucket; active alarms gate the write path (AlarmApplier)."""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Set

from ..storage import backend as bk
from .api import AlarmMember, AlarmType

ALARM_BUCKET = bk.Bucket("alarm")
_KEY = struct.Struct(">QB")  # member_id, alarm type


class AlarmStore:
    def __init__(self, backend: bk.Backend) -> None:
        self._lock = threading.Lock()
        self.b = backend
        self._types: Dict[AlarmType, Set[int]] = {}
        tx = backend.batch_tx
        with tx.lock:
            tx.unsafe_create_bucket(ALARM_BUCKET)
        for k, _v in backend.read_tx().range(ALARM_BUCKET, b"", b"\xff" * 16, 0):
            mid, t = _KEY.unpack(k)
            self._types.setdefault(AlarmType(t), set()).add(mid)

    def activate(self, member_id: int, alarm: AlarmType) -> Optional[AlarmMember]:
        with self._lock:
            members = self._types.setdefault(alarm, set())
            if member_id in members:
                return None
            members.add(member_id)
            tx = self.b.batch_tx
            with tx.lock:
                tx.put(ALARM_BUCKET, _KEY.pack(member_id, int(alarm)), b"\x01")
            return AlarmMember(member_id=member_id, alarm=alarm)

    def deactivate(self, member_id: int, alarm: AlarmType) -> Optional[AlarmMember]:
        with self._lock:
            members = self._types.get(alarm, set())
            if member_id not in members:
                return None
            members.discard(member_id)
            tx = self.b.batch_tx
            with tx.lock:
                tx.delete(ALARM_BUCKET, _KEY.pack(member_id, int(alarm)))
            return AlarmMember(member_id=member_id, alarm=alarm)

    def get(self, alarm: AlarmType = AlarmType.NONE) -> List[AlarmMember]:
        with self._lock:
            out: List[AlarmMember] = []
            for t, members in sorted(self._types.items()):
                if alarm != AlarmType.NONE and t != alarm:
                    continue
                out.extend(AlarmMember(member_id=m, alarm=t) for m in sorted(members))
            return out

    def active_types(self) -> Set[AlarmType]:
        with self._lock:
            return {t for t, m in self._types.items() if m}
