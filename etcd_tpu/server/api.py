"""Request/response model: the etcdserverpb analog
(ref: api/etcdserverpb/rpc.proto and raft_internal.proto).

The reference's InternalRaftRequest is a protobuf union of every
replicated operation; here it is a tagged dict serialized as JSON with
hex-encoded byte fields. JSON costs more than proto on the wire but the
replicated payload stays host-side (entry *data* never lands on the
TPU — the device sees only (term,index) metadata; SURVEY.md §7 "payload
bytes don't belong on the TPU"), so clarity wins.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Any, Dict, List, Optional

from ..storage.mvcc.kv import KeyValue


# -- sort / compare enums (rpc.proto RangeRequest/Compare) ---------------------


class SortOrder(IntEnum):
    NONE = 0
    ASCEND = 1
    DESCEND = 2


class SortTarget(IntEnum):
    KEY = 0
    VERSION = 1
    CREATE = 2
    MOD = 3
    VALUE = 4


class CompareResult(IntEnum):
    EQUAL = 0
    GREATER = 1
    LESS = 2
    NOT_EQUAL = 3


class CompareTarget(IntEnum):
    VERSION = 0
    CREATE = 1
    MOD = 2
    VALUE = 3
    LEASE = 4


class AlarmType(IntEnum):
    NONE = 0
    NOSPACE = 1
    CORRUPT = 2


class AlarmAction(IntEnum):
    GET = 0
    ACTIVATE = 1
    DEACTIVATE = 2


@dataclass
class ResponseHeader:
    cluster_id: int = 0
    member_id: int = 0
    revision: int = 0
    raft_term: int = 0


@dataclass
class PutRequest:
    key: bytes = b""
    value: bytes = b""
    lease: int = 0
    prev_kv: bool = False
    ignore_value: bool = False
    ignore_lease: bool = False


@dataclass
class PutResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)
    prev_kv: Optional[KeyValue] = None


@dataclass
class RangeRequest:
    key: bytes = b""
    range_end: bytes = b""
    limit: int = 0
    revision: int = 0
    sort_order: SortOrder = SortOrder.NONE
    sort_target: SortTarget = SortTarget.KEY
    serializable: bool = False
    keys_only: bool = False
    count_only: bool = False
    min_mod_revision: int = 0
    max_mod_revision: int = 0
    min_create_revision: int = 0
    max_create_revision: int = 0


@dataclass
class RangeResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)
    kvs: List[KeyValue] = field(default_factory=list)
    more: bool = False
    count: int = 0


@dataclass
class DeleteRangeRequest:
    key: bytes = b""
    range_end: bytes = b""
    prev_kv: bool = False


@dataclass
class DeleteRangeResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)
    deleted: int = 0
    prev_kvs: List[KeyValue] = field(default_factory=list)


@dataclass
class Compare:
    result: CompareResult = CompareResult.EQUAL
    target: CompareTarget = CompareTarget.VERSION
    key: bytes = b""
    range_end: bytes = b""  # rpc.proto Compare.range_end (txn range compares)
    version: int = 0
    create_revision: int = 0
    mod_revision: int = 0
    value: bytes = b""
    lease: int = 0


@dataclass
class RequestOp:
    """Union: exactly one member set (rpc.proto RequestOp)."""

    request_range: Optional[RangeRequest] = None
    request_put: Optional[PutRequest] = None
    request_delete_range: Optional[DeleteRangeRequest] = None
    request_txn: Optional["TxnRequest"] = None


@dataclass
class ResponseOp:
    response_range: Optional[RangeResponse] = None
    response_put: Optional[PutResponse] = None
    response_delete_range: Optional[DeleteRangeResponse] = None
    response_txn: Optional["TxnResponse"] = None


@dataclass
class TxnRequest:
    compare: List[Compare] = field(default_factory=list)
    success: List[RequestOp] = field(default_factory=list)
    failure: List[RequestOp] = field(default_factory=list)


@dataclass
class TxnResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)
    succeeded: bool = False
    responses: List[ResponseOp] = field(default_factory=list)


@dataclass
class CompactionRequest:
    revision: int = 0
    physical: bool = False


@dataclass
class CompactionResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)


@dataclass
class LeaseGrantRequest:
    ttl: int = 0
    id: int = 0


@dataclass
class LeaseGrantResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)
    id: int = 0
    ttl: int = 0
    error: str = ""


@dataclass
class LeaseRevokeRequest:
    id: int = 0


@dataclass
class LeaseRevokeResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)


@dataclass
class LeaseCheckpoint:
    id: int = 0
    remaining_ttl: int = 0


@dataclass
class LeaseCheckpointRequest:
    checkpoints: List[LeaseCheckpoint] = field(default_factory=list)


@dataclass
class AlarmRequest:
    action: AlarmAction = AlarmAction.GET
    member_id: int = 0
    alarm: AlarmType = AlarmType.NONE


@dataclass
class AlarmMember:
    member_id: int = 0
    alarm: AlarmType = AlarmType.NONE


@dataclass
class AlarmResponse:
    header: ResponseHeader = field(default_factory=ResponseHeader)
    alarms: List[AlarmMember] = field(default_factory=list)


# -- auth ops (rpc.proto Auth service; all replicated via raft) ----------------


@dataclass
class AuthRequest:
    """Union of auth mutations, tagged by `op` (the reference gives each
    its own message; the applier dispatch is equivalent)."""

    op: str = ""  # enable|disable|user_add|user_delete|...
    name: str = ""
    password: str = ""
    role: str = ""
    key: bytes = b""
    range_end: bytes = b""
    perm_type: int = 0
    no_password: bool = False


# -- internal raft request -----------------------------------------------------

_BYTES_FIELDS = {"key", "value", "range_end"}


def _enc(obj: Any) -> Any:
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, list):
        return [_enc(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _enc(v) for k, v in obj.items()}
    if hasattr(obj, "__dataclass_fields__"):
        out = {}
        for f in obj.__dataclass_fields__:
            v = getattr(obj, f)
            if v is None:
                continue
            out[f] = _enc(v)
        return out
    if isinstance(obj, IntEnum):
        return int(obj)
    return obj


def _dec_bytes(v: Any) -> bytes:
    return bytes.fromhex(v) if isinstance(v, str) else v


def _build(cls, d: Dict[str, Any]):
    """Rehydrate a dataclass from a json dict (recursive on known fields)."""
    kw = {}
    for f, fd in cls.__dataclass_fields__.items():
        if f not in d:
            continue
        v = d[f]
        t = fd.type
        if f in ("key", "value", "range_end") or t == "bytes":
            kw[f] = _dec_bytes(v)
        elif f == "compare":
            kw[f] = [_build(Compare, x) for x in v]
        elif f in ("success", "failure"):
            kw[f] = [_build_request_op(x) for x in v]
        elif f == "checkpoints":
            kw[f] = [_build(LeaseCheckpoint, x) for x in v]
        else:
            kw[f] = v
    return cls(**kw)


def _build_request_op(d: Dict[str, Any]) -> RequestOp:
    op = RequestOp()
    if "request_range" in d:
        op.request_range = _build(RangeRequest, d["request_range"])
    if "request_put" in d:
        op.request_put = _build(PutRequest, d["request_put"])
    if "request_delete_range" in d:
        op.request_delete_range = _build(DeleteRangeRequest, d["request_delete_range"])
    if "request_txn" in d:
        op.request_txn = _build(TxnRequest, d["request_txn"])
    return op


_REQUEST_TYPES = {
    "put": PutRequest,
    "range": RangeRequest,
    "delete_range": DeleteRangeRequest,
    "txn": TxnRequest,
    "compaction": CompactionRequest,
    "lease_grant": LeaseGrantRequest,
    "lease_revoke": LeaseRevokeRequest,
    "lease_checkpoint": LeaseCheckpointRequest,
    "alarm": AlarmRequest,
    "auth": AuthRequest,
    "cluster_member_attr": None,  # dict passthrough
    "downgrade": None,
}


@dataclass
class InternalRaftRequest:
    """ref: api/etcdserverpb/raft_internal.proto — union of all
    replicated ops, one field set per request."""

    id: int = 0
    op: str = ""
    req: Any = None
    # The username+revision the proposal was authorized under; re-checked
    # at apply time (raft_internal.proto header.username/auth_revision).
    username: str = ""
    auth_revision: int = 0

    def marshal(self) -> bytes:
        return json.dumps(
            {
                "id": self.id,
                "op": self.op,
                "req": _enc(self.req),
                "u": self.username,
                "ar": self.auth_revision,
            },
            separators=(",", ":"),
        ).encode()

    @staticmethod
    def unmarshal(data: bytes) -> "InternalRaftRequest":
        d = json.loads(data.decode())
        op = d["op"]
        cls = _REQUEST_TYPES.get(op)
        if op == "txn":
            req = _build(TxnRequest, d["req"])
        elif cls is not None:
            req = _build(cls, d["req"])
        else:
            req = d["req"]
        return InternalRaftRequest(
            id=d["id"],
            op=op,
            req=req,
            username=d.get("u", ""),
            auth_revision=d.get("ar", 0),
        )
