"""grpc-gateway JSON interop surface (ref: server/embed/serve.go
registering the grpc-gateway mux; api/etcdserverpb/gw/rpc.pb.gw.go
routes). POST /v3/<service>/<method> with a JSON body; byte fields
(key, value, range_end...) travel base64, exactly like the gateway's
protobuf-JSON mapping.

Routes (the reference's curl surface):
    /v3/kv/range | put | deleterange | txn | compaction
    /v3/lease/grant | revoke | timetolive | leases
    /v3/maintenance/status | hash
    /v3/cluster/member/list
    /v3/auth/authenticate
"""

from __future__ import annotations

import base64
import json
from typing import Any, Dict, Optional

from . import version as ver
from .server import api as sapi


def _b64d(v: Optional[str]) -> bytes:
    return base64.b64decode(v) if v else b""


def _b64e(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _enc_header(h: sapi.ResponseHeader) -> Dict[str, Any]:
    return {
        "cluster_id": str(h.cluster_id),
        "member_id": str(h.member_id),
        "revision": str(h.revision),
        "raft_term": str(h.raft_term),
    }


def _enc_kv(kv) -> Dict[str, Any]:
    return {
        "key": _b64e(kv.key),
        "create_revision": str(kv.create_revision),
        "mod_revision": str(kv.mod_revision),
        "version": str(kv.version),
        "value": _b64e(kv.value),
        "lease": str(kv.lease),
    }


def _dec_range(body: Dict[str, Any]) -> sapi.RangeRequest:
    return sapi.RangeRequest(
        key=_b64d(body.get("key")),
        range_end=_b64d(body.get("range_end")),
        limit=int(body.get("limit", 0)),
        revision=int(body.get("revision", 0)),
        serializable=bool(body.get("serializable", False)),
        keys_only=bool(body.get("keys_only", False)),
        count_only=bool(body.get("count_only", False)),
        sort_order=sapi.SortOrder(int(body.get("sort_order", 0))),
        sort_target=sapi.SortTarget(int(body.get("sort_target", 0))),
    )


def _dec_put(body: Dict[str, Any]) -> sapi.PutRequest:
    return sapi.PutRequest(
        key=_b64d(body.get("key")),
        value=_b64d(body.get("value")),
        lease=int(body.get("lease", 0)),
        prev_kv=bool(body.get("prev_kv", False)),
        ignore_value=bool(body.get("ignore_value", False)),
        ignore_lease=bool(body.get("ignore_lease", False)),
    )


def handle(server, path: str, body: Dict[str, Any],
           token: Optional[str] = None) -> Dict[str, Any]:
    """Dispatch one gateway call; returns the JSON-ready response dict.
    Raises KeyError for unknown routes (404 upstream)."""
    s = server
    if path == "/v3/kv/range":
        resp = s.range(_dec_range(body), token=token)
        return {
            "header": _enc_header(resp.header),
            "kvs": [_enc_kv(kv) for kv in resp.kvs],
            "count": str(resp.count),
            **({"more": True} if resp.more else {}),
        }
    if path == "/v3/kv/put":
        resp = s.put(_dec_put(body), token=token)
        out = {"header": _enc_header(resp.header)}
        if resp.prev_kv is not None:
            out["prev_kv"] = _enc_kv(resp.prev_kv)
        return out
    if path == "/v3/kv/deleterange":
        resp = s.delete_range(sapi.DeleteRangeRequest(
            key=_b64d(body.get("key")),
            range_end=_b64d(body.get("range_end")),
            prev_kv=bool(body.get("prev_kv", False)),
        ), token=token)
        return {
            "header": _enc_header(resp.header),
            "deleted": str(resp.deleted),
            "prev_kvs": [_enc_kv(kv) for kv in resp.prev_kvs],
        }
    if path == "/v3/kv/txn":
        resp = s.txn(_dec_txn(body), token=token)
        return _enc_txn_response(resp)
    if path == "/v3/kv/compaction":
        resp = s.compact(sapi.CompactionRequest(
            revision=int(body.get("revision", 0)),
            physical=bool(body.get("physical", False)),
        ), token=token)
        return {"header": _enc_header(resp.header)}
    if path == "/v3/lease/grant":
        resp = s.lease_grant(ttl=int(body.get("TTL", body.get("ttl", 0))),
                             lease_id=int(body.get("ID", body.get("id", 0))),
                             token=token)
        return {
            "header": _enc_header(resp.header),
            "ID": str(resp.id),
            "TTL": str(resp.ttl),
        }
    if path == "/v3/lease/revoke":
        resp = s.lease_revoke(int(body.get("ID", body.get("id", 0))),
                              token=token)
        return {"header": _enc_header(resp.header)}
    if path == "/v3/lease/timetolive":
        out = s.lease_time_to_live(int(body.get("ID", body.get("id", 0))),
                                   keys=bool(body.get("keys", False)))
        if out is None:
            return {"ID": body.get("ID", "0"), "TTL": "-1"}
        return {
            "ID": str(out.get("id", 0)),
            "TTL": str(out.get("ttl", -1)),
            "grantedTTL": str(out.get("granted_ttl", 0)),
            # The lessor tracks attached keys as str; the gateway
            # surface is bytes-in-base64 like every key field.
            "keys": [_b64e(k.encode() if isinstance(k, str) else k)
                     for k in out.get("keys", [])],
        }
    if path == "/v3/lease/leases":
        return {"leases": [{"ID": str(l)} for l in s.lease_leases()]}
    if path == "/v3/maintenance/status":
        return {
            "header": _enc_header(s.response_header()),
            "version": ver.SERVER_VERSION,
            "dbSize": str(s.be.size()),
            "leader": str(s.leader()),
            "raftIndex": str(s.applied_index()),
            "raftTerm": str(s._term),
        }
    if path == "/v3/maintenance/hash":
        h, rev, crev = s.hash_kv(0)
        return {"header": _enc_header(s.response_header()), "hash": h}
    if path == "/v3/cluster/member/list":
        return {
            "header": _enc_header(s.response_header()),
            "members": [
                {
                    "ID": str(m.id),
                    "name": m.name,
                    "peerURLs": list(m.peer_urls),
                    "clientURLs": list(m.client_urls),
                    **({"isLearner": True} if m.is_learner else {}),
                }
                for m in s.cluster.member_list()
            ],
        }
    if path == "/v3/auth/authenticate":
        tok = s.authenticate(body.get("name", ""), body.get("password", ""))
        return {"header": _enc_header(s.response_header()), "token": tok}
    raise KeyError(path)


def _dec_txn(body: Dict[str, Any]) -> sapi.TxnRequest:
    def dec_cmp(c: Dict[str, Any]) -> sapi.Compare:
        target = sapi.CompareTarget(int(c.get("target", 0)))
        kw: Dict[str, Any] = {}
        if "create_revision" in c:
            kw["create_revision"] = int(c["create_revision"])
        if "mod_revision" in c:
            kw["mod_revision"] = int(c["mod_revision"])
        if "version" in c:
            kw["version"] = int(c["version"])
        if "value" in c:
            kw["value"] = _b64d(c["value"])
        return sapi.Compare(
            result=sapi.CompareResult(int(c.get("result", 0))),
            target=target,
            key=_b64d(c.get("key")),
            range_end=_b64d(c.get("range_end")),
            **kw,
        )

    def dec_op(o: Dict[str, Any]) -> sapi.RequestOp:
        if "request_put" in o:
            return sapi.RequestOp(request_put=_dec_put(o["request_put"]))
        if "request_range" in o:
            return sapi.RequestOp(request_range=_dec_range(o["request_range"]))
        if "request_delete_range" in o:
            d = o["request_delete_range"]
            return sapi.RequestOp(request_delete_range=sapi.DeleteRangeRequest(
                key=_b64d(d.get("key")),
                range_end=_b64d(d.get("range_end")),
                prev_kv=bool(d.get("prev_kv", False)),
            ))
        if "request_txn" in o:
            return sapi.RequestOp(request_txn=_dec_txn(o["request_txn"]))
        raise ValueError(f"empty RequestOp: {o}")

    return sapi.TxnRequest(
        compare=[dec_cmp(c) for c in body.get("compare", [])],
        success=[dec_op(o) for o in body.get("success", [])],
        failure=[dec_op(o) for o in body.get("failure", [])],
    )


def _enc_txn_response(resp: sapi.TxnResponse) -> Dict[str, Any]:
    def enc_op(op: sapi.ResponseOp) -> Dict[str, Any]:
        if op.response_put is not None:
            out: Dict[str, Any] = {
                "header": _enc_header(op.response_put.header)}
            if op.response_put.prev_kv is not None:
                out["prev_kv"] = _enc_kv(op.response_put.prev_kv)
            return {"response_put": out}
        if op.response_range is not None:
            rr = op.response_range
            return {"response_range": {
                "header": _enc_header(rr.header),
                "kvs": [_enc_kv(kv) for kv in rr.kvs],
                "count": str(rr.count),
            }}
        if op.response_delete_range is not None:
            dr = op.response_delete_range
            return {"response_delete_range": {
                "header": _enc_header(dr.header),
                "deleted": str(dr.deleted),
            }}
        if op.response_txn is not None:
            return {"response_txn": _enc_txn_response(op.response_txn)}
        return {}

    return {
        "header": _enc_header(resp.header),
        "succeeded": resp.succeeded,
        "responses": [enc_op(op) for op in resp.responses],
    }
