"""CLI entry (ref: server/etcdmain/main.go:25 Main, etcd.go:53
startEtcdOrProxyV2, gateway.go, config.go flag set).

Subcommands:

* (default) / ``etcd``   — start a member from flags or --config-file
* ``gateway start``      — the L4 TCP forwarder (etcdmain/gateway.go)
* ``grpc-proxy start``   — the caching/coalescing L7 proxy

``python -m etcd_tpu`` lands here.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
from typing import List, Optional

from .. import version as ver
from ..embed import Config, config_from_file, start_etcd
from ..embed.config import ConfigError, parse_urls


def _add_member_flags(p: argparse.ArgumentParser) -> None:
    cfg = Config()
    p.add_argument("--name", default=cfg.name)
    p.add_argument("--data-dir", default="")
    p.add_argument("--listen-peer-urls", default=cfg.listen_peer_urls)
    p.add_argument("--listen-client-urls", default=cfg.listen_client_urls)
    p.add_argument("--listen-metrics-urls", default="")
    p.add_argument("--initial-advertise-peer-urls", default="")
    p.add_argument("--advertise-client-urls", default="")
    p.add_argument("--initial-cluster", default="")
    p.add_argument("--initial-cluster-state", default=cfg.initial_cluster_state)
    p.add_argument("--initial-cluster-token", default=cfg.initial_cluster_token)
    p.add_argument("--heartbeat-interval", type=int, default=cfg.heartbeat_interval)
    p.add_argument("--election-timeout", type=int, default=cfg.election_timeout)
    p.add_argument("--snapshot-count", type=int, default=cfg.snapshot_count)
    p.add_argument("--quota-backend-bytes", type=int, default=cfg.quota_backend_bytes)
    p.add_argument("--max-request-bytes", type=int, default=cfg.max_request_bytes)
    p.add_argument("--auto-compaction-mode", default="")
    p.add_argument("--auto-compaction-retention", default="0")
    p.add_argument("--auth-token", default=cfg.auth_token)
    p.add_argument("--initial-corrupt-check", action="store_true")
    p.add_argument("--corrupt-check-time", type=float, default=0.0)
    p.add_argument("--cert-file", default="")
    p.add_argument("--key-file", default="")
    p.add_argument("--trusted-ca-file", default="")
    p.add_argument("--client-cert-auth", action="store_true")
    p.add_argument("--auto-tls", action="store_true")
    p.add_argument("--peer-cert-file", default="")
    p.add_argument("--peer-key-file", default="")
    p.add_argument("--peer-trusted-ca-file", default="")
    p.add_argument("--peer-client-cert-auth", action="store_true")
    p.add_argument("--peer-auto-tls", action="store_true")
    p.add_argument("--discovery-endpoints", default="")
    p.add_argument("--discovery-srv", default="")
    p.add_argument("--enable-v2", action="store_true")
    p.add_argument("--listen-v2-urls", default="")
    p.add_argument("--listen-gateway-urls", default="")
    p.add_argument("--discovery-srv-name", default="")
    p.add_argument("--discovery-token", default="")
    p.add_argument("--log-level", default=cfg.log_level)
    p.add_argument("--enable-pprof", action="store_true")
    p.add_argument("--config-file", default="")


def _config_from_args(args: argparse.Namespace) -> Config:
    if args.config_file:
        return config_from_file(args.config_file)
    cfg = Config()
    for f in cfg.__dataclass_fields__:
        if hasattr(args, f):
            setattr(cfg, f, getattr(args, f))
    if not cfg.initial_cluster and not cfg.discovery_token \
            and not cfg.discovery_srv:
        cfg.initial_cluster = (
            f"{cfg.name}={cfg.effective_advertise_peer_urls()}"
        )
    return cfg


def _run_etcd(args: argparse.Namespace) -> int:
    # Debug hook standing in for Go's SIGQUIT goroutine dump: SIGUSR1
    # writes every thread's stack to stderr (the e2e harness and a
    # human operator use it to diagnose a wedged member in place).
    import faulthandler

    faulthandler.register(signal.SIGUSR1, file=sys.stderr)
    try:
        cfg = _config_from_args(args)
        e = start_etcd(cfg)
    except ConfigError as err:
        print(f"error: {err}", file=sys.stderr)
        return 1

    def _dump_lessor(signum, frame):
        try:
            les = e.server.lessor
            with les._lock:
                lines = [
                    f"  lease {l.id:x} ttl={l.ttl} rem_ttl={l.remaining_ttl} "
                    f"remaining={l.remaining():.1f} "
                    f"queued={l.id in les.expired_queue} "
                    f"pending={l.id in les._expired_pending}"
                    for l in les.lease_map.values()
                ]
                print(
                    f"LESSOR primary={les._primary} "
                    f"n={len(les.lease_map)}\n" + "\n".join(lines),
                    file=sys.stderr, flush=True,
                )
        except Exception as exc:  # noqa: BLE001 — diagnostics only
            print(f"LESSOR dump failed: {exc!r}", file=sys.stderr, flush=True)

    signal.signal(signal.SIGUSR2, _dump_lessor)
    ch, cp = e.client_addr
    mh, mp = e.metrics_addr
    print(
        f"etcd_tpu member {cfg.name} ({e.server.id:x}) serving: "
        f"clients http://{ch}:{cp}, metrics http://{mh}:{mp}",
        flush=True,
    )
    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    try:
        while not stop.is_set() and not e.server._stopped.is_set():
            stop.wait(0.2)
    finally:
        e.close()
    return 0


def _run_gateway(args: argparse.Namespace) -> int:
    from ..proxy.tcpproxy import TCPProxy

    eps = parse_urls(
        ",".join(
            x if "://" in x else f"http://{x}"
            for x in args.endpoints.split(",")
        )
    )
    host, port = args.listen_addr.rsplit(":", 1)
    proxy = TCPProxy(eps, bind=(host, int(port)),
                     monitor_interval=args.retry_delay)
    print(
        f"tcpproxy: ready to proxy client requests to {eps}", flush=True
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.is_set():
            stop.wait(0.2)
    finally:
        proxy.stop()
    return 0


def _run_grpc_proxy(args: argparse.Namespace) -> int:
    from ..proxy.grpcproxy import start_grpc_proxy

    eps = []
    for x in args.endpoints.split(","):
        if "://" not in x:
            x = f"http://{x}"
        eps.extend(parse_urls(x))
    host, port = args.listen_addr.rsplit(":", 1)
    proxy = start_grpc_proxy(eps, bind=(host, int(port)))
    print(f"grpcproxy: listening on {proxy.addr}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    try:
        while not stop.is_set():
            stop.wait(0.2)
    finally:
        proxy.stop()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = argparse.ArgumentParser(
        prog="etcd_tpu", description="etcd-capability TPU-native framework"
    )
    parser.add_argument("--version", action="store_true")
    sub = parser.add_subparsers(dest="cmd")

    p_etcd = sub.add_parser("etcd", help="start a member")
    _add_member_flags(p_etcd)

    p_gw = sub.add_parser("gateway", help="L4 gateway")
    gw_sub = p_gw.add_subparsers(dest="gw_cmd")
    p_gw_start = gw_sub.add_parser("start")
    p_gw_start.add_argument("--listen-addr", default="127.0.0.1:23790")
    p_gw_start.add_argument("--endpoints", default="127.0.0.1:2379")
    p_gw_start.add_argument("--retry-delay", type=float, default=60.0)

    p_gp = sub.add_parser("grpc-proxy", help="L7 caching/coalescing proxy")
    gp_sub = p_gp.add_subparsers(dest="gp_cmd")
    p_gp_start = gp_sub.add_parser("start")
    p_gp_start.add_argument("--listen-addr", default="127.0.0.1:23790")
    p_gp_start.add_argument("--endpoints", default="127.0.0.1:2379")

    # Bare flags (no subcommand) start a member, like `etcd --...`.
    if not argv or argv[0].startswith("-"):
        if "--version" in argv:
            print(f"etcd_tpu Version: {ver.SERVER_VERSION}")
            print(f"Cluster Version: {ver.CLUSTER_VERSION}")
            print(f"API Version: {ver.API_VERSION}")
            return 0
        argv = ["etcd"] + argv

    args = parser.parse_args(argv)
    if args.cmd == "etcd":
        return _run_etcd(args)
    if args.cmd == "gateway":
        if getattr(args, "gw_cmd", None) != "start":
            p_gw.print_help()
            return 2
        return _run_gateway(args)
    if args.cmd == "grpc-proxy":
        if getattr(args, "gp_cmd", None) != "start":
            p_gp.print_help()
            return 2
        return _run_grpc_proxy(args)
    parser.print_help()
    return 2
