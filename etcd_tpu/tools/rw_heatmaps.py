"""Client-side read/write grid heatmap data generator
(ref: tools/rw-heatmaps — sweeps value size × R/W ratio and emits CSV
for the heatmap plot script).

`python -m etcd_tpu.tools.rw_heatmaps --endpoints h:p [--out rw.csv]`
runs a grid of (value_size, read_ratio) cells against a live cluster
and writes one CSV row per cell:

    value_size,conn_count,read_ratio,reads_per_sec,writes_per_sec

The reference drives `benchmark mixed` over the same grid and plots
with rw-heatmaps/plot_data.py; the CSV schema here matches what that
plotting flow consumes.

Cluster-SIDE heat (per-group commit progress / backlog over time) now
comes from the fleet observatory instead (ISSUE 10): members run with
``fleet_summary`` on, the device summarizes every round, and
``obs.fleet.FleetHub`` dumps a bounded groups×time ``fleetheat_*``
artifact — see ``tools/fleet_console.py``. This tool remains the
client-facing grid sweep; its default output lands under the same
``artifacts/`` naming scheme so grid CSVs and fleet heat dumps live
side by side.
"""

from __future__ import annotations

import argparse
import csv
import random
import sys
import threading
import time
from typing import List, Tuple

from ..client.client import Client
from ..server import api as sapi


def _parse_endpoints(s: str) -> List[Tuple[str, int]]:
    out = []
    for part in s.split(","):
        part = part.strip()
        if "://" in part:
            part = part.split("://", 1)[1]
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


def run_cell(endpoints, value_size: int, read_ratio: float, clients: int,
             duration: float) -> Tuple[float, float]:
    """One grid cell: mixed load for `duration`s; returns (r/s, w/s)."""
    counts = [[0, 0] for _ in range(clients)]  # [reads, writes]
    stop = threading.Event()
    value = b"x" * value_size

    def worker(idx: int) -> None:
        c = Client(endpoints)
        rnd = random.Random(idx)
        try:
            while not stop.is_set():
                key = b"heat/%d" % rnd.randrange(1000)
                if rnd.random() < read_ratio:
                    c.get(key, serializable=True)
                    counts[idx][0] += 1
                else:
                    c.put(key, value)
                    counts[idx][1] += 1
        except Exception:  # noqa: BLE001 — cell ends on conn loss
            pass
        finally:
            c.close()

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(duration)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    dt = time.perf_counter() - t0
    reads = sum(c[0] for c in counts)
    writes = sum(c[1] for c in counts)
    return reads / dt, writes / dt


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rw-heatmaps")
    p.add_argument("--endpoints", default="127.0.0.1:2379")
    p.add_argument("--out", default="",
                   help="output CSV (default: a timestamped "
                        "artifacts/rwgrid_* path via obs.artifacts)")
    p.add_argument("--clients", type=int, default=8)
    p.add_argument("--duration", type=float, default=2.0,
                   help="seconds per grid cell")
    p.add_argument("--value-sizes", default="64,256,1024,4096")
    p.add_argument("--read-ratios", default="0.0,0.25,0.5,0.75,1.0")
    args = p.parse_args(argv)

    endpoints = _parse_endpoints(args.endpoints)
    sizes = [int(x) for x in args.value_sizes.split(",")]
    ratios = [float(x) for x in args.read_ratios.split(",")]
    if not args.out:
        from ..obs.artifacts import KIND_RWGRID, dump_path

        args.out = dump_path(KIND_RWGRID, "client", "grid", ext="csv")

    with open(args.out, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["value_size", "conn_count", "read_ratio",
                    "reads_per_sec", "writes_per_sec"])
        for size in sizes:
            for ratio in ratios:
                rps, wps = run_cell(endpoints, size, ratio,
                                    args.clients, args.duration)
                w.writerow([size, args.clients, ratio,
                            f"{rps:.1f}", f"{wps:.1f}"])
                print(f"size={size} ratio={ratio:.2f}: "
                      f"{rps:.0f} r/s {wps:.0f} w/s", flush=True)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
