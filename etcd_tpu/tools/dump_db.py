"""Offline backend inspector (ref: tools/etcd-dump-db — list-bucket,
iterate-bucket, hash over a stopped member's db file)."""

from __future__ import annotations

import argparse
import hashlib
import os
import sqlite3
import sys
from typing import List, Optional


TABLE_PREFIX = "bucket_"  # storage/backend.py Bucket.table naming


def _tables(conn: sqlite3.Connection) -> List[str]:
    return [
        r[0] for r in conn.execute(
            "SELECT name FROM sqlite_master WHERE type='table' ORDER BY name"
        )
        if r[0].startswith(TABLE_PREFIX)
    ]


def _bucket_name(table: str) -> str:
    return table[len(TABLE_PREFIX):]


def _open_ro(db_path: str) -> sqlite3.Connection:
    # mode=ro (not immutable): the backend runs journal_mode=WAL, so a
    # not-yet-checkpointed -wal sidecar must be consulted for reads.
    return sqlite3.connect(f"file:{db_path}?mode=ro", uri=True)


def list_bucket(db_path: str) -> int:
    conn = _open_ro(db_path)
    try:
        for t in _tables(conn):
            print(_bucket_name(t))
    finally:
        conn.close()
    return 0


def iterate_bucket(db_path: str, bucket: str, limit: int = 0,
                   decode: bool = False) -> int:
    conn = _open_ro(db_path)
    table = TABLE_PREFIX + bucket
    try:
        if table not in _tables(conn):
            print(f"bucket {bucket!r} not found", file=sys.stderr)
            return 1
        n = 0
        for k, v in conn.execute(f"SELECT k, v FROM {table} ORDER BY k"):
            if decode and bucket == "key":
                from ..storage.mvcc.kv import KeyValue
                from ..storage.mvcc.revision import (
                    bytes_to_rev, is_tombstone_key,
                )

                rev = bytes_to_rev(k)
                if is_tombstone_key(k):
                    print(f"rev={{{rev.main}/{rev.sub}}} TOMBSTONE "
                          f"key={v!r}")
                else:
                    kv = KeyValue.unmarshal(v)
                    print(
                        f"rev={{{rev.main}/{rev.sub}}} key={kv.key!r} | "
                        f"val={kv.value!r} | created={kv.create_revision} "
                        f"| mod={kv.mod_revision} | ver={kv.version} "
                        f"| lease={kv.lease:x}"
                    )
            else:
                print(f"key={k.hex()} | value={v.hex()}")
            n += 1
            if limit and n >= limit:
                break
    finally:
        conn.close()
    return 0


def hash_db(db_path: str) -> int:
    h = hashlib.sha256()
    conn = _open_ro(db_path)
    try:
        for t in _tables(conn):
            h.update(_bucket_name(t).encode())
            for k, v in conn.execute(f"SELECT k, v FROM {t} ORDER BY k"):
                h.update(k)
                h.update(v)
    finally:
        conn.close()
    print(f"db path: {db_path}")
    print(f"Hash: {int.from_bytes(h.digest()[:4], 'big'):x}")
    return 0


def _resolve_db(path: str) -> str:
    """Accept a data dir, member dir, or db file."""
    if os.path.isfile(path):
        return path
    direct = os.path.join(path, "db")
    if os.path.isfile(direct):
        return direct
    for entry in sorted(os.listdir(path)):
        cand = os.path.join(path, entry, "db")
        if entry.startswith("member-") and os.path.isfile(cand):
            return cand
    raise FileNotFoundError(f"no db under {path}")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="etcd-dump-db")
    sub = p.add_subparsers(dest="cmd")
    x = sub.add_parser("list-bucket")
    x.add_argument("path")
    x = sub.add_parser("iterate-bucket")
    x.add_argument("path")
    x.add_argument("bucket")
    x.add_argument("--limit", type=int, default=0)
    x.add_argument("--decode", action="store_true")
    x = sub.add_parser("hash")
    x.add_argument("path")
    args = p.parse_args(argv)
    try:
        if args.cmd == "list-bucket":
            return list_bucket(_resolve_db(args.path))
        if args.cmd == "iterate-bucket":
            return iterate_bucket(
                _resolve_db(args.path), args.bucket, args.limit, args.decode
            )
        if args.cmd == "hash":
            return hash_db(_resolve_db(args.path))
    except FileNotFoundError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    p.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
