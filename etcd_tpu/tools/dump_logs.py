"""Offline WAL inspector (ref: tools/etcd-dump-logs — dump entries
with decoded request payloads, HardState records, snapshot markers)."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from ..native import walog as nwalog
from ..storage import wal as walmod


def _resolve_wal(path: str) -> str:
    if os.path.isdir(os.path.join(path, "wal")):
        return os.path.join(path, "wal")
    if os.path.basename(path) == "wal" and os.path.isdir(path):
        return path
    for entry in sorted(os.listdir(path)):
        cand = os.path.join(path, entry, "wal")
        if entry.startswith("member-") and os.path.isdir(cand):
            return cand
    raise FileNotFoundError(f"no wal dir under {path}")


def _describe_entry(term: int, index: int, etype: int, data: bytes) -> str:
    from ..raft.types import EntryType

    if etype in (int(EntryType.EntryConfChange), int(EntryType.EntryConfChangeV2)):
        kind = ("conf-change" if etype == int(EntryType.EntryConfChange)
                else "conf-change-v2")
        return f"{term}\t{index}\t{kind}\t{len(data)}B"
    if not data:
        return f"{term}\t{index}\tnorm\t<empty (term start)>"
    try:
        from ..server.api import InternalRaftRequest

        req = InternalRaftRequest.unmarshal(data)
        detail = f"id={req.id} op={req.op}"
        r = req.req
        key = getattr(r, "key", None)
        if key is not None:
            detail += f" key={key!r}"
        return f"{term}\t{index}\tnorm\t{detail}"
    except Exception:  # noqa: BLE001 — not an InternalRaftRequest
        return f"{term}\t{index}\tnorm\t{len(data)}B (opaque)"


def dump(path: str, start_index: int = 0, limit: int = 0) -> int:
    wal_dir = _resolve_wal(path)
    print(f"WAL entries from {wal_dir}:")
    print("term\tindex\ttype\tdata")
    n = 0
    for rtype, data, _seq, _meta in nwalog.read_all(wal_dir, repair=False):
        if rtype == walmod.REC_ENTRY:
            hdr = walmod._ENTRY_HDR
            term, index, etype = hdr.unpack(data[: hdr.size])
            if index < start_index:
                continue
            print(_describe_entry(term, index, etype, data[hdr.size:]))
            n += 1
            if limit and n >= limit:
                break
        elif rtype == walmod.REC_STATE:
            term, vote, commit = walmod._STATE.unpack(data)
            print(f"-\t-\tstate\tterm={term} vote={vote:x} commit={commit}")
        elif rtype == walmod.REC_SNAPSHOT:
            index, term = walmod._SNAP.unpack(data)
            print(f"{term}\t{index}\tsnapshot\t-")
        elif rtype == walmod.REC_METADATA:
            print(f"-\t-\tmetadata\t{data.hex()}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="etcd-dump-logs")
    p.add_argument("path", help="data dir, member dir, or wal dir")
    p.add_argument("--start-index", type=int, default=0)
    p.add_argument("--limit", type=int, default=0)
    args = p.parse_args(argv)
    try:
        return dump(args.path, args.start_index, args.limit)
    except FileNotFoundError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
