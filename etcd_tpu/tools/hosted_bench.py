"""Hosted-path benchmark: 3 real OS processes over a selectable peer
fabric (``--fabric=tcp`` sockets or ``--fabric=shm`` mmap'd SPSC
rings, ISSUE 16), G groups on CPU — the service-rate number next to
bench.py's kernel rate (VERDICT r04 task #1: an artifact with a
floor). ``--pin-cores`` pins member i to core (i-1) mod ncpu, the
one-core-per-member multi-core shape.

Writes HOSTED_BENCH.json at the repo root:

    {"puts_per_sec": ..., "p50_ms": ..., "p99_ms": ...,
     "n": ..., "groups_led": ...,
     "phase_ms_per_round": {"stage": ..., "step": ..., "extract": ...,
                            "collect": ..., "wal": ..., "apply": ...,
                            "send": ...},
     "restart_catchup_s": ..., "config": "...", "captured_at": "..."}

(phase_ms_per_round is the member-round budget averaged over members —
the BENCH_NOTES phase table, reproducible from the artifact; the same
split is exported as the round-phase histograms under --telemetry.)

With ``--trace`` the workers run the proposal-lifecycle tracer
(etcd_tpu.obs) and the artifact additionally carries ``slo``: per-hop
p50/p99 over the merged cross-member spans (the named decomposition
propose→stage→step→fsync→send→peer-fsync→ack→commit→apply) plus
traced commit/apply percentiles — the per-hop budget shape ROADMAP
item 4's gRPC SLO story consumes. The merged Perfetto trace lands in
``artifacts/hosted_trace.json``. Tracing has measurable sampling cost,
so ``--trace`` runs are labeled and are NOT the parity baseline.

``--wal-pipeline`` (or ``ETCD_TPU_WAL_PIPELINE=1``) flies the workers
with the async group-commit WAL pipeline (ISSUE 13); A/B rows against
the same-day inline baseline land in BENCH_NOTES and the
``artifacts/hosted_walpipe_*.json`` artifacts. Pair with
``ETCD_TPU_FSYNC_DELAY_MS`` (walog-level slow-disk emulation) on boxes
whose local fsync is microsecond-class — the pipeline overlaps IO
wait, so a free fsync leaves nothing to win.

``--apply-plane`` flies the workers with the device-resident apply
plane (ISSUE 19: tensorized KV + leader leases); ``--read-mix 0.9``
converts that fraction of each member's ops into linearizable reads
and records a ``reads`` block (merged read percentiles plus the
lease-hit vs ReadIndex-fallback split). With ``--trace`` the SLO
table additionally carries a ``read_hop`` row with the same split —
the apply plane's headline is leased reads taking ZERO quorum hops.

Run:  python -m etcd_tpu.tools.hosted_bench [--groups 1024] [--n 3000]
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

MEMBERS = 3


def free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def spawn(mid, raft_ports, admin_ports, data_dir, groups, gen=0,
          trace=0, wal_pipeline=False, fabric="tcp", shm_dir=None,
          pin_cores=False, apply_plane=False):
    peers = [
        f"--peer={pid}=127.0.0.1:{raft_ports[pid]}"
        for pid in range(1, MEMBERS + 1) if pid != mid
    ]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ETCD_TPU_PROF"] = "1"
    if trace:
        # Sample rate shared by all members (the cross-member join
        # requires identical sampling decisions); seed pinned so two
        # --trace runs sample the same key population.
        env["ETCD_TPU_TRACE_SAMPLE"] = str(trace)
        env.setdefault("ETCD_TPU_TRACE_SEED", "0")
    # Transfer sentinel (ISSUE 7): worker round dispatch fails hard on
    # any implicit transfer instead of silently syncing per round.
    env.setdefault("ETCD_TPU_TRANSFER_GUARD", "disallow")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    log = open(os.path.join(data_dir, f"worker-{mid}-gen{gen}.log"), "wb")
    return subprocess.Popen(
        [
            sys.executable, "-m", "etcd_tpu.batched.hosting_proc",
            "--id", str(mid), "--members", str(MEMBERS),
            "--groups", str(groups), "--data-dir", data_dir,
            "--bind", f"127.0.0.1:{raft_ports[mid]}",
            "--admin", f"127.0.0.1:{admin_ports[mid]}",
            "--tick-interval", "0.1",
        ] + (["--trace"] if trace else [])
        + (["--wal-pipeline"] if wal_pipeline else [])
        + (["--apply-plane"] if apply_plane else [])
        + (["--fabric", fabric] if fabric != "tcp" else [])
        + (["--shm-dir", shm_dir] if fabric == "shm" else [])
        # One pinned core per member: member i on core (i-1) mod ncpu.
        # On a 1-core box every member pins to core 0 (the status quo
        # made explicit); on a real multi-core box this is the shape
        # the shm fabric's headline targets assume.
        + (["--pin-core", str((mid - 1) % (os.cpu_count() or 1))]
           if pin_cores else [])
        + peers,
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def main() -> None:
    from etcd_tpu.batched.hosting_proc import wait_admin

    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=1024)
    ap.add_argument("--n", type=int, default=3000)
    ap.add_argument("--value-size", type=int, default=64)
    ap.add_argument("--inflight", type=int, default=4,
                    help="wave cap per led group")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--out", default=None)
    ap.add_argument("--trace", type=int, nargs="?", const=8, default=0,
                    metavar="SAMPLE",
                    help="run the workers with proposal-lifecycle "
                         "tracing (1-in-SAMPLE, default 8) and record "
                         "the per-hop SLO table into the artifact")
    from etcd_tpu.pkg import env_flag

    ap.add_argument("--wal-pipeline", action="store_true",
                    default=env_flag("ETCD_TPU_WAL_PIPELINE"),
                    help="run the workers with the async group-commit "
                         "WAL pipeline (ISSUE 13); also honored via "
                         "ETCD_TPU_WAL_PIPELINE=1 — A/B rows against "
                         "the inline baseline land in BENCH_NOTES")
    ap.add_argument("--fabric", choices=("tcp", "shm"), default="tcp",
                    help="peer transport for the workers: tcp "
                         "(TCPRouter sockets, default) or shm (the "
                         "mmap'd SPSC ring fabric, ISSUE 16); "
                         "artifacts are labeled with the choice")
    ap.add_argument("--shm-dir", default=None,
                    help="shared lane-ring directory for --fabric=shm "
                         "(default: <data-dir>/shmfabric)")
    ap.add_argument("--pin-cores", action="store_true",
                    help="pin member i to core (i-1) mod ncpu — the "
                         "one-core-per-member multi-core shape")
    ap.add_argument("--apply-plane", action="store_true",
                    help="run the workers with the device-resident "
                         "apply plane (ISSUE 19): tensorized KV + "
                         "leader leases; lease-held linearizable "
                         "reads skip the ReadIndex quorum round")
    ap.add_argument("--read-mix", type=float, default=0.0,
                    metavar="FRAC",
                    help="fraction of each member's ops issued as "
                         "linearizable reads (e.g. 0.9); the SLO "
                         "table gains a read-hop row splitting "
                         "lease-hit vs ReadIndex-fallback")
    args = ap.parse_args()
    if not 0.0 <= args.read_mix <= 1.0:
        ap.error("--read-mix must be in [0, 1]")
    # Slow-disk emulation label (native/walog.py): a bench flown with
    # ETCD_TPU_FSYNC_DELAY_MS set must say so in its artifact config.
    fsync_delay = os.environ.get("ETCD_TPU_FSYNC_DELAY_MS", "")
    delay_tag = (f" fsync_delay={fsync_delay}ms"
                 if fsync_delay not in ("", "0") else "")
    import tempfile

    data_dir = args.data_dir or tempfile.mkdtemp(prefix="hosted-bench-")
    shm_dir = args.shm_dir or os.path.join(data_dir, "shmfabric")
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out_path = args.out or os.path.join(repo, "HOSTED_BENCH.json")

    raft_p = dict(zip(range(1, MEMBERS + 1), free_ports(MEMBERS)))
    admin_p = dict(zip(range(1, MEMBERS + 1), free_ports(MEMBERS)))
    procs, clients = {}, {}
    try:
        for mid in range(1, MEMBERS + 1):
            procs[mid] = spawn(mid, raft_p, admin_p, data_dir,
                               args.groups, trace=args.trace,
                               wal_pipeline=args.wal_pipeline,
                               fabric=args.fabric, shm_dir=shm_dir,
                               pin_cores=args.pin_cores,
                               apply_plane=args.apply_plane)
        for mid in range(1, MEMBERS + 1):
            clients[mid] = wait_admin(("127.0.0.1", admin_p[mid]),
                                      timeout=300.0)
        # Balanced leadership: group g led by member g%3+1, re-asserted
        # until it holds STEADY — an unbalanced cluster turns the bench
        # into a one-member measurement (and a still-settling one loses
        # proposals to leadership moves mid-run).
        deadline = time.monotonic() + 300.0
        nudge = 0.0
        while time.monotonic() < deadline:
            leads = clients[1].call(op="leaders")["leads"]
            misplaced = [g for g, x in enumerate(leads)
                         if x != g % MEMBERS + 1]
            if not misplaced:
                break
            if time.monotonic() > nudge:
                for mid, c in clients.items():
                    # Groups this member should NOT lead but does:
                    # transfer them to their assigned member. Groups
                    # with no leader at all: campaign directly.
                    for target in range(1, MEMBERS + 1):
                        if target == mid:
                            continue
                        mine = [g for g in misplaced
                                if leads[g] == mid
                                and g % MEMBERS == target - 1]
                        if mine:
                            # wait_s=0: this loop is a periodic nudge
                            # with its own re-poll cadence — the op's
                            # default bounded wait would serialize up
                            # to MEMBERS^2 five-second waits per pass.
                            c.call(op="transfer", groups=mine[:512],
                                   to=target, wait_s=0)
                    orphans = [g for g in misplaced
                               if leads[g] == 0
                               and g % MEMBERS == mid - 1]
                    if orphans:
                        c.call(op="campaign", groups=orphans[:512])
                nudge = time.monotonic() + 3.0
            time.sleep(0.25)
        else:
            raise TimeoutError(f"leadership never balanced "
                               f"({len(misplaced)} misplaced)")
        time.sleep(2.0)  # settle
        for c in clients.values():
            c.call(op="prof_reset")

        # Aggregate service rate: all three members bench their own
        # groups CONCURRENTLY (each drives ~G/3 leaders; the cluster's
        # real offered-load shape, like `benchmark put` with multiple
        # clients against all endpoints).
        from concurrent.futures import ThreadPoolExecutor

        from etcd_tpu.batched.hosting_proc import ProcClient

        per = max(args.n // MEMBERS, 1)

        def run_bench(mid):
            bc = ProcClient(("127.0.0.1", admin_p[mid]), timeout=900.0)
            try:
                return bc.call(op="bench", n=per,
                               value_size=args.value_size,
                               inflight=args.inflight,
                               read_mix=args.read_mix)
            finally:
                bc.close()

        with ThreadPoolExecutor(MEMBERS) as ex:
            parts = list(ex.map(run_bench, range(1, MEMBERS + 1)))
        bad = [p for p in parts if not p.get("ok")]
        if bad:
            raise RuntimeError(f"bench failed: {bad}")
        # Per-phase member-round budget (ms/round, averaged over the
        # members): stage/step/extract/collect from the rawnode timers
        # (ETCD_TPU_PROF is set on the workers), wal/apply/send from
        # the member pipeline stats — the BENCH_NOTES phase table,
        # recorded in the artifact instead of ad-hoc profiling.
        phase_ms = {}
        for mid, c in clients.items():
            prof = c.call(op="prof")
            st = prof.get("stats", {})
            print(f"member {mid} prof: {st}", file=sys.stderr)
            rounds = max(st.get("rn_rounds", 0), 1)
            m_rounds = max(st.get("rounds", 0), 1)
            for p in ("stage", "step", "extract", "collect"):
                v = st.get(f"rn_{p}")
                if v is not None:
                    phase_ms.setdefault(p, []).append(v / rounds * 1e3)
            # "fsync" (stats fsync_s) is the device half alone; with
            # the pipeline on it runs OFF the round thread, so the
            # amortized ms/round here shrinking is the headline.
            for p in ("wal", "apply", "send", "fsync"):
                v = st.get(f"{p}_s")
                if v is not None:
                    phase_ms.setdefault(p, []).append(v / m_rounds * 1e3)
        phase_ms = {
            p: round(sum(v) / len(v), 2) for p, v in phase_ms.items()
        }
        # Aggregate: throughputs add (concurrent windows); percentiles
        # come from the UNION of the members' latency samples.
        total_done = sum(p["completed"] for p in parts)
        merged = sorted(
            x for p in parts for x in p.pop("lat_ms_samples", []))
        bench = {
            "ok": True,
            "n": sum(p["n"] for p in parts),
            "completed": total_done,
            "lost": sum(p["lost"] for p in parts),
            "groups": sum(p["groups"] for p in parts),
            "puts_per_sec": round(
                sum(p["puts_per_sec"] for p in parts), 1),
            "p50_ms": merged[len(merged) // 2] if merged else 0.0,
            "p99_ms": (merged[max(int(len(merged) * 0.99) - 1, 0)]
                       if merged else 0.0),
            "per_member": parts,
        }
        # Read-mix lane (ISSUE 19): merged read percentiles from the
        # union of samples (same rule as writes) plus the lease-hit /
        # ReadIndex-fallback split — the apply plane's headline is the
        # hit ratio, not just the latency.
        if args.read_mix > 0:
            rmerged = sorted(
                x for p in parts for x in p.pop("read_lat_ms_samples", []))
            hits = sum(p.get("lease_hits", 0) for p in parts)
            falls = sum(p.get("lease_fallbacks", 0) for p in parts)
            bench["reads"] = {
                "n": sum(p.get("reads", 0) for p in parts),
                "completed": sum(p.get("reads_completed", 0)
                                 for p in parts),
                "lost": sum(p.get("reads_lost", 0) for p in parts),
                "reads_per_sec": round(
                    sum(p.get("reads_per_sec", 0.0) for p in parts), 1),
                "p50_ms": rmerged[len(rmerged) // 2] if rmerged else 0.0,
                "p99_ms": (rmerged[max(int(len(rmerged) * 0.99) - 1, 0)]
                           if rmerged else 0.0),
                "lease_hits": hits,
                "lease_fallbacks": falls,
                "lease_hit_ratio": round(hits / max(hits + falls, 1), 4),
            }

        # SLO table (--trace): pull every member's span ring over the
        # admin 'trace' op and join them in-process — per-hop p50/p99
        # on the aligned clock, the shape the gRPC front-end's SLO
        # story consumes. Captured BEFORE the kill below tears member
        # 3's ring away.
        slo = None
        if args.trace:
            from etcd_tpu.obs.export import validate_chrome_trace
            from etcd_tpu.obs.merge import hop_stats, merge

            payloads = []
            for mid, c in clients.items():
                r = c.call(op="trace")
                if r.get("ok"):
                    payloads.append(r["payload"])
                else:
                    print(f"member {mid} trace pull failed: {r}",
                          file=sys.stderr)
            if len(payloads) == MEMBERS:
                trace_obj, slo = merge(payloads)
                validate_chrome_trace(trace_obj)
                tpath = os.path.join(repo, "artifacts",
                                     "hosted_trace.json")
                os.makedirs(os.path.dirname(tpath), exist_ok=True)
                with open(tpath, "w") as f:
                    json.dump(trace_obj, f)
                    f.write("\n")
                slo["merged_trace"] = os.path.relpath(tpath, repo)
                # Self-labeling: the slo block names its own capture
                # conditions, so grafting it into an untraced headline
                # artifact (traced runs are never the headline — the
                # sampling cost is real) keeps the provenance visible.
                # Read hop (ISSUE 19): the client-observed
                # linearizable-read latency next to the traced write
                # hops, with the lease-hit vs ReadIndex-fallback split
                # counted separately. Kept OUT of slo["hops"] — those
                # rows telescope to the write e2e; this one doesn't.
                if args.read_mix > 0 and "reads" in bench:
                    r = bench["reads"]
                    slo["read_hop"] = {
                        "n": r["completed"],
                        "p50_ms": r["p50_ms"],
                        "p99_ms": r["p99_ms"],
                        "lease_hit": r["lease_hits"],
                        "readindex_fallback": r["lease_fallbacks"],
                        "lease_hit_ratio": r["lease_hit_ratio"],
                    }
                slo["config"] = (f"G={args.groups} R={MEMBERS} "
                                 f"value={args.value_size}B "
                                 f"inflight={args.inflight}/group CPU "
                                 f"fabric={args.fabric} "
                                 f"trace=1/{args.trace}"
                                 + (" walpipe=on" if args.wal_pipeline
                                    else "")
                                 + (" applyplane=on" if args.apply_plane
                                    else "")
                                 + (f" read_mix={args.read_mix:g}"
                                    if args.read_mix > 0 else "")
                                 + delay_tag)
                slo["captured_at"] = time.strftime("%Y-%m-%dT%H:%M:%S")
                print(f"slo: {json.dumps(slo['hops'])}",
                      file=sys.stderr)

        # Restart catch-up: kill -9 member 3, write under its nose,
        # restart, time until it serves the missed write.
        procs[3].kill()
        procs[3].wait(timeout=10)
        clients[3].close()
        g = next(g for g in range(args.groups) if g % MEMBERS == 0)
        clients[1].call(op="put", g=g, k="Y2F0Y2h1cA==",  # b64 "catchup"
                        v="MQ==")
        t0 = time.monotonic()
        procs[3] = spawn(3, raft_p, admin_p, data_dir, args.groups,
                         gen=1, trace=args.trace,
                         wal_pipeline=args.wal_pipeline,
                         fabric=args.fabric, shm_dir=shm_dir,
                         pin_cores=args.pin_cores,
                         apply_plane=args.apply_plane)
        clients[3] = wait_admin(("127.0.0.1", admin_p[3]), timeout=300.0)
        while time.monotonic() - t0 < 180.0:
            if clients[3].get(g, b"catchup") == b"1":
                break
            time.sleep(0.25)
        else:
            raise TimeoutError("restarted member did not catch up")
        catchup_s = time.monotonic() - t0

        result = {
            "puts_per_sec": bench["puts_per_sec"],
            "p50_ms": bench["p50_ms"],
            "p99_ms": bench["p99_ms"],
            "n": bench["n"],
            "completed": bench.get("completed", bench["n"]),
            "lost": bench.get("lost", 0),
            "groups_led": bench["groups"],
            "phase_ms_per_round": phase_ms,
            "fabric": args.fabric,
            "restart_catchup_s": round(catchup_s, 1),
            "config": (f"G={args.groups} R={MEMBERS} procs={MEMBERS} "
                       f"value={args.value_size}B "
                       f"inflight={args.inflight}/group CPU "
                       f"fabric={args.fabric}"
                       + (" pinned" if args.pin_cores else "")
                       + (f" trace=1/{args.trace}" if args.trace
                          else "")
                       + (" walpipe=on" if args.wal_pipeline else "")
                       + (" applyplane=on" if args.apply_plane else "")
                       + (f" read_mix={args.read_mix:g}"
                          if args.read_mix > 0 else "")
                       + delay_tag),
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }
        if "reads" in bench:
            result["reads"] = bench["reads"]
        if slo is not None:
            result["slo"] = slo
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(json.dumps(result))
    finally:
        for c in clients.values():
            try:
                c.call(op="stop")
            except Exception:  # noqa: BLE001
                pass
            c.close()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


if __name__ == "__main__":
    main()
