"""Tunnel-live TPU measurement batch (BENCH_NOTES round-5 task).

Fires EVERY open TPU perf question in one run, so a live tunnel window
is never wasted on a single capture (the round-4 lesson):

  (a) re-capture the G=65536 headline rate (six-lane deliver, the
      bench.py default config) so the driver record can be confirmed;
  (b) deliver-shape A/B ON TPU — merged scans and the ISSUE 14
      vectorized fold vs the six-lane baseline (--deliver-shape; CPU
      has not predicted TPU for this kernel before, so the accelerator
      default only ever moves on numbers from this section);
  (c) the Pallas fused quorum/ring kernels vs their XLA forms
      (integration gate, pallas_kernels.py docstring);
  (d) device-side commit p50 — rounds-to-commit counted by stepping
      single rounds (correctness only), priced at the per-round wall
      time of the async multi-round scans, NOT at the tunnel RTT of a
      single dispatch (the round-4 number was RTT-dominated);
  (e) an xprof trace of the steady-state round (best effort — the
      axon remote platform may not support profiling).

Writes artifacts/tpu_r05/batch.json with every number + provenance and
appends nothing anywhere else (BENCH_NOTES is written by hand from it).

    python -m etcd_tpu.tools.tpu_batch [--groups 65536]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _log(msg: str) -> None:
    print(f"[tpu_batch {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def _make_engine(groups: int, shape: str):
    import jax.numpy as jnp

    from etcd_tpu.batched import BatchedConfig, MultiRaftEngine

    cfg = BatchedConfig(
        num_groups=groups,
        num_replicas=3,
        window=32,
        max_ents_per_msg=4,
        max_props_per_round=2,
        election_timeout=1 << 20,
        heartbeat_timeout=4,
        auto_compact=True,
        lanes_minor=True,  # pinned lane-filling layout (bench.py on TPU)
        deliver_shape=shape,
    )
    eng = MultiRaftEngine(cfg)
    eng.campaign([g * cfg.num_replicas for g in range(groups)])
    eng.run_rounds(4, tick=False)
    assert (eng.leaders() == 0).all(), "election failed in batch setup"
    props = jnp.zeros((cfg.num_instances,), jnp.int32)
    props = props.at[jnp.arange(groups) * cfg.num_replicas].set(2)
    return eng, props


def _rate(eng, props, rounds_per_call: int = 16, calls: int = 8) -> float:
    import jax

    eng.run_rounds(rounds_per_call, tick=True, propose_n=props)  # warmup
    jax.block_until_ready(eng.state.commit)
    t0 = time.perf_counter()
    for _ in range(calls):
        eng.run_rounds(rounds_per_call, tick=True, propose_n=props)
    jax.block_until_ready(eng.state.commit)
    dt = time.perf_counter() - t0
    return eng.cfg.num_groups * rounds_per_call * calls / dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=65536)
    ap.add_argument("--out", default="artifacts/tpu_r05")
    ap.add_argument("--deliver-shape", dest="deliver_shapes",
                    default="merged,vectorized",
                    help="comma-separated deliver shapes to A/B "
                         "against the six-lane baseline (section b)")
    args = ap.parse_args()
    args.deliver_shapes = [s.strip() for s in
                           args.deliver_shapes.split(",") if s.strip()]

    import jax
    import jax.numpy as jnp

    from ..batched.compile_cache import enable_compile_cache

    # Persistent XLA cache: a re-fired batch (tunnel died mid-run) pays
    # disk hits instead of the ~500s/config remote compile.
    cache_dir = enable_compile_cache()
    _log(f"compile cache: {cache_dir or 'disabled'}")

    platform = jax.devices()[0].platform
    _log(f"platform={platform} devices={jax.devices()}")
    os.makedirs(args.out, exist_ok=True)
    result: dict = {
        "platform": platform,
        "device": str(jax.devices()[0]),
        "groups": args.groups,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "captured_by": "builder (tools/tpu_batch.py)",
    }

    def flush() -> None:
        with open(os.path.join(args.out, "batch.json"), "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")

    # ---- (a) headline capture: six-lane deliver, bench.py config ----
    t0 = time.perf_counter()
    eng, props = _make_engine(args.groups, "lanes")
    compile_s = time.perf_counter() - t0
    _log(f"(a) six-lane G={args.groups} built+compiled in {compile_s:.0f}s")
    rate_six = _rate(eng, props)
    commits = eng.commits()
    assert commits.min() > 0
    _log(f"(a) six-lane rate: {rate_six:,.0f} group-rounds/s")
    result["a_six_lane"] = {
        "rate_group_rounds_per_s": round(rate_six, 1),
        "compile_s": round(compile_s, 1),
        "config": "G=%d R=3 W=32 layout=minor deliver=lanes"
                  % args.groups,
        "commits_min": int(commits.min()),
    }
    flush()

    # ---- (d) device-side commit p50 ----
    # rounds-to-commit: counted with single-round steps (each pays a
    # tunnel RTT but only the ROUND COUNT is used, never the wall time);
    # priced at the per-round wall time of the pipelined scan above.
    one = jnp.zeros((eng.cfg.num_instances,), jnp.int32)
    one = one.at[jnp.arange(args.groups) * eng.cfg.num_replicas].set(1)
    eng.run_rounds(1, tick=False, propose_n=one)  # warm 1-round program
    for _ in range(4):
        eng.run_rounds(1, tick=False)
    jax.block_until_ready(eng.state.commit)
    base = int(eng.commits()[:, 0].min())
    eng.run_rounds(1, tick=False, propose_n=one)
    rounds_to_commit = 1
    while int(eng.commits()[:, 0].min()) <= base and rounds_to_commit < 10:
        eng.run_rounds(1, tick=False)
        rounds_to_commit += 1
    timed_out = int(eng.commits()[:, 0].min()) <= base
    per_round_s = args.groups / rate_six  # seconds per round at steady state
    p50_us = rounds_to_commit * per_round_s * 1e6
    _log(f"(d) rounds_to_commit={rounds_to_commit}, per-round "
         f"{per_round_s*1e6:.1f}us -> device-side commit p50 "
         f"{p50_us:.1f}us timed_out={timed_out}")
    result["d_commit_p50"] = {
        "rounds_to_commit": rounds_to_commit,
        "timed_out": timed_out,
        "per_round_us": round(per_round_s * 1e6, 2),
        "commit_p50_us_device_side": round(p50_us, 2),
        "note": "round count from single-round stepping (count only); "
                "priced at steady-state per-round wall time, not tunnel "
                "RTT",
    }
    flush()

    # ---- (e) xprof trace (best effort) ----
    trace_dir = os.path.join(args.out, "xprof")
    try:
        with jax.profiler.trace(trace_dir):
            eng.run_rounds(16, tick=True, propose_n=props)
            jax.block_until_ready(eng.state.commit)
        has_files = any(files for _, _, files in os.walk(trace_dir))
        result["e_xprof"] = {"ok": has_files, "dir": trace_dir}
        _log(f"(e) xprof trace saved={has_files} -> {trace_dir}")
    except Exception as e:  # noqa: BLE001 — profiling is best-effort
        result["e_xprof"] = {"ok": False, "error": repr(e)}
        _log(f"(e) xprof failed: {e!r}")
    flush()
    del eng, props

    # ---- (c) Pallas kernels vs XLA forms ----
    try:
        from etcd_tpu.tools import pallas_bench

        import contextlib
        import io

        saved_argv = sys.argv
        buf = io.StringIO()
        try:
            sys.argv = ["pallas_bench"]
            with contextlib.redirect_stdout(buf):
                pallas_bench.main()
        finally:
            sys.argv = saved_argv
        result["c_pallas"] = {"ok": True, "report": buf.getvalue()}
        _log("(c) pallas_bench:\n" + buf.getvalue())
    except Exception as e:  # noqa: BLE001 — keep the batch going
        result["c_pallas"] = {"ok": False, "error": repr(e)}
        _log(f"(c) pallas_bench failed: {e!r}")
    flush()

    # ---- (b) deliver-shape A/B ON TPU (--deliver-shape picks the
    # comparison set; default covers merged + the ISSUE 14 vectorized
    # fold — the on-device tuning the r5 notes demanded, one command
    # when the tunnel is live) ----
    for shape in args.deliver_shapes:
        key = f"b_deliver_{shape}"
        try:
            t0 = time.perf_counter()
            eng2, props2 = _make_engine(args.groups, shape)
            compile2_s = time.perf_counter() - t0
            _log(f"(b) {shape} G={args.groups} built+compiled in "
                 f"{compile2_s:.0f}s")
            rate_shape = _rate(eng2, props2)
            assert eng2.commits().min() > 0
            _log(f"(b) {shape} rate: {rate_shape:,.0f} group-rounds/s "
                 f"({rate_shape / rate_six:.2f}x six-lane)")
            result[key] = {
                "rate_group_rounds_per_s": round(rate_shape, 1),
                "compile_s": round(compile2_s, 1),
                "vs_six_lane": round(rate_shape / rate_six, 3),
            }
            del eng2, props2
        except Exception as e:  # noqa: BLE001
            result[key] = {"ok": False, "error": repr(e)}
            _log(f"(b) {shape} deliver failed: {e!r}")
        flush()

    _log("batch complete")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
