"""BASELINE.md benchmark configs #2-#5: the multi-Raft shard sweep.

    python -m etcd_tpu.tools.bench_sweep [--configs 2,3,4] [--quick]

#2  1k-shard,  3 replicas — leader append path (steady proposals)
#3  10k-shard, 5 replicas — commit index + Progress tracker on device
#4  100k-shard, 3 replicas — randomized elections + vote-tally kernel
#5  1M-shard,  3 replicas — JointConfig membership (half the groups run
    a joint config, commit = min of both quorum halves) + a ReadIndex
    batch opened on every leader each measured block, confirmed via
    heartbeat-ack quorum on device

Each config prints one JSON line; config #1 (raftexample 3-node single
group) is covered by the raftexample suite + demo, not this sweep.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp


def _steady_rate(groups: int, replicas: int, rounds: int, calls: int,
                 lanes_minor: bool) -> dict:
    from etcd_tpu.batched import BatchedConfig, MultiRaftEngine

    cfg = BatchedConfig(
        num_groups=groups, num_replicas=replicas, window=32,
        max_ents_per_msg=4, max_props_per_round=2,
        election_timeout=1 << 20, heartbeat_timeout=4,
        auto_compact=True, lanes_minor=lanes_minor,
    )
    eng = MultiRaftEngine(cfg)
    eng.campaign([g * replicas for g in range(groups)])
    eng.run_rounds(4, tick=False)
    assert (eng.leaders() == 0).all()
    props = jnp.zeros((cfg.num_instances,), jnp.int32)
    props = props.at[jnp.arange(groups) * replicas].set(2)
    eng.run_rounds(rounds, tick=True, propose_n=props)
    jax.block_until_ready(eng.state.commit)
    t0 = time.perf_counter()
    for _ in range(calls):
        eng.run_rounds(rounds, tick=True, propose_n=props)
    jax.block_until_ready(eng.state.commit)
    dt = time.perf_counter() - t0
    assert eng.commits().min() > 0
    return {
        "groups": groups,
        "replicas": replicas,
        "group_rounds_per_sec": round(groups * rounds * calls / dt, 1),
    }


def _election_rate(groups: int, replicas: int, rounds: int, calls: int,
                   lanes_minor: bool) -> dict:
    """Config #4: randomized timer elections — every group keeps
    ticking with a short election timeout, continuously re-electing via
    the vote-tally kernel."""
    from etcd_tpu.batched import BatchedConfig, MultiRaftEngine

    cfg = BatchedConfig(
        num_groups=groups, num_replicas=replicas, window=16,
        max_ents_per_msg=2, max_props_per_round=1,
        election_timeout=4, heartbeat_timeout=1,
        auto_compact=True, lanes_minor=lanes_minor,
    )
    eng = MultiRaftEngine(cfg)
    eng.run_rounds(rounds, tick=True)  # warmup: natural elections fire
    jax.block_until_ready(eng.state.term)
    t0 = time.perf_counter()
    for _ in range(calls):
        eng.run_rounds(rounds, tick=True)
    jax.block_until_ready(eng.state.term)
    dt = time.perf_counter() - t0
    terms = eng.terms()
    assert int(terms.max()) > 0, "no elections fired"
    return {
        "groups": groups,
        "replicas": replicas,
        "group_rounds_per_sec": round(groups * rounds * calls / dt, 1),
        "max_term_reached": int(terms.max()),
        "leaders_now": int((eng.leaders() >= 0).sum()),
    }


def _joint_readindex_rate(groups: int, replicas: int, rounds: int,
                          calls: int, lanes_minor: bool) -> dict:
    """Config #5: steady appends with half the groups in a joint
    config (commit takes both quorum halves) and a ReadIndex batch
    opened on every leader per measured block."""
    import numpy as np

    from etcd_tpu.batched import BatchedConfig, MultiRaftEngine

    cfg = BatchedConfig(
        num_groups=groups, num_replicas=replicas, window=32,
        max_ents_per_msg=4, max_props_per_round=2,
        election_timeout=1 << 20, heartbeat_timeout=4,
        auto_compact=True, lanes_minor=lanes_minor,
    )
    eng = MultiRaftEngine(cfg)
    # Half the groups run joint {all} x {all-but-last} — a real two-
    # quorum commit rule (bulk mask upload, one device op).
    half = groups // 2
    st = eng.state
    vout = np.zeros((cfg.num_instances, replicas), bool)
    joint = np.zeros((cfg.num_instances,), bool)
    # Joint groups are exactly [0, half): two slice writes, no loop.
    vout[: half * replicas, : replicas - 1] = True
    joint[: half * replicas] = True
    eng.state = st._replace(
        voter_out=jnp.asarray(vout), in_joint=jnp.asarray(joint))

    eng.campaign([g * replicas for g in range(groups)])
    eng.run_rounds(4, tick=False)
    assert (eng.leaders() == 0).all()
    props = jnp.zeros((cfg.num_instances,), jnp.int32)
    props = props.at[jnp.arange(groups) * replicas].set(2)
    leader_rows = jnp.zeros((cfg.num_instances,), bool).at[
        jnp.arange(groups) * replicas].set(True)

    def block() -> None:
        # One ReadIndex batch per leader, then the steady rounds (the
        # acks confirm within them — read_only.go's heartbeat quorum).
        eng.step_round(read_req=leader_rows, propose_n=props)
        eng.run_rounds(rounds - 1, tick=True, propose_n=props)

    block()  # warmup/compile
    jax.block_until_ready(eng.state.commit)
    t0 = time.perf_counter()
    for _ in range(calls):
        block()
    jax.block_until_ready(eng.state.commit)
    dt = time.perf_counter() - t0
    seq, idx, ready = eng.read_states()
    lead_idx = [g * replicas for g in range(groups)]
    confirmed = int(sum(1 for i in lead_idx if ready[i]))
    assert eng.commits().min() > 0
    assert confirmed > 0, "no ReadIndex batch ever confirmed"
    return {
        "groups": groups,
        "replicas": replicas,
        "joint_groups": half,
        "group_rounds_per_sec": round(groups * rounds * calls / dt, 1),
        "read_batches_confirmed": confirmed,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="2,3,4,5")
    ap.add_argument("--quick", action="store_true",
                    help="small G (CI-sized run)")
    ap.add_argument("--lanes-minor", type=int, default=-1,
                    help="-1 auto (tpu: minor), 0 major, 1 minor")
    args = ap.parse_args()
    want = {int(c) for c in args.configs.split(",")}

    platform = jax.devices()[0].platform
    accelerated = platform in ("tpu", "axon")
    lm = accelerated if args.lanes_minor < 0 else bool(args.lanes_minor)
    q = args.quick or not accelerated

    runs = {
        2: ("append-path", lambda: _steady_rate(
            1024 if q else 1024, 3, 16, 4, lm)),
        3: ("commit+progress-R5", lambda: _steady_rate(
            2048 if q else 10240, 5, 16, 4, lm)),
        4: ("randomized-elections", lambda: _election_rate(
            4096 if q else 102400, 3, 16, 4, lm)),
        5: ("joint+readindex-scale", lambda: _joint_readindex_rate(
            16384 if q else 1048576, 3, 8, 2, lm)),
    }
    for c in sorted(want):
        name, fn = runs[c]
        res = fn()
        res.update({"config": c, "name": name, "platform": platform,
                    "layout": "minor" if lm else "major"})
        print(json.dumps(res))


if __name__ == "__main__":
    main()
