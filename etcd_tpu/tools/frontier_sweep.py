"""Latency/throughput frontier sweep over group count G.

Maps the (throughput, commit-latency) frontier of the batched engine:
for each G it measures steady-state group-rounds/s through the
double-buffered pipelined round loop (engine.run_rounds_pipelined —
chunk k+1 enqueued while chunk k's scan runs, donated state buffers)
AND the device commit p50 — wall-clock from a quiet-point proposal to
quorum commit across every group, the bench.py methodology. One sweep
answers the VERDICT r05 top-two items together: how much throughput
each latency point buys, and where the knee is.

Every engine build routes XLA compilation through the persistent
on-disk cache (batched/compile_cache.py, ETCD_TPU_COMPILE_CACHE), so
re-running the sweep — or re-measuring one point after a tunnel death —
pays disk hits instead of the ~500s/config remote compile that made
round-5 sweeps a one-shot affair. The sweep records per-point build
times and (by default) re-builds the first config in a fresh
subprocess at the end to log the measured warm-start compile time
against the cold one.

Before measuring, the pipelined loop is differentially gated against
single-round stepping (same program as the shadow-verified step_round
path) on a small config: commits/terms/leaders must match exactly, or
the sweep aborts. The full oracle check lives in
tests/batched/test_pipelined.py; this inline gate just refuses to
publish numbers from a loop that diverged.

Writes ``artifacts/frontier.json``:

    {"platform", "captured_at", "loop": "pipelined",
     "points": [{"groups", "rate_group_rounds_per_s", "commit_p50_ms",
                 "commit_p50_rounds", "build_s"}, ...],
     "warm_start": {"groups", "cold_build_s", "warm_build_s"}}

and prints a markdown table for BENCH_NOTES.md (``--append-notes``
appends it under a dated heading).

    python -m etcd_tpu.tools.frontier_sweep            # platform defaults
    python -m etcd_tpu.tools.frontier_sweep --groups 1024,4096,16384
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import subprocess
import sys
import time

# The TPU sweep of the north-star plan (ISSUE 1); 131072 probes past
# the headline G for the throughput knee. CPU defaults stay small
# enough that the whole sweep (builds included) fits a CI-scale box.
TPU_GROUPS = [1024, 4096, 16384, 65536, 131072]
CPU_GROUPS = [256, 512, 1024, 4096]


def _log(msg: str) -> None:
    print(f"[frontier {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def _make_engine(groups: int, shape: str, telemetry: bool = False):
    # The bench.py config and setup (BENCH_r05 methodology), from the
    # shared module so the sweep cannot desynchronize from bench.py.
    from .benchlib import make_bench_engine

    return make_bench_engine(groups, lanes_minor=True,
                             deliver_shape=shape,
                             telemetry=telemetry)


def _pipeline_gate(shape: str) -> None:
    """Refuse to measure a pipelined loop that diverges from
    single-round stepping (the shadow-verified path)."""
    import numpy as np

    a, props = _make_engine(64, shape)
    b, _ = _make_engine(64, shape)
    a.run_rounds_pipelined(48, chunk=8, tick=True, propose_n=props)
    for _ in range(48):
        b.step_round(tick=True, propose_n=props)
    for f in ("term", "role", "lead", "commit", "last"):
        # jitlint: waive(sync-in-loop) -- differential gate, not a hot path: one bulk gather per state field (5 total) to compare pipelined vs serial stepping
        av, bv = np.asarray(getattr(a.state, f)), np.asarray(
            getattr(b.state, f))
        assert (av == bv).all(), (
            f"pipelined loop diverged from single-round stepping on "
            f"{f}; refusing to record frontier numbers")
    _log(f"pipeline gate[{shape}]: pipelined == single-round "
         "stepping over 48 rounds at G=64")


def _measure_point(groups: int, shape: str, rounds_per_call: int,
                   calls: int, telemetry: bool = False) -> dict:
    from .benchlib import measure_commit_p50, measure_rate

    t0 = time.perf_counter()
    eng, props = _make_engine(groups, shape, telemetry)
    build_s = time.perf_counter() - t0
    _log(f"G={groups}: built+compiled in {build_s:.1f}s")

    # Throughput through the pipelined loop (bench.py's measurement,
    # shared via benchlib so the numbers stay comparable).
    rate = measure_rate(eng, props, rounds_per_call, calls,
                        pipelined=True)
    commits = eng.commits()
    assert commits.min() > 0
    _log(f"G={groups}: {rate:,.0f} group-rounds/s")

    p50_ms, rounds = measure_commit_p50(eng)
    _log(f"G={groups}: commit p50 {p50_ms:.2f}ms over {rounds} rounds")

    del eng, props
    gc.collect()
    return {
        "groups": groups,
        "deliver": shape,
        "rate_group_rounds_per_s": round(rate, 1),
        "commit_p50_ms": round(p50_ms, 2),
        "commit_p50_rounds": rounds,
        "build_s": round(build_s, 2),
    }


def _warm_probe(groups: int, shape: str) -> None:
    """Subprocess mode: build one engine and print its build time —
    a fresh process has no in-memory jit cache, so this measures the
    persistent-cache warm start."""
    t0 = time.perf_counter()
    _make_engine(groups, shape)
    print(json.dumps({"build_s": round(time.perf_counter() - t0, 2)}))


def _run_warm_probe(groups: int, shape: str) -> "float | None":
    cmd = [sys.executable, "-m", "etcd_tpu.tools.frontier_sweep",
           "--warm-probe", str(groups), "--deliver-shape", shape]
    try:
        out = subprocess.run(cmd, capture_output=True, timeout=1800,
                             check=True)
        return json.loads(out.stdout.decode().strip().splitlines()[-1])[
            "build_s"]
    except Exception as e:  # noqa: BLE001 — warm probe is best-effort
        _log(f"warm probe failed: {e!r}")
        return None


def _markdown(result: dict) -> str:
    lines = [
        "| G | deliver | group-rounds/s | commit p50 (ms) | rounds "
        "| build (s) |",
        "|---|---|---|---|---|---|",
    ]
    for p in result["points"]:
        lines.append(
            "| {groups} | {deliver} | {rate_group_rounds_per_s:,.0f} | "
            "{commit_p50_ms} | {commit_p50_rounds} | {build_s} |"
            .format(**p))
    ws = result.get("warm_start")
    if ws and ws.get("warm_build_s") is not None:
        lines.append(
            f"\nWarm start (persistent compile cache, fresh process, "
            f"G={ws['groups']}): {ws['warm_build_s']}s vs "
            f"{ws['cold_build_s']}s cold.")
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", default="",
                    help="comma-separated G list (default per platform)")
    ap.add_argument("--out", default="artifacts/frontier.json")
    ap.add_argument("--rounds-per-call", type=int, default=16)
    ap.add_argument("--calls", type=int, default=8)
    ap.add_argument("--deliver-shape", default="",
                    help="comma-separated deliver shapes to sweep "
                         "(lanes|merged|vectorized; default: the "
                         "platform default shape). Each point row "
                         "records its shape, so one sweep writes the "
                         "per-shape frontier (ISSUE 14).")
    ap.add_argument("--telemetry", action="store_true",
                    help="compile the kernel telemetry plane into the "
                         "measured round (overhead sweep; ISSUE 4)")
    ap.add_argument("--skip-gate", action="store_true")
    ap.add_argument("--skip-warm-check", action="store_true")
    ap.add_argument("--append-notes", default="",
                    help="append the markdown table to this file")
    ap.add_argument("--warm-probe", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    from etcd_tpu.batched.compile_cache import enable_compile_cache

    cache_dir = enable_compile_cache()

    if args.warm_probe:
        _warm_probe(args.warm_probe, args.deliver_shape or "auto")
        return

    _log(f"compile cache: {cache_dir or 'disabled'}")

    import jax

    platform = jax.devices()[0].platform
    accelerated = platform in ("tpu", "axon")
    from etcd_tpu.batched.state import DELIVER_SHAPES, \
        default_deliver_shape

    if args.deliver_shape:
        shapes = [s.strip() for s in args.deliver_shape.split(",")]
        for s in shapes:
            if s not in DELIVER_SHAPES:
                raise SystemExit(
                    f"unknown deliver shape {s!r} (choose from "
                    f"{DELIVER_SHAPES})")
    else:
        shapes = [default_deliver_shape()]
    if args.groups:
        group_list = [int(g) for g in args.groups.split(",")]
    else:
        group_list = TPU_GROUPS if accelerated else CPU_GROUPS
    _log(f"platform={platform} sweep G={group_list} "
         f"deliver={','.join(shapes)}")

    if not args.skip_gate:
        for s in shapes:
            _pipeline_gate(s)

    result: dict = {
        "platform": platform,
        "device": str(jax.devices()[0]),
        "loop": "pipelined (run_rounds_pipelined chunk=%d depth=2)"
                % args.rounds_per_call,
        "deliver": shapes,
        "telemetry": bool(args.telemetry),
        "compile_cache": cache_dir or "disabled",
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "captured_by": "tools/frontier_sweep.py",
        "points": [],
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)

    def flush() -> None:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")

    for g in group_list:
        for s in shapes:
            try:
                result["points"].append(
                    _measure_point(g, s, args.rounds_per_call,
                                   args.calls, args.telemetry))
            except Exception as e:  # noqa: BLE001 — partial frontier
                _log(f"G={g} {s} failed: {e!r}; frontier stays partial")
                result.setdefault("failed", []).append(
                    {"groups": g, "deliver": s, "error": repr(e)})
            flush()

    if not args.skip_warm_check and result["points"] and cache_dir:
        p0 = result["points"][0]
        warm = _run_warm_probe(p0["groups"], p0["deliver"])
        result["warm_start"] = {
            "groups": p0["groups"],
            "cold_build_s": p0["build_s"],
            "warm_build_s": warm,
        }
        flush()
        if warm is not None:
            _log(f"warm start: {warm}s vs {p0['build_s']}s cold")

    table = _markdown(result)
    print(table)
    if args.append_notes:
        with open(args.append_notes, "a") as f:
            f.write(
                f"\n### Frontier sweep ({platform}, "
                f"{time.strftime('%Y-%m-%d')}, tools/frontier_sweep.py)"
                f"\n\n{table}\n")


if __name__ == "__main__":
    main()
