"""1k-shard multi-raft KV service demo: 3 members hosting N groups on
the batched device engine, real payloads through WAL + apply
(BASELINE.md config #2 shape, served end-to-end rather than simulated).

    python -m etcd_tpu.tools.multiraft_demo [--groups 1024] [--puts 2000]

Prints a JSON summary: groups, elected leaders, puts applied, wall time,
puts/sec, and per-member WAL fsync stats.
"""

from __future__ import annotations

import argparse
import json
import tempfile
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--groups", type=int, default=1024)
    ap.add_argument("--puts", type=int, default=2000)
    ap.add_argument("--members", type=int, default=3)
    args = ap.parse_args()

    from etcd_tpu.batched.hosting import MultiRaftCluster
    from etcd_tpu.batched.state import BatchedConfig

    cfg = BatchedConfig(
        num_groups=args.groups,
        num_replicas=args.members,
        window=64,
        max_ents_per_msg=8,
        max_props_per_round=4,
        election_timeout=10,
        heartbeat_timeout=1,
        pre_vote=True,
        check_quorum=True,
        auto_compact=True,
    )
    with tempfile.TemporaryDirectory() as tmp:
        t0 = time.monotonic()
        c = MultiRaftCluster(tmp, num_members=args.members,
                             num_groups=args.groups, cfg=cfg)
        try:
            leads = c.wait_leaders(timeout=120.0)
            t_elect = time.monotonic() - t0

            t1 = time.monotonic()
            rng = np.random.default_rng(0)
            groups = rng.integers(0, args.groups, args.puts)
            for i, g in enumerate(groups):
                c.put(int(g), b"k%d" % i, b"v%d" % i, timeout=30.0)
            t_puts = time.monotonic() - t1

            stats = {
                m.id: dict(zip(("syncs", "sync_ns"), m.wal.sync_stats()))
                for m in c.members.values()
            }
            print(json.dumps({
                "groups": args.groups,
                "members": args.members,
                "leaders_elected": int((leads > 0).sum()),
                "election_wall_s": round(t_elect, 2),
                "puts": args.puts,
                "puts_wall_s": round(t_puts, 2),
                "puts_per_sec": round(args.puts / t_puts, 1),
                "wal_fsyncs": stats,
            }))
        finally:
            c.stop()


if __name__ == "__main__":
    main()
