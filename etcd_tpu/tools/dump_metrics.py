"""Metrics dumper (ref: tools/etcd-dump-metrics — spawn or scrape a
member and print its metric names/values sorted).

Three sources: an HTTP /metrics endpoint (--addr), a batched hosting
member's admin port (--admin, the line-JSON 'metrics' op serving the
same Prometheus text — kernel telemetry counters, invariant trips,
WAL fsync / round-phase histograms, router loss classes, and the
etcd_tpu_fleet_* observatory families when the member runs --fleet),
or the local registry (default: every metric this build registers).

``--watch N`` re-scrapes every N seconds and prints per-interval
deltas and rates for every series that moved — eyeball a live hosted
run without restarting the scrape loop by hand::

    python -m etcd_tpu.tools.dump_metrics --admin 127.0.0.1:8001 --watch 5
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
import time
import urllib.request
from typing import Callable, Dict, List, Optional


def _print_text(text: str, names_only: bool) -> int:
    for line in sorted(text.splitlines()):
        if line.startswith("#"):
            continue
        print(line.split(" ")[0] if names_only else line)
    return 0


def _fetch_url(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


def _fetch_admin(addr: str) -> str:
    """Scrape a hosting member's admin endpoint (hosting_proc
    AdminServer, op 'metrics')."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=10) as s:
        f = s.makefile("rwb")
        f.write(json.dumps({"op": "metrics"}).encode() + b"\n")
        f.flush()
        resp = json.loads(f.readline())
    if not resp.get("ok"):
        raise RuntimeError(f"admin metrics failed: {resp}")
    return resp["text"]


def parse_series(text: str) -> Dict[str, float]:
    """Prometheus exposition text -> {series-with-labels: value}."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out


def watch(fetch: Callable[[], str], interval: float,
          count: int = 0) -> int:
    """Periodic re-scrape: after the baseline snapshot, print every
    series whose value moved, with the interval delta and per-second
    rate. Runs until `count` intervals (0 = forever / Ctrl-C)."""
    prev = parse_series(fetch())
    t_prev = time.monotonic()
    print(f"baseline: {len(prev)} series; interval {interval:g}s",
          flush=True)
    i = 0
    while count == 0 or i < count:
        time.sleep(interval)
        try:
            cur = parse_series(fetch())
        except (OSError, RuntimeError, ConnectionError) as e:
            # Transient by design: this codebase's own flows kill -9
            # and restart members mid-run. Keep the baseline and keep
            # scraping — the whole point of --watch is not having to
            # restart the loop by hand.
            print(f"scrape failed (retrying next interval): {e}",
                  file=sys.stderr, flush=True)
            i += 1
            continue
        now = time.monotonic()
        dt = max(now - t_prev, 1e-9)
        stamp = time.strftime("%H:%M:%S")
        moved = []
        for name, v in sorted(cur.items()):
            d = v - prev.get(name, 0.0)
            if d == 0 and name in prev:
                continue
            moved.append((name, v, d))
        print(f"-- {stamp} (+{dt:.1f}s, {len(moved)} series moved)",
              flush=True)
        for name, v, d in moved:
            print(f"{name} {v:g}  Δ{d:+g}  ({d / dt:+.1f}/s)",
                  flush=True)
        prev, t_prev = cur, now
        i += 1
    return 0


def dump_url(url: str, names_only: bool = False) -> int:
    return _print_text(_fetch_url(url), names_only)


def dump_admin(addr: str, names_only: bool = False) -> int:
    try:
        text = _fetch_admin(addr)
    except RuntimeError as e:
        print(str(e), file=sys.stderr)
        return 1
    return _print_text(text, names_only)


def dump_local(names_only: bool = False) -> int:
    """Every metric this build registers (spawns nothing: importing the
    server modules registers the full set; the batched telemetry
    families register explicitly — they are otherwise lazy)."""
    import etcd_tpu.server.metrics  # noqa: F401
    import etcd_tpu.server.server  # noqa: F401
    import etcd_tpu.storage.metrics  # noqa: F401
    import etcd_tpu.storage.mvcc.metrics  # noqa: F401
    import etcd_tpu.transport.metrics  # noqa: F401
    from etcd_tpu.batched import telemetry as btel
    from etcd_tpu.obs import fleet as bfleet
    from etcd_tpu.pkg import metrics as pmet

    for name in btel.TM_NAMES:
        btel.counter_family(name)
    btel.invariant_family()
    btel.wal_fsync_histogram()
    btel.round_phase_histogram()
    btel.router_loss_counter()
    btel.fenced_groups_gauge()
    # Storage fault plane (ISSUE 15): fail-stop/disk-full/injection/
    # salvage families — the IO-error contract's observability face.
    # (member_limping rides the fleet anomaly counter below; the limp
    # signal gauge is etcd_tpu_fleet_fsync_ewma_ms.)
    btel.disk_fault_failstop_counter()
    btel.disk_full_gauge()
    btel.disk_fault_injected_counter()
    btel.disk_fault_salvage_counter()
    # Shm ring fabric families (ISSUE 16): per-lane ring occupancy,
    # high-water, frames/copied-bytes throughput and ring-full events
    # (record losses stay on etcd_tpu_router_loss_total).
    btel.shm_ring_depth_gauge()
    btel.shm_ring_high_water_gauge()
    btel.shm_frames_counter()
    btel.shm_copy_bytes_counter()
    btel.shm_ring_full_counter()
    # Device apply-plane families (ISSUE 19): KV slot occupancy,
    # lease/watch census, and the lease-hit vs ReadIndex-fallback
    # read split the read-mix SLO row reports.
    btel.apply_plane_slots_gauge()
    btel.apply_plane_leases_gauge()
    btel.apply_plane_overflow_gauge()
    btel.apply_plane_watch_events_counter()
    btel.apply_plane_reads_counter()
    # Fleet observatory families (ISSUE 10): histograms + censuses +
    # anomaly counters fed from the device SummaryFrame; --watch picks
    # their deltas up like any other series once a member moves them.
    bfleet.register_families()
    for line in pmet.DEFAULT.expose().splitlines():
        if line.startswith("#"):
            continue
        print(line.split(" ")[0] if names_only else line)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="etcd-dump-metrics")
    p.add_argument("--addr", default="",
                   help="scrape http://addr/metrics instead of local defaults")
    p.add_argument("--admin", default="",
                   help="scrape a batched hosting member's admin port "
                        "(host:port, hosting_proc 'metrics' op)")
    p.add_argument("--names-only", action="store_true")
    p.add_argument("--watch", type=float, default=0.0, metavar="N",
                   help="re-scrape every N seconds, printing deltas/"
                        "rates per interval for series that moved")
    p.add_argument("--count", type=int, default=0,
                   help="stop --watch after this many intervals "
                        "(0 = run until interrupted)")
    args = p.parse_args(argv)
    url = args.addr
    if url:
        if not url.startswith("http"):
            url = f"http://{url}"
        if not url.endswith("/metrics"):
            url += "/metrics"
    if args.watch > 0:
        if args.admin:
            return watch(lambda: _fetch_admin(args.admin), args.watch,
                         args.count)
        if url:
            return watch(lambda: _fetch_url(url), args.watch,
                         args.count)
        print("--watch needs --admin or --addr (the local registry "
              "has nothing moving)", file=sys.stderr)
        return 2
    if args.admin:
        return dump_admin(args.admin, args.names_only)
    if url:
        return dump_url(url, args.names_only)
    return dump_local(args.names_only)


if __name__ == "__main__":
    sys.exit(main())
