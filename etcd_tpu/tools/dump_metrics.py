"""Metrics dumper (ref: tools/etcd-dump-metrics — spawn or scrape a
member and print its metric names/values sorted).

Three sources: an HTTP /metrics endpoint (--addr), a batched hosting
member's admin port (--admin, the line-JSON 'metrics' op serving the
same Prometheus text — kernel telemetry counters, invariant trips,
WAL fsync / round-phase histograms, router loss classes), or the local
registry (default: every metric this build registers)."""

from __future__ import annotations

import argparse
import json
import socket
import sys
import urllib.request
from typing import List, Optional


def _print_text(text: str, names_only: bool) -> int:
    for line in sorted(text.splitlines()):
        if line.startswith("#"):
            continue
        print(line.split(" ")[0] if names_only else line)
    return 0


def dump_url(url: str, names_only: bool = False) -> int:
    with urllib.request.urlopen(url, timeout=10) as r:
        text = r.read().decode()
    return _print_text(text, names_only)


def dump_admin(addr: str, names_only: bool = False) -> int:
    """Scrape a hosting member's admin endpoint (hosting_proc
    AdminServer, op 'metrics')."""
    host, _, port = addr.rpartition(":")
    with socket.create_connection((host, int(port)), timeout=10) as s:
        f = s.makefile("rwb")
        f.write(json.dumps({"op": "metrics"}).encode() + b"\n")
        f.flush()
        resp = json.loads(f.readline())
    if not resp.get("ok"):
        print(f"admin metrics failed: {resp}", file=sys.stderr)
        return 1
    return _print_text(resp["text"], names_only)


def dump_local(names_only: bool = False) -> int:
    """Every metric this build registers (spawns nothing: importing the
    server modules registers the full set; the batched telemetry
    families register explicitly — they are otherwise lazy)."""
    import etcd_tpu.server.metrics  # noqa: F401
    import etcd_tpu.server.server  # noqa: F401
    import etcd_tpu.storage.metrics  # noqa: F401
    import etcd_tpu.storage.mvcc.metrics  # noqa: F401
    import etcd_tpu.transport.metrics  # noqa: F401
    from etcd_tpu.batched import telemetry as btel
    from etcd_tpu.pkg import metrics as pmet

    for name in btel.TM_NAMES:
        btel.counter_family(name)
    btel.invariant_family()
    btel.wal_fsync_histogram()
    btel.round_phase_histogram()
    btel.router_loss_counter()
    btel.fenced_groups_gauge()
    for line in pmet.DEFAULT.expose().splitlines():
        if line.startswith("#"):
            continue
        print(line.split(" ")[0] if names_only else line)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="etcd-dump-metrics")
    p.add_argument("--addr", default="",
                   help="scrape http://addr/metrics instead of local defaults")
    p.add_argument("--admin", default="",
                   help="scrape a batched hosting member's admin port "
                        "(host:port, hosting_proc 'metrics' op)")
    p.add_argument("--names-only", action="store_true")
    args = p.parse_args(argv)
    if args.admin:
        return dump_admin(args.admin, args.names_only)
    if args.addr:
        url = args.addr
        if not url.startswith("http"):
            url = f"http://{url}"
        if not url.endswith("/metrics"):
            url += "/metrics"
        return dump_url(url, args.names_only)
    return dump_local(args.names_only)


if __name__ == "__main__":
    sys.exit(main())
