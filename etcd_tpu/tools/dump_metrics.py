"""Metrics dumper (ref: tools/etcd-dump-metrics — spawn or scrape a
member and print its metric names/values sorted)."""

from __future__ import annotations

import argparse
import sys
import urllib.request
from typing import List, Optional


def dump_url(url: str, names_only: bool = False) -> int:
    with urllib.request.urlopen(url, timeout=10) as r:
        text = r.read().decode()
    for line in sorted(text.splitlines()):
        if line.startswith("#"):
            continue
        print(line.split(" ")[0] if names_only else line)
    return 0


def dump_local(names_only: bool = False) -> int:
    """Every metric this build registers (spawns nothing: importing the
    server modules registers the full set)."""
    import etcd_tpu.server.metrics  # noqa: F401
    import etcd_tpu.server.server  # noqa: F401
    import etcd_tpu.storage.metrics  # noqa: F401
    import etcd_tpu.storage.mvcc.metrics  # noqa: F401
    import etcd_tpu.transport.metrics  # noqa: F401
    from etcd_tpu.pkg import metrics as pmet

    for line in pmet.DEFAULT.expose().splitlines():
        if line.startswith("#"):
            continue
        print(line.split(" ")[0] if names_only else line)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="etcd-dump-metrics")
    p.add_argument("--addr", default="",
                   help="scrape http://addr/metrics instead of local defaults")
    p.add_argument("--names-only", action="store_true")
    args = p.parse_args(argv)
    if args.addr:
        url = args.addr
        if not url.startswith("http"):
            url = f"http://{url}"
        if not url.endswith("/metrics"):
            url += "/metrics"
        return dump_url(url, args.names_only)
    return dump_local(args.names_only)


if __name__ == "__main__":
    sys.exit(main())
