"""Operational tooling (ref: tools/ — benchmark, etcd-dump-db,
etcd-dump-logs, etcd-dump-metrics, local-tester)."""
