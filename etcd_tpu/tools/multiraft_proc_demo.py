"""Multi-raft hosting demo with members as real OS processes.

Spawns R MultiRaftMember worker processes (one per member, wired by
TCPRouter over real sockets — the reference's peers-as-processes shape,
ref: rafthttp/transport.go:97-132, Procfile), elects balanced leaders
across G groups, runs a hosted-path put benchmark, then kill -9s one
member and restarts it to demonstrate WAL replay + catch-up at the
hosting layer.

    python -m etcd_tpu.tools.multiraft_proc_demo \
        [--groups 1024] [--members 3] [--puts 500] [--no-kill]

Prints a JSON summary line.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

from ..batched.hosting_proc import ProcClient, wait_admin


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _spawn(mid, members, groups, raft_ports, admin_ports, data_dir, gen=0):
    peers = [
        f"--peer={pid}=127.0.0.1:{raft_ports[pid]}"
        for pid in range(1, members + 1) if pid != mid
    ]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    log = open(os.path.join(data_dir, f"worker-{mid}-gen{gen}.log"), "wb")
    return subprocess.Popen(
        [
            sys.executable, "-m", "etcd_tpu.batched.hosting_proc",
            "--id", str(mid), "--members", str(members),
            "--groups", str(groups), "--data-dir", data_dir,
            "--bind", f"127.0.0.1:{raft_ports[mid]}",
            "--admin", f"127.0.0.1:{admin_ports[mid]}",
            "--tick-interval", "0.02",
        ] + peers,
        env=env, stdout=log, stderr=subprocess.STDOUT,
    )


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--groups", type=int, default=1024)
    p.add_argument("--members", type=int, default=3)
    p.add_argument("--puts", type=int, default=500)
    p.add_argument("--value-size", type=int, default=64)
    p.add_argument("--data-dir", default="")
    p.add_argument("--no-kill", action="store_true",
                   help="skip the kill -9 / restart phase")
    a = p.parse_args()

    data_dir = a.data_dir or tempfile.mkdtemp(prefix="multiraft-proc-")
    R, G = a.members, a.groups
    raft_p = dict(zip(range(1, R + 1), _free_ports(R)))
    admin_p = dict(zip(range(1, R + 1), _free_ports(R)))
    procs, clients = {}, {}
    summary = {"groups": G, "members": R, "data_dir": data_dir}
    try:
        t0 = time.perf_counter()
        for mid in range(1, R + 1):
            procs[mid] = _spawn(mid, R, G, raft_p, admin_p, data_dir)
        for mid in range(1, R + 1):
            clients[mid] = wait_admin(("127.0.0.1", admin_p[mid]),
                                      timeout=300.0)
        summary["boot_s"] = round(time.perf_counter() - t0, 1)

        t0 = time.perf_counter()
        for mid, c in clients.items():
            c.call(op="campaign",
                   groups=[g for g in range(G) if g % R == mid - 1])
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            r = clients[1].call(op="leaders")
            if all(x > 0 for x in r["leads"]):
                break
            stuck = [g for g, x in enumerate(r["leads"]) if x == 0]
            clients[1].call(op="campaign", groups=stuck[:512])
            time.sleep(0.5)
        else:
            raise TimeoutError("leader election did not converge")
        summary["election_s"] = round(time.perf_counter() - t0, 1)

        bench = clients[1].call(op="bench", n=a.puts,
                                value_size=a.value_size)
        summary["hosted_puts_per_sec"] = bench.get("puts_per_sec")
        summary["commit_p50_ms"] = bench.get("p50_ms")
        summary["commit_p99_ms"] = bench.get("p99_ms")
        summary["bench_groups"] = bench.get("groups")

        if not a.no_kill:
            victim = R
            procs[victim].kill()
            procs[victim].wait(timeout=10)
            clients[victim].close()
            # Survivors still serve a group the victim led.
            g = next(g for g in range(G) if g % R == victim - 1)
            t0 = time.perf_counter()
            deadline = time.monotonic() + 120
            ok = False
            while time.monotonic() < deadline and not ok:
                for c in [clients[m] for m in clients if m != victim]:
                    r = c.put(g, b"after-kill", b"1")
                    if r.get("ok"):
                        ok = True
                        break
                time.sleep(0.1)
            summary["reelect_put_s"] = round(time.perf_counter() - t0, 1)

            procs[victim] = _spawn(victim, R, G, raft_p, admin_p,
                                   data_dir, gen=1)
            clients[victim] = wait_admin(
                ("127.0.0.1", admin_p[victim]), timeout=300.0)
            t0 = time.perf_counter()
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                if clients[victim].get(g, b"after-kill") == b"1":
                    break
                time.sleep(0.25)
            else:
                raise TimeoutError("restarted member did not catch up")
            summary["catchup_s"] = round(time.perf_counter() - t0, 1)
        print(json.dumps(summary))
    finally:
        for c in clients.values():
            try:
                c.call(op="stop")
            except Exception:  # noqa: BLE001
                pass
            c.close()
        for pr in procs.values():
            try:
                pr.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pr.kill()


if __name__ == "__main__":
    main()
