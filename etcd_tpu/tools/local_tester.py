"""Local cluster runner with optional fault loop (ref:
tools/local-tester — Procfile cluster + network/process faults for
manual soak testing).

`python -m etcd_tpu.tools.local_tester --members 3 --data-root /tmp/lc`
boots a real-process cluster, prints endpoints, and (with --faults)
randomly SIGSTOPs/SIGCONTs or SIGKILLs+restarts members until ^C.
"""

from __future__ import annotations

import argparse
import os
import random
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional


def _free_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class _Member:
    def __init__(self, name: str, data_dir: str, peer: int, client: int,
                 metrics: int, initial: str) -> None:
        self.name = name
        self.data_dir = data_dir
        self.peer, self.client, self.metrics = peer, client, metrics
        self.initial = initial
        self.proc: Optional[subprocess.Popen] = None

    def start(self) -> None:
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "etcd_tpu",
             "--name", self.name, "--data-dir", self.data_dir,
             "--listen-peer-urls", f"http://127.0.0.1:{self.peer}",
             "--listen-client-urls", f"http://127.0.0.1:{self.client}",
             "--listen-metrics-urls", f"http://127.0.0.1:{self.metrics}",
             "--initial-cluster", self.initial,
             "--heartbeat-interval", "50", "--election-timeout", "500"],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
        )


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="local-tester")
    p.add_argument("--members", type=int, default=3)
    p.add_argument("--data-root", default="/tmp/etcd_tpu-local")
    p.add_argument("--faults", action="store_true")
    p.add_argument("--fault-interval", type=float, default=10.0)
    p.add_argument("--rounds", type=int, default=0, help="0 = until ^C")
    args = p.parse_args(argv)

    ports = _free_ports(3 * args.members)
    names = [f"m{i}" for i in range(args.members)]
    initial = ",".join(
        f"{nm}=http://127.0.0.1:{ports[3 * i]}" for i, nm in enumerate(names)
    )
    members = [
        _Member(nm, os.path.join(args.data_root, nm), ports[3 * i],
                ports[3 * i + 1], ports[3 * i + 2], initial)
        for i, nm in enumerate(names)
    ]
    for m in members:
        m.start()
    print("client endpoints:",
          ",".join(f"127.0.0.1:{m.client}" for m in members), flush=True)
    print("metrics:",
          ",".join(f"127.0.0.1:{m.metrics}" for m in members), flush=True)

    rng = random.Random()
    rounds = 0
    try:
        while True:
            time.sleep(args.fault_interval if args.faults else 1.0)
            if not args.faults:
                continue
            m = rng.choice(members)
            fault = rng.choice(["pause", "kill"])
            if fault == "pause" and m.proc and m.proc.poll() is None:
                print(f"[fault] SIGSTOP {m.name}", flush=True)
                m.proc.send_signal(signal.SIGSTOP)
                time.sleep(rng.uniform(1, args.fault_interval))
                m.proc.send_signal(signal.SIGCONT)
                print(f"[fault] SIGCONT {m.name}", flush=True)
            elif fault == "kill":
                print(f"[fault] SIGKILL + restart {m.name}", flush=True)
                if m.proc and m.proc.poll() is None:
                    m.proc.kill()
                    m.proc.wait(timeout=15)
                m.start()
            rounds += 1
            if args.rounds and rounds >= args.rounds:
                break
    except KeyboardInterrupt:
        pass
    finally:
        for m in members:
            if m.proc and m.proc.poll() is None:
                m.proc.terminate()
        for m in members:
            if m.proc:
                try:
                    m.proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    m.proc.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
