"""Load generator (ref: tools/benchmark/cmd/{put,range,txn_put,
txn_mixed,stm,watch,watch_get,lease}.go — QPS + latency percentiles via
pkg/report).

`python -m etcd_tpu.tools.benchmark <cmd> --endpoints ... --total N
--clients C`; each worker owns a connection, results aggregate into one
report (report.go percentiles p50/p90/p95/p99/p99.9).
"""

from __future__ import annotations

import argparse
import random
import sys
import threading
import time
from typing import List, Optional, Tuple

from ..client.client import Client
from ..client.concurrency import STM
from ..pkg.report import Report
from ..server import api as sapi


def _parse_endpoints(s: str) -> List[Tuple[str, int]]:
    out = []
    for part in s.split(","):
        part = part.strip()
        if "://" in part:
            part = part.split("://", 1)[1]
        host, port = part.rsplit(":", 1)
        out.append((host, int(port)))
    return out


def _run_workers(args, work) -> Report:
    """Spawn args.clients workers; `work(client, rep, worker_idx, i)`
    runs for each of the worker's share of args.total operations."""
    rep = Report()
    eps = _parse_endpoints(args.endpoints)
    per = args.total // args.clients

    def worker(idx: int) -> None:
        c = Client(eps, request_timeout=args.timeout)
        try:
            for i in range(per):
                try:
                    rep.timed(work, c, idx, i)
                except Exception:  # noqa: BLE001 — recorded by timed
                    pass
        finally:
            c.close()

    threads = [
        threading.Thread(target=worker, args=(i,)) for i in range(args.clients)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rep.wall = time.monotonic() - t0  # type: ignore[attr-defined]
    return rep


def bench_put(args) -> Report:
    rng = random.Random(0)
    val = b"v" * args.val_size

    def work(c: Client, idx: int, i: int) -> None:
        if args.sequential_keys:
            key = f"{idx:03d}-{i:010d}"
        else:
            key = f"{rng.randrange(args.key_space_size):0{args.key_size}d}"
        c.put(key.encode()[: args.key_size].ljust(args.key_size, b"0"), val)

    return _run_workers(args, work)


def bench_range(args) -> Report:
    key = args.key.encode()
    end = args.end.encode() if args.end else None

    def work(c: Client, idx: int, i: int) -> None:
        c.get(key, end, serializable=not args.consistency_l)

    return _run_workers(args, work)


def bench_txn_put(args) -> Report:
    val = b"v" * args.val_size

    def work(c: Client, idx: int, i: int) -> None:
        ops = [
            sapi.RequestOp(request_put=sapi.PutRequest(
                key=f"{idx}-{i}-{j}".encode(), value=val,
            ))
            for j in range(args.txn_ops)
        ]
        c.txn(sapi.TxnRequest(success=ops))

    return _run_workers(args, work)


def bench_txn_mixed(args) -> Report:
    val = b"v" * args.val_size
    rng = random.Random(1)

    def work(c: Client, idx: int, i: int) -> None:
        key = f"{rng.randrange(args.key_space_size)}".encode()
        if rng.random() < args.read_ratio:
            c.txn(sapi.TxnRequest(success=[
                sapi.RequestOp(request_range=sapi.RangeRequest(key=key))
            ]))
        else:
            c.txn(sapi.TxnRequest(success=[
                sapi.RequestOp(request_put=sapi.PutRequest(key=key, value=val))
            ]))

    return _run_workers(args, work)


def bench_stm(args) -> Report:
    """Transactional read-modify-write loops (cmd/stm.go)."""
    def work(c: Client, idx: int, i: int) -> None:
        stm = STM(c)

        def apply(txn) -> None:
            k = f"stm-{i % args.key_space_size}".encode()
            cur = txn.get(k)
            txn.put(k, (cur or b"0")[:8] + b"+")

        stm.run(apply)

    return _run_workers(args, work)


def bench_watch(args) -> Report:
    """Watch event delivery throughput (cmd/watch.go: watchers on a
    keyspace, publishers hammering it; measures event latency)."""
    eps = _parse_endpoints(args.endpoints)
    rep = Report()
    watcher_client = Client(eps, request_timeout=args.timeout)
    handles = [
        watcher_client.watch(f"w{j % args.key_space_size}".encode())
        for j in range(args.watchers)
    ]
    stamps = {}
    done = threading.Event()

    def drain() -> None:
        got_n = 0
        while got_n < args.total and not done.wait(0):
            for h in handles:
                got = h.get(timeout=0.05)
                if got is None:
                    continue
                _, events = got
                for ev in events:
                    t0 = stamps.get(ev.kv.value)
                    if t0 is not None:
                        rep.results(time.monotonic() - t0)
                    got_n += 1
                    if got_n >= args.total:
                        return

    dt = threading.Thread(target=drain)
    dt.start()
    pub = Client(eps, request_timeout=args.timeout)
    for i in range(args.total):
        v = f"{i}".encode()
        stamps[v] = time.monotonic()
        pub.put(f"w{i % args.key_space_size}".encode(), v)
    dt.join(timeout=30)
    done.set()
    for h in handles:
        h.cancel()
    pub.close()
    watcher_client.close()
    return rep


def bench_lease_keepalive(args) -> Report:
    def work(c: Client, idx: int, i: int) -> None:
        if i == 0:
            resp = c.lease_grant(ttl=60)
            setattr(c, "_bench_lease", resp.id)
        c.lease_keep_alive_once(getattr(c, "_bench_lease"))

    return _run_workers(args, work)


def bench_mvcc_put(args) -> Report:
    """Raw storage-path put throughput: in-proc store, no server
    (cmd/mvcc_put.go benches the mvcc layer directly)."""
    import tempfile

    from ..storage import backend as bk
    from ..storage.mvcc.kvstore import KVStore

    rep = Report()
    with tempfile.TemporaryDirectory() as td:
        be = bk.open_backend(td + "/db")
        kv = KVStore(be)
        val = b"v" * args.val_size
        for i in range(args.total):
            t0 = time.monotonic()
            kv.put(f"{i % args.key_space_size}".encode(), val)
            rep.results(time.monotonic() - t0)
        be.close()
    return rep


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="benchmark")
    p.add_argument("--endpoints", default="127.0.0.1:2379")
    p.add_argument("--clients", type=int, default=10)
    p.add_argument("--total", type=int, default=1000)
    p.add_argument("--timeout", type=float, default=30.0)
    p.add_argument("--val-size", type=int, default=8)
    p.add_argument("--key-size", type=int, default=8)
    p.add_argument("--key-space-size", type=int, default=1000)
    sub = p.add_subparsers(dest="cmd")

    x = sub.add_parser("put")
    x.add_argument("--sequential-keys", action="store_true")
    x = sub.add_parser("range")
    x.add_argument("key")
    x.add_argument("end", nargs="?", default="")
    x.add_argument("--consistency-l", action="store_true")
    x = sub.add_parser("txn-put")
    x.add_argument("--txn-ops", type=int, default=4)
    x = sub.add_parser("txn-mixed")
    x.add_argument("--read-ratio", type=float, default=0.5)
    sub.add_parser("stm")
    x = sub.add_parser("watch")
    x.add_argument("--watchers", type=int, default=10)
    sub.add_parser("lease-keepalive")
    sub.add_parser("mvcc-put")

    args = p.parse_args(argv)
    fns = {
        "put": bench_put, "range": bench_range, "txn-put": bench_txn_put,
        "txn-mixed": bench_txn_mixed, "stm": bench_stm,
        "watch": bench_watch, "lease-keepalive": bench_lease_keepalive,
        "mvcc-put": bench_mvcc_put,
    }
    if args.cmd not in fns:
        p.print_help()
        return 2
    rep = fns[args.cmd](args)
    print(rep.render())
    return 0


if __name__ == "__main__":
    sys.exit(main())
