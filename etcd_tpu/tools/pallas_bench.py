"""Time the Pallas quorum/ring kernels against their XLA forms on the
current backend (run on TPU to decide the hot-path integration gate —
see pallas_kernels.py and BENCH_NOTES.md).

    python -m etcd_tpu.tools.pallas_bench [N] [R] [W]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def _time(fn, *args, calls=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(calls):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / calls


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 65536 * 3
    r = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    w = int(sys.argv[3]) if len(sys.argv) > 3 else 32

    from etcd_tpu.batched.kernels import (
        joint_committed,
        joint_vote_result,
        term_at,
    )
    from etcd_tpu.batched.pallas_kernels import (
        quorum_commit_vote,
        term_at_batch,
    )

    platform = jax.devices()[0].platform
    interpret = platform == "cpu"
    if interpret:
        # Interpret mode executes the kernel in Python per grid step —
        # CPU timings are meaningless; this is a smoke run only.
        n = min(n, 1024)
    calls = 2 if interpret else 20
    rng = np.random.RandomState(0)
    match = jnp.asarray(rng.randint(0, 50, size=(n, r)), jnp.int32)
    voter = jnp.asarray(rng.rand(n, r) < 0.9)
    vout = jnp.asarray(rng.rand(n, r) < 0.3)
    joint = jnp.asarray(rng.rand(n) < 0.2)
    votes = jnp.asarray(rng.randint(-1, 2, size=(n, r)), jnp.int32)
    log = jnp.asarray(rng.randint(1, 9, size=(n, w)), jnp.int32)
    snapi = jnp.asarray(rng.randint(0, 100, size=n), jnp.int32)
    snapt = jnp.asarray(rng.randint(1, 9, size=n), jnp.int32)
    last = snapi + jnp.asarray(rng.randint(0, w, size=n), jnp.int32)
    idx = snapi + jnp.asarray(rng.randint(-2, w + 2, size=n), jnp.int32)

    xla_quorum = jax.jit(jax.vmap(joint_committed))
    xla_vote = jax.jit(jax.vmap(joint_vote_result))
    xla_term = jax.jit(jax.vmap(term_at))

    tq = _time(lambda: quorum_commit_vote(
        match, voter, vout, joint, votes, interpret=interpret),
        calls=calls)
    tx = _time(lambda: (xla_quorum(match, voter, vout, joint),
                        xla_vote(votes, voter, vout, joint)),
        calls=calls)
    print(f"[{platform}] quorum+vote N={n} R={r}: "
          f"pallas={tq*1e3:.3f}ms xla={tx*1e3:.3f}ms", flush=True)

    tp = _time(lambda: term_at_batch(
        log, snapi, snapt, last, idx, interpret=interpret),
        calls=calls)
    tx = _time(lambda: xla_term(log, snapi, snapt, last, idx),
               calls=calls)
    print(f"[{platform}] term_at N={n} W={w}: "
          f"pallas={tp*1e3:.3f}ms xla={tx*1e3:.3f}ms", flush=True)


if __name__ == "__main__":
    main()
