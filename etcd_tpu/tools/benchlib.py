"""Shared bench methodology: the canonical engine config and the
commit-p50 measurement.

``bench.py`` (the headline number) and ``tools/frontier_sweep.py``
(the latency/throughput frontier) must stay directly comparable to
each other and to the committed BENCH_r05 captures — same R/W/E
config, same election setup, same proposal load, same quiet-point
commit-latency loop. Both import these helpers so a methodology tweak
lands in one place and cannot silently desynchronize the two tools'
numbers.
"""

from __future__ import annotations

import time
from typing import Tuple


def make_bench_engine(groups: int, lanes_minor: bool = True,
                      deliver_shape: str = "auto",
                      telemetry: bool = False,
                      fleet: bool = False):
    """Build the canonical bench engine (BENCH_r05 methodology: R=3,
    W=32, E=4, steady state with no timer elections, auto-compacting
    ring), elect every group's slot-0 replica, and return the engine
    plus the steady 2-entries-per-group-per-round proposal vector.

    ``deliver_shape`` is the ISSUE 14 A/B axis (lanes | merged |
    vectorized; "auto" = platform default) — every headline number
    names the concrete shape it ran (engine.cfg.deliver_shape after
    resolution).

    ``telemetry`` compiles the kernel telemetry plane in (ISSUE 4):
    the headline number stays telemetry-off; BENCH_TELEMETRY=1 /
    frontier --telemetry measure the overhead so it stays pinned in
    BENCH_NOTES. ``fleet`` likewise compiles the fleet-summary plane
    in (ISSUE 10; BENCH_FLEET=1 / tools/fleet_overhead.py)."""
    import jax.numpy as jnp

    from ..batched import BatchedConfig, MultiRaftEngine

    cfg = BatchedConfig(
        num_groups=groups,
        num_replicas=3,
        window=32,
        max_ents_per_msg=4,
        max_props_per_round=2,
        election_timeout=1 << 20,  # steady state: no timer elections
        heartbeat_timeout=4,
        auto_compact=True,  # sustained load: ring chases the applied mark
        lanes_minor=lanes_minor,
        deliver_shape=deliver_shape,
        telemetry=telemetry,
        fleet_summary=fleet,
    )
    eng = MultiRaftEngine(cfg)
    eng.campaign([g * cfg.num_replicas for g in range(groups)])
    eng.run_rounds(4, tick=False)
    assert (eng.leaders() == 0).all(), "election failed in bench setup"
    props = jnp.zeros((cfg.num_instances,), jnp.int32)
    props = props.at[jnp.arange(groups) * cfg.num_replicas].set(2)
    return eng, props


def measure_rate(eng, props, rounds_per_call: int, calls: int,
                 pipelined: bool = False) -> float:
    """Steady-state group-rounds/s. The warmup compiles the
    chunk-sized scan program (rounds is a static arg, so the serial
    warmup covers the pipelined timed loop too — same program); the
    timed region then drives either sequential ``run_rounds`` calls
    (the BENCH_r05 headline methodology) or one
    ``run_rounds_pipelined`` pass with chunk == rounds_per_call."""
    import jax

    eng.run_rounds(rounds_per_call, tick=True, propose_n=props)  # warmup
    jax.block_until_ready(eng.state.commit)
    t0 = time.perf_counter()
    if pipelined:
        eng.run_rounds_pipelined(
            rounds_per_call * calls, chunk=rounds_per_call,
            tick=True, propose_n=props)
    else:
        for _ in range(calls):
            eng.run_rounds(rounds_per_call, tick=True, propose_n=props)
    jax.block_until_ready(eng.state.commit)
    dt = time.perf_counter() - t0
    return eng.cfg.num_groups * rounds_per_call * calls / dt


def measure_commit_p50(eng, max_rounds: int = 10) -> Tuple[float, int]:
    """Device commit p50: propose one entry per group at a quiet point,
    then step single rounds until every group's commit covers it — the
    wall-clock from propose to quorum-commit. All groups move in
    lockstep, so p50 == the common latency. Returns (ms, rounds)."""
    import jax
    import jax.numpy as jnp

    groups = eng.cfg.num_groups
    one = jnp.zeros((eng.cfg.num_instances,), jnp.int32)
    one = one.at[jnp.arange(groups) * eng.cfg.num_replicas].set(1)
    # Warm the single-round program (rounds is a static arg) and drain
    # the in-flight pipeline so the measurement starts quiesced.
    eng.run_rounds(1, tick=False, propose_n=one)
    for _ in range(4):
        eng.run_rounds(1, tick=False)
    jax.block_until_ready(eng.state.commit)
    base = eng.commits()[:, 0].min()
    t0 = time.perf_counter()
    eng.run_rounds(1, tick=False, propose_n=one)
    jax.block_until_ready(eng.state.commit)
    rounds = 1
    while eng.commits()[:, 0].min() <= base and rounds < max_rounds:
        eng.run_rounds(1, tick=False)
        # jitlint: waive(sync-in-loop) -- the sync IS the measurement: commit p50 is wall-clock from propose to observed quorum commit, one fence per round by definition
        jax.block_until_ready(eng.state.commit)
        rounds += 1
    return (time.perf_counter() - t0) * 1000, rounds
