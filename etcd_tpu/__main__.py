"""`python -m etcd_tpu` → etcdmain (ref: server/main.go)."""

import sys

from .etcdmain import main

if __name__ == "__main__":
    sys.exit(main())
