"""Client-side leasing: a write-through cache over keys "owned" via
leasing markers (ref: client/v3/leasing/{kv,cache,txn}.go).

Protocol (all LeasingKV clients cooperate through marker keys under a
shared prefix; a plain Client bypassing the protocol must not touch the
leased keys, same caveat as the reference):

* **acquire on read** — a txn atomically creates ``pfx+key`` bound to
  the session lease and reads the key; once owned, gets serve from the
  local cache with no server round-trip (kv.go Get fast path);
* **write-through** — the owner updates via a txn guarded on its marker
  still existing, then updates the cache (txn.go applyf);
* **revocation** — a non-owner writer stamps the marker with "REVOKE";
  every owner watches its markers and deletes them (dropping cache) on
  revoke, unblocking the writer (kv.go revoke/waitSession);
* session death releases all markers via lease expiry.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..server import api as sapi
from ..storage.mvcc.kv import EventType
from .client import Client
from .util import prefix_end
from .concurrency import Session

REVOKE = b"REVOKE"


class LeasingKV:
    def __init__(self, client: Client, prefix: str,
                 session_ttl: int = 10) -> None:
        self.c = client
        self.pfx = prefix.encode() if isinstance(prefix, str) else prefix
        self.session = Session(client, ttl=session_ttl)
        self._lock = threading.Lock()
        self._cache: Dict[bytes, Optional[sapi.KeyValue]] = {}
        self._owned: Dict[bytes, int] = {}  # key -> marker create_rev
        # key -> header captured at acquisition; cache hits serve it so
        # header.revision never regresses to 0 (ref: leasing/kv.go Get).
        self._hdr: Dict[bytes, sapi.ResponseHeader] = {}
        self._acquiring: set = set()  # keys mid-acquisition
        self._revoked_early: set = set()  # REVOKE seen while acquiring
        self.cache_hits = 0
        self._closed = False
        self._watch = client.watch(self.pfx, prefix_end(self.pfx))
        self._watcher = threading.Thread(target=self._watch_loop, daemon=True)
        self._watcher.start()

    def close(self) -> None:
        self._closed = True
        self._watch.cancel()
        self._watcher.join(timeout=5)
        # Release markers so other clients acquire immediately.
        with self._lock:
            owned = list(self._owned)
            self._owned.clear()
            self._cache.clear()
            self._hdr.clear()
        for key in owned:
            try:
                self.c.delete(self.pfx + key)
            except Exception:  # noqa: BLE001 — lease expiry reclaims
                pass
        self.session.close()

    # -- read path -------------------------------------------------------------

    def get(self, key: bytes) -> sapi.RangeResponse:
        with self._lock:
            if key in self._owned:
                self.cache_hits += 1
                kv = self._cache.get(key)
                return sapi.RangeResponse(
                    header=self._hdr.get(key, sapi.ResponseHeader()),
                    kvs=[kv] if kv is not None else [],
                    count=1 if kv is not None else 0,
                )
        marker = self.pfx + key
        # Atomically acquire the marker + read the key (kv.go Get txn).
        txn = sapi.TxnRequest(
            compare=[sapi.Compare(
                target=sapi.CompareTarget.CREATE,
                result=sapi.CompareResult.EQUAL,
                key=marker, create_revision=0,
            )],
            success=[
                sapi.RequestOp(request_put=sapi.PutRequest(
                    key=marker, value=b"", lease=self.session.lease_id,
                )),
                sapi.RequestOp(request_range=sapi.RangeRequest(key=key)),
            ],
            failure=[
                sapi.RequestOp(request_range=sapi.RangeRequest(key=key)),
            ],
        )
        with self._lock:
            self._acquiring.add(key)
            self._revoked_early.discard(key)
        try:
            resp = self.c.txn(txn)
            if resp.succeeded:
                rr = resp.responses[1].response_range
                with self._lock:
                    poisoned = key in self._revoked_early
                    if not poisoned:
                        self._owned[key] = resp.header.revision
                        self._cache[key] = rr.kvs[0] if rr.kvs else None
                        self._hdr[key] = resp.header
                if poisoned:
                    # A REVOKE raced our acquisition: release right away
                    # so the waiting writer proceeds.
                    try:
                        self.c.delete(marker)
                    except Exception:  # noqa: BLE001 — lease reclaims
                        pass
            else:
                rr = resp.responses[0].response_range
            return rr
        finally:
            with self._lock:
                self._acquiring.discard(key)
                self._revoked_early.discard(key)

    # -- write path ------------------------------------------------------------

    def put(self, key: bytes, value: bytes,
            timeout: float = 10.0) -> sapi.PutResponse:
        marker = self.pfx + key
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                owned_rev = self._owned.get(key)
            if owned_rev is not None:
                # Owner write-through, guarded on OUR marker (created at
                # the acquisition revision) still existing — "any marker
                # exists" would pass after another client re-acquired.
                txn = sapi.TxnRequest(
                    compare=[sapi.Compare(
                        target=sapi.CompareTarget.CREATE,
                        result=sapi.CompareResult.EQUAL,
                        key=marker, create_revision=owned_rev,
                    )],
                    success=[sapi.RequestOp(
                        request_put=sapi.PutRequest(key=key, value=value)
                    )],
                )
                resp = self.c.txn(txn)
                if resp.succeeded:
                    pr = resp.responses[0].response_put
                    with self._lock:
                        if key in self._owned:
                            self._hdr[key] = resp.header
                            prev = self._cache.get(key)
                            rev = resp.header.revision
                            self._cache[key] = sapi.KeyValue(
                                key=key, value=value,
                                mod_revision=rev,
                                create_revision=(
                                    prev.create_revision if prev else rev
                                ),
                                version=(prev.version + 1 if prev else 1),
                                lease=prev.lease if prev else 0,
                            )
                    return pr
                with self._lock:  # lost ownership mid-flight
                    self._owned.pop(key, None)
                    self._cache.pop(key, None)
                    self._hdr.pop(key, None)
                continue
            # Non-owner: write directly if unleased, else request revoke.
            txn = sapi.TxnRequest(
                compare=[sapi.Compare(
                    target=sapi.CompareTarget.CREATE,
                    result=sapi.CompareResult.EQUAL,
                    key=marker, create_revision=0,
                )],
                success=[sapi.RequestOp(
                    request_put=sapi.PutRequest(key=key, value=value)
                )],
                failure=[sapi.RequestOp(
                    # ignore_lease keeps the marker bound to the OWNER's
                    # session lease: if the owner died, the marker still
                    # expires with that lease instead of living forever
                    # (ref: client/v3/leasing/kv.go:410 WithIgnoreLease).
                    request_put=sapi.PutRequest(
                        key=marker, value=REVOKE, ignore_lease=True
                    )
                )],
            )
            resp = self.c.txn(txn)
            if resp.succeeded:
                return resp.responses[0].response_put
            # Wait for the owner to release, then retry.
            self._wait_marker_gone(marker, deadline)
        raise TimeoutError(f"leasing put {key!r} timed out")

    def _wait_marker_gone(self, marker: bytes, deadline: float) -> None:
        while time.monotonic() < deadline:
            r = self.c.get(marker)
            if r.count == 0:
                return
            time.sleep(0.05)

    # -- revocation watcher ----------------------------------------------------

    def _watch_loop(self) -> None:
        while not self._closed:
            got = self._watch.get(timeout=0.2)
            if got is None:
                continue
            _, events = got
            for ev in events:
                key = ev.kv.key[len(self.pfx):]
                if ev.type == EventType.PUT and ev.kv.value == REVOKE:
                    with self._lock:
                        if key in self._acquiring:
                            # Acquisition in flight: poison it so the
                            # winner releases immediately.
                            self._revoked_early.add(key)
                        mine = key in self._owned
                        if mine:
                            self._owned.pop(key, None)
                            self._cache.pop(key, None)
                            self._hdr.pop(key, None)
                    if mine:
                        try:
                            self.c.delete(self.pfx + key)
                        except Exception:  # noqa: BLE001
                            pass
                elif ev.type == EventType.DELETE:
                    # Marker gone (owner released or lease expired).
                    with self._lock:
                        self._owned.pop(key, None)
                        self._cache.pop(key, None)
                        self._hdr.pop(key, None)


