"""Client core (ref: client/v3/client.go, kv.go, watch.go, lease.go,
retry_interceptor.go).

One live connection at a time over the endpoint list; a reader thread
routes unary responses by id and watch pushes by stream id. Connection
loss → next endpoint (round-robin, client.go's balancer), watches
resume from last-seen revision + 1, in-flight unary calls fail over
transparently when safe (idempotent or connection-refused-before-send).
"""

from __future__ import annotations

import socket
import ssl
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..server import api as sapi
from ..pkg import rpctypes
from ..storage.mvcc.kv import Event
from ..v3rpc import wire

RETRYABLE = {"ConnectionError"}  # transport-level, always safe to retry
# Server-side errors that mean "try another endpoint" for any method
# (ref: retry_interceptor.go retryPolicy + isSafeRetry).
FAILOVER_ETYPES = {"NotLeaderError", "StoppedError"}
IDEMPOTENT = {
    "Range",
    "Status",
    "MemberList",
    "HashKV",
    "LeaseTimeToLive",
    "LeaseLeases",
    "AuthStatus",
    "UserGet",
    "UserList",
    "RoleGet",
    "RoleList",
    "WatchCreate",
    "LeaseKeepAlive",
    # Lock/Campaign claims are keyed by (name, lease): retrying re-enters
    # the same server-side wait on the same ownership key, so a retry
    # after a dropped connection continues the claim instead of
    # duplicating it (ref: v3lock.go Lock — key is <name>/<lease-hex>).
    "Lock",
    "Campaign",
}


class ClientError(Exception):
    def __init__(self, etype: str, msg: str = "",
                 code: Optional[str] = None,
                 grpc_code: Optional[int] = None) -> None:
        super().__init__(f"{etype}: {msg}")
        self.etype = etype
        self.msg = msg
        # Canonical error identity (ref: api/v3rpc/rpctypes/error.go):
        # retry/failover decisions key off these, not the class name.
        self.code = code
        self.grpc_code = grpc_code

    def as_typed(self) -> Optional[Exception]:
        """The canonical server-side exception, when this error carries
        a table code (for callers that match on exception types)."""
        if self.code is None:
            return None
        return rpctypes.exception_for(self.code, self.msg)


class ConnClosed(Exception):
    pass


@dataclass
class _Pending:
    ev: threading.Event = field(default_factory=threading.Event)
    result: Any = None
    error: Optional[Dict] = None
    sent: bool = False
    method: str = ""  # diagnostics: names the call in conn-loss errors


class WatchHandle:
    """One logical watch; survives reconnects
    (ref: client/v3/watch.go watchGrpcStream resume)."""

    def __init__(self, client: "Client", key: bytes, range_end: Optional[bytes],
                 start_rev: int) -> None:
        self.c = client
        self.key = key
        self.range_end = range_end
        self.next_rev = start_rev
        self.watch_id: Optional[int] = None
        self.canceled = False
        self._q: List[Tuple[int, List[Event]]] = []
        self._cv = threading.Condition()

    def _push(self, revision: int, events: List[Event]) -> None:
        with self._cv:
            self._q.append((revision, events))
            self.next_rev = max(self.next_rev, revision + 1)
            self._cv.notify_all()

    def get(self, timeout: Optional[float] = None) -> Optional[Tuple[int, List[Event]]]:
        """Next (revision, events) batch; None on timeout."""
        with self._cv:
            if not self._q:
                self._cv.wait(timeout)
            return self._q.pop(0) if self._q else None

    def events(self, timeout: float = 5.0):
        """Generator of events until cancel()."""
        while not self.canceled:
            batch = self.get(timeout=timeout)
            if batch is None:
                return
            for ev in batch[1]:
                yield ev

    def cancel(self) -> None:
        self.canceled = True
        if self.watch_id is not None:
            try:
                self.c._request("WatchCancel", {"watch_id": self.watch_id})
            except Exception:  # noqa: BLE001
                pass
        with self.c._lock:
            self.c._watches.pop(self.watch_id, None)
        with self._cv:
            self._cv.notify_all()


class ObserveHandle:
    """One election Observe stream: leader kvs pushed by the server
    (ref: v3election.go:76-91 Observe)."""

    def __init__(self, client: "Client", observe_id: int) -> None:
        self.c = client
        self.observe_id = observe_id
        self.canceled = False
        self._q: List = []
        self._cv = threading.Condition()

    def _push(self, kv) -> None:
        with self._cv:
            self._q.append(kv)
            self._cv.notify_all()

    def get(self, timeout: Optional[float] = None):
        """Next leader kv; None on timeout."""
        with self._cv:
            if not self._q:
                self._cv.wait(timeout)
            return self._q.pop(0) if self._q else None

    def cancel(self) -> None:
        self.canceled = True
        try:
            self.c._request("ObserveCancel", {"observe_id": self.observe_id})
        except Exception:  # noqa: BLE001
            pass
        with self.c._lock:
            self.c._observes.pop(self.observe_id, None)
            # In-flight frames pushed before the cancel was processed
            # would otherwise park in the early buffer forever.
            self.c._observe_early.pop(self.observe_id, None)
        with self._cv:
            self._cv.notify_all()


class Client:
    def __init__(
        self,
        endpoints: List[Tuple[str, int]],
        username: str = "",
        password: str = "",
        dial_timeout: float = 2.0,
        request_timeout: float = 10.0,
        tls_info=None,
    ) -> None:
        self.endpoints = list(endpoints)
        self._ep_index = 0
        self.username = username
        self.password = password
        self.token: Optional[str] = None
        self.dial_timeout = dial_timeout
        self.request_timeout = request_timeout
        # Client-channel TLS (ref: clientv3 TLS config via
        # client/pkg/transport ClientConfig).
        self._ssl = None
        self._tls_server_name = ""
        if tls_info is not None:
            # A CA-only TLSInfo is valid for a client (server cert
            # verification without mutual TLS).
            self._ssl = tls_info.client_context()
            self._tls_server_name = tls_info.server_name

        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._wlock = threading.Lock()
        self._next_id = 1
        self._pending: Dict[int, _Pending] = {}
        self._watches: Dict[int, WatchHandle] = {}
        self._observes: Dict[int, "ObserveHandle"] = {}
        # Observe frames that raced ahead of the Observe response (the
        # server pump can push before the observe_id reaches us); keyed
        # by ostream id, drained when observe() registers the handle.
        self._observe_early: Dict[int, list] = {}
        self._closed = False
        self._reconnect_gen = 0

        self._connect_any()

    # -- connection management -------------------------------------------------

    def _connect_any(self) -> None:
        last_err: Optional[Exception] = None
        for _ in range(len(self.endpoints)):
            ep = self.endpoints[self._ep_index % len(self.endpoints)]
            self._ep_index += 1
            try:
                self._connect(ep)
                return
            except OSError as e:
                last_err = e
        raise ClientError("ConnectionError", f"no endpoint reachable: {last_err}")

    def _connect(self, ep: Tuple[str, int]) -> None:
        sock = socket.create_connection(ep, timeout=self.dial_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._ssl is not None:
            sock = self._ssl.wrap_socket(
                sock, server_hostname=self._tls_server_name or ep[0])
        sock.settimeout(None)
        with self._lock:
            self._sock = sock
            self._reconnect_gen += 1
            gen = self._reconnect_gen
            # Observe ids restart per connection; buffered frames from
            # the previous connection must not seed the new one's ids
            # (the dying read loop skips its own clear when it exits on
            # a generation mismatch).
            self._observe_early.clear()
        threading.Thread(
            target=self._read_loop, args=(sock, gen), daemon=True
        ).start()
        if self.username and self.token is None:
            self._authenticate_locked()
        self._resume_watches()

    def _authenticate_locked(self) -> None:
        self.token = None
        resp = self._request(
            "Authenticate",
            {"name": self.username, "password": self.password},
            _no_reauth=True,
        )
        self.token = resp["token"]

    def authenticate(self, username: str, password: str) -> None:
        self.username, self.password = username, password
        self._authenticate_locked()

    def _resume_watches(self) -> None:
        with self._lock:
            handles = list(self._watches.values())
            self._watches.clear()
        for h in handles:
            if h.canceled:
                continue
            try:
                self._establish_watch(h)
            except Exception:  # noqa: BLE001 — retried on next reconnect
                pass

    def _read_loop(self, sock: socket.socket, gen: int) -> None:
        while True:
            frame = wire.read_frame(sock)
            if frame is None:
                break
            if "stream" in frame:
                with self._lock:
                    h = self._watches.get(frame["stream"])
                if h is not None:
                    ev = frame["event"]
                    h._push(
                        ev["revision"],
                        [wire.dec_event(d) for d in ev.get("events", [])],
                    )
                continue
            if "ostream" in frame:
                with self._lock:
                    oh = self._observes.get(frame["ostream"])
                    # Buffer only for the live connection: observe ids
                    # restart per connection, and a dead loop draining
                    # its socket tail must not seed the next
                    # connection's ids with stale leader kvs.
                    if oh is None and self._reconnect_gen == gen:
                        buf = self._observe_early.setdefault(
                            frame["ostream"], [])
                        if len(buf) < 64:
                            buf.append(frame["kv"])
                if oh is not None:
                    oh._push(wire.dec_kv(frame["kv"]))
                continue
            rid = frame.get("id")
            with self._lock:
                p = self._pending.pop(rid, None)
            if p is not None:
                p.result = frame.get("result")
                p.error = frame.get("error")
                p.ev.set()
        # Connection died: fail pending, mark socket gone.
        with self._lock:
            if self._reconnect_gen != gen:
                return
            self._sock = None
            pend = list(self._pending.values())
            self._pending.clear()
            self._observe_early.clear()  # ids are per-connection
        self._fail_pendings(pend)

    @staticmethod
    def _fail_pendings(pend: List["_Pending"]) -> None:
        for p in pend:
            p.error = {"type": "ConnectionError",
                       "msg": f"connection lost ({p.method or 'call'} in flight)"}
            p.ev.set()

    # -- unary calls -----------------------------------------------------------

    def _request(
        self,
        method: str,
        params: Dict[str, Any],
        timeout: Optional[float] = None,
        _no_reauth: bool = False,
        token: Optional[str] = None,
    ) -> Any:
        """`token` overrides the client's own auth token for this call
        (used by grpcproxy to forward the downstream caller's token)."""
        timeout = timeout or self.request_timeout
        attempts = max(2 * len(self.endpoints), 2)
        last: Optional[ClientError] = None
        for _ in range(attempts):
            if self._closed:
                raise ClientError("Closed", "client closed")
            try:
                return self._request_once(method, params, timeout, token=token)
            except ClientError as e:
                last = e
                invalid_token = (
                    e.code == "ErrInvalidAuthToken"
                    or e.etype == "InvalidAuthTokenError"
                )
                if invalid_token and not _no_reauth and self.username:
                    self._authenticate_locked()
                    continue
                retryable = e.etype in RETRYABLE and (
                    method in IDEMPOTENT or not getattr(e, "sent", True)
                )
                # Failover on codes when the server sends them (gRPC
                # Unavailable class, ref: retry_interceptor.go retrying
                # on codes.Unavailable); class names only as the legacy
                # fallback for code-less peers.
                if e.code is not None or e.grpc_code is not None:
                    failover = (
                        e.grpc_code == int(rpctypes.Code.Unavailable)
                        or e.code in rpctypes.FAILOVER_SYMBOLS
                        or e.etype in FAILOVER_ETYPES
                    )
                else:
                    failover = e.etype in FAILOVER_ETYPES
                if not (retryable or failover):
                    raise
                try:
                    if self._sock is None:
                        self._connect_any()
                    elif failover:
                        self._rotate_endpoint()
                except ClientError as ce:
                    last = ce
                time.sleep(0.05)
        raise last  # type: ignore[misc]

    def _rotate_endpoint(self) -> None:
        with self._lock:
            sock = self._sock
            self._sock = None
            # Requests in flight on the dying connection would otherwise
            # hang until their own deadline: the old read loop skips its
            # pending-failure pass once _reconnect_gen moves on (it can't
            # tell which pendings were re-issued on the new conn). Fail
            # them here so waiters see the break immediately and the
            # retry loop re-sends the retry-safe ones.
            pend = list(self._pending.values())
            self._pending.clear()
        self._fail_pendings(pend)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._connect_any()

    def _request_once(self, method: str, params: Dict, timeout: float,
                      token: Optional[str] = None) -> Any:
        with self._lock:
            sock = self._sock
            rid = self._next_id
            self._next_id += 1
            p = _Pending(method=method)
            self._pending[rid] = p
        if sock is None:
            with self._lock:
                self._pending.pop(rid, None)
            err = ClientError("ConnectionError", "not connected")
            err.sent = False
            raise err
        msg = {"id": rid, "method": method, "params": params}
        tok = token if token is not None else self.token
        if tok is not None:
            msg["token"] = tok
        try:
            with self._wlock:
                wire.write_frame(sock, msg)
        except OSError:
            with self._lock:
                self._pending.pop(rid, None)
            err = ClientError("ConnectionError", "send failed")
            err.sent = False
            raise err
        if not p.ev.wait(timeout=timeout):
            with self._lock:
                self._pending.pop(rid, None)
            raise ClientError("Timeout", f"{method} timed out")
        if p.error is not None:
            e = ClientError(
                p.error["type"], p.error.get("msg", ""),
                code=p.error.get("code"),
                grpc_code=p.error.get("grpcCode"),
            )
            e.sent = True
            raise e
        return p.result

    # -- KV API (client/v3/kv.go) ----------------------------------------------

    def put(
        self,
        key: bytes,
        value: bytes,
        lease: int = 0,
        prev_kv: bool = False,
    ) -> sapi.PutResponse:
        req = sapi.PutRequest(key=key, value=value, lease=lease, prev_kv=prev_kv)
        return wire.dec_response("Put", self._request("Put", wire.enc(req)))

    def get(
        self,
        key: bytes,
        range_end: Optional[bytes] = None,
        revision: int = 0,
        limit: int = 0,
        serializable: bool = False,
        count_only: bool = False,
        keys_only: bool = False,
        sort_order: sapi.SortOrder = sapi.SortOrder.NONE,
        sort_target: sapi.SortTarget = sapi.SortTarget.KEY,
    ) -> sapi.RangeResponse:
        req = sapi.RangeRequest(
            key=key,
            range_end=range_end or b"",
            revision=revision,
            limit=limit,
            serializable=serializable,
            count_only=count_only,
            keys_only=keys_only,
            sort_order=sort_order,
            sort_target=sort_target,
        )
        return wire.dec_response("Range", self._request("Range", wire.enc(req)))

    def delete(
        self, key: bytes, range_end: Optional[bytes] = None, prev_kv: bool = False
    ) -> sapi.DeleteRangeResponse:
        req = sapi.DeleteRangeRequest(
            key=key, range_end=range_end or b"", prev_kv=prev_kv
        )
        return wire.dec_response(
            "DeleteRange", self._request("DeleteRange", wire.enc(req))
        )

    def txn(self, txn_req: sapi.TxnRequest) -> sapi.TxnResponse:
        return wire.dec_response("Txn", self._request("Txn", wire.enc(txn_req)))

    def compact(self, revision: int, physical: bool = False) -> sapi.CompactionResponse:
        req = sapi.CompactionRequest(revision=revision, physical=physical)
        return wire.dec_response("Compact", self._request("Compact", wire.enc(req)))

    # -- watch (client/v3/watch.go) --------------------------------------------

    def watch(
        self, key: bytes, range_end: Optional[bytes] = None, start_rev: int = 0
    ) -> WatchHandle:
        h = WatchHandle(self, key, range_end, start_rev)
        self._establish_watch(h)
        return h

    def _establish_watch(self, h: WatchHandle) -> None:
        params: Dict[str, Any] = {
            "key": h.key.hex(),
            "start_revision": h.next_rev,
        }
        if h.range_end:
            params["range_end"] = h.range_end.hex()
        resp = self._request("WatchCreate", params)
        h.watch_id = resp["watch_id"]
        with self._lock:
            self._watches[h.watch_id] = h

    # -- lease (client/v3/lease.go) --------------------------------------------

    def lease_grant(self, ttl: int, lease_id: int = 0) -> sapi.LeaseGrantResponse:
        return wire.dec_response(
            "LeaseGrant", self._request("LeaseGrant", {"ttl": ttl, "id": lease_id})
        )

    def lease_revoke(self, lease_id: int) -> sapi.LeaseRevokeResponse:
        return wire.dec_response(
            "LeaseRevoke", self._request("LeaseRevoke", {"id": lease_id})
        )

    def lease_keep_alive_once(self, lease_id: int) -> int:
        resp = self._request("LeaseKeepAlive", {"id": lease_id})
        return resp["ttl"]

    def lease_time_to_live(self, lease_id: int, keys: bool = False) -> Dict:
        return self._request("LeaseTimeToLive", {"id": lease_id, "keys": keys})

    def lease_keep_alive(self, lease_id: int, interval: Optional[float] = None):
        """Background keepalive; returns a stop callable
        (ref: lease.go KeepAlive loop — sends at ttl/3 cadence)."""
        stop = threading.Event()
        if interval is None:
            ttl = max(self.lease_time_to_live(lease_id).get("granted_ttl", 3), 1)
            interval = ttl / 3.0

        def loop() -> None:
            while not stop.wait(interval):
                try:
                    self.lease_keep_alive_once(lease_id)
                except ClientError:
                    pass  # retried next tick (failover handled in _request)

        threading.Thread(target=loop, daemon=True).start()
        return stop.set

    # -- cluster / maintenance -------------------------------------------------

    def member_list(self) -> List[Dict]:
        return self._request("MemberList", {})["members"]

    def member_add(
        self, member_id: int, name: str = "", peer_urls=None, is_learner=False
    ) -> List[Dict]:
        return self._request(
            "MemberAdd",
            {
                "id": member_id,
                "name": name,
                "peer_urls": peer_urls or [],
                "is_learner": is_learner,
            },
        )["members"]

    def member_remove(self, member_id: int) -> List[Dict]:
        return self._request("MemberRemove", {"id": member_id})["members"]

    def member_promote(self, member_id: int) -> List[Dict]:
        return self._request("MemberPromote", {"id": member_id})["members"]

    def status(self) -> Dict:
        return self._request("Status", {})

    def hash_kv(self, revision: int = 0) -> Dict:
        return self._request("HashKV", {"revision": revision})

    def defragment(self) -> None:
        self._request("Defragment", {})

    def move_leader(self, target_id: int) -> None:
        self._request("MoveLeader", {"target_id": target_id})

    def snapshot(self) -> bytes:
        return bytes.fromhex(self._request("Snapshot", {})["blob"])

    def alarm(self, req: sapi.AlarmRequest) -> sapi.AlarmResponse:
        return wire.dec_response("Alarm", self._request("Alarm", wire.enc(req)))

    # -- election/lock services (server/etcdserver/api/v3election, v3lock) -----

    def lock(self, name: bytes, lease: int, timeout: Optional[float] = None) -> bytes:
        """Server-side Lock RPC (v3lock.go:28-46): blocks on the server
        until the lease owns the mutex; returns the ownership key."""
        params: Dict[str, Any] = {"name": name.hex(), "lease": lease}
        if timeout:
            params["timeout"] = timeout
        rpc_timeout = (timeout + 5.0) if timeout else 24 * 3600.0
        resp = self._request("Lock", params, timeout=rpc_timeout)
        return bytes.fromhex(resp["key"])

    def unlock(self, key: bytes) -> None:
        self._request("Unlock", {"key": key.hex()})

    def campaign(self, name: bytes, lease: int, value: bytes,
                 timeout: Optional[float] = None) -> Dict[str, Any]:
        """Server-side Campaign RPC (v3election.go:42-58); returns the
        LeaderKey dict {name, key, rev, lease} proving leadership."""
        params: Dict[str, Any] = {
            "name": name.hex(), "lease": lease, "value": value.hex()}
        if timeout:
            params["timeout"] = timeout
        rpc_timeout = (timeout + 5.0) if timeout else 24 * 3600.0
        return self._request("Campaign", params, timeout=rpc_timeout)["leader"]

    def proclaim(self, leader: Dict[str, Any], value: bytes) -> None:
        self._request("Proclaim", {"leader": leader, "value": value.hex()})

    def resign(self, leader: Dict[str, Any]) -> None:
        self._request("Resign", {"leader": leader})

    def election_leader(self, name: bytes):
        resp = self._request("Leader", {"name": name.hex()})
        return wire.dec_kv(resp["kv"])

    def observe(self, name: bytes) -> "ObserveHandle":
        """Server-side Observe stream: leader kvs as they change."""
        resp = self._request("Observe", {"name": name.hex()})
        oh = ObserveHandle(self, resp["observe_id"])
        with self._lock:
            # Drain the early buffer under the same lock that registers
            # the handle: once registered, the read loop pushes directly,
            # and a direct push must not overtake older buffered frames.
            self._observes[oh.observe_id] = oh
            for kv in self._observe_early.pop(oh.observe_id, []):
                oh._push(wire.dec_kv(kv))
        return oh

    # -- auth ------------------------------------------------------------------

    def auth_op(self, req: sapi.AuthRequest) -> Any:
        return self._request("Auth", wire.enc(req))

    def auth_enable(self) -> None:
        self.auth_op(sapi.AuthRequest(op="enable"))

    def auth_disable(self) -> None:
        self.auth_op(sapi.AuthRequest(op="disable"))

    def close(self) -> None:
        self._closed = True
        with self._lock:
            sock = self._sock
            self._sock = None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
