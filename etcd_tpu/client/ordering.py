"""Ordering guard: reject responses that travel back in time
(ref: client/v3/ordering/kv.go + util.go — tracks the max revision seen
and errors when a (possibly stale, failed-over) server answers with an
older one).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..server import api as sapi
from .client import Client


class OrderViolationError(Exception):
    """ref: ordering.ErrNoGreaterRev."""


def new_order_violation_switch_endpoint_closure(client: Client):
    """The reference's remedy: rotate to another endpoint and retry once
    (ordering/util.go NewOrderViolationSwitchEndpointClosure)."""

    def fix(_err: OrderViolationError) -> None:
        client._rotate_endpoint()

    return fix


class OrderingKV:
    """Wraps a Client's read path with the monotonic-revision check."""

    def __init__(self, client: Client,
                 violation_fn: Optional[Callable] = None) -> None:
        self.c = client
        self.violation_fn = violation_fn
        self._lock = threading.Lock()
        self._prev_rev = 0

    def _check(self, header: sapi.ResponseHeader):
        with self._lock:
            if header.revision < self._prev_rev:
                err = OrderViolationError(
                    f"revision {header.revision} < previously seen "
                    f"{self._prev_rev}"
                )
                if self.violation_fn is not None:
                    self.violation_fn(err)
                raise err
            self._prev_rev = max(self._prev_rev, header.revision)

    def get(self, key: bytes, **kw) -> sapi.RangeResponse:
        resp = self.c.get(key, **kw)
        self._check(resp.header)
        return resp

    def put(self, key: bytes, value: bytes, **kw) -> sapi.PutResponse:
        resp = self.c.put(key, value, **kw)
        self._check(resp.header)
        return resp

    def delete(self, key: bytes, **kw) -> sapi.DeleteRangeResponse:
        resp = self.c.delete(key, **kw)
        self._check(resp.header)
        return resp

    def txn(self, req: sapi.TxnRequest) -> sapi.TxnResponse:
        resp = self.c.txn(req)
        self._check(resp.header)
        return resp
