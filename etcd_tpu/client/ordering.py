"""Ordering guard: reject responses that travel back in time
(ref: client/v3/ordering/kv.go + util.go — tracks the max revision seen
and errors when a (possibly stale, failed-over) server answers with an
older one).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from ..server import api as sapi
from .client import Client


class OrderViolationError(Exception):
    """ref: ordering.ErrNoGreaterRev."""


def new_order_violation_switch_endpoint_closure(client: Client):
    """The reference's remedy: rotate to another endpoint and retry once
    (ordering/util.go NewOrderViolationSwitchEndpointClosure)."""

    def fix(_err: OrderViolationError) -> None:
        client._rotate_endpoint()

    return fix


class OrderingKV:
    """Wraps a Client's read path with the monotonic-revision check."""

    def __init__(self, client: Client,
                 violation_fn: Optional[Callable] = None) -> None:
        self.c = client
        self.violation_fn = violation_fn
        self._lock = threading.Lock()
        self._prev_rev = 0

    def _violated(self, header: sapi.ResponseHeader) -> bool:
        with self._lock:
            if header.revision < self._prev_rev:
                return True
            self._prev_rev = max(self._prev_rev, header.revision)
            return False

    def _do(self, op: Callable[[], object], retry: bool):
        """Run op; on an order violation apply the remedy (endpoint
        rotate) and — for READS only — retry ONCE before raising, the
        way the reference reissues the request after the violation
        closure runs (ordering/kv.go). Mutations are never re-executed:
        the first attempt already committed, and replaying it would
        double-apply the write."""
        resp = op()
        if not self._violated(resp.header):
            return resp
        err = OrderViolationError(
            f"revision {resp.header.revision} < previously seen revision"
        )
        if self.violation_fn is None:
            raise err
        self.violation_fn(err)
        if not retry:
            raise err
        resp = op()
        if self._violated(resp.header):
            raise err
        return resp

    def get(self, key: bytes, **kw) -> sapi.RangeResponse:
        return self._do(lambda: self.c.get(key, **kw), retry=True)

    def put(self, key: bytes, value: bytes, **kw) -> sapi.PutResponse:
        return self._do(lambda: self.c.put(key, value, **kw), retry=False)

    def delete(self, key: bytes, **kw) -> sapi.DeleteRangeResponse:
        return self._do(lambda: self.c.delete(key, **kw), retry=False)

    def txn(self, req: sapi.TxnRequest) -> sapi.TxnResponse:
        return self._do(lambda: self.c.txn(req), retry=False)
