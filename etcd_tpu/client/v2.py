"""Legacy v2 REST client (ref: client/v2/client.go, keys.go —
KeysAPI Get/Set/Create/CreateInOrder/Update/Delete/Watcher over the
/v2/keys HTTP surface), stdlib http.client only."""

from __future__ import annotations

import http.client
import json
from dataclasses import dataclass, field
from typing import List, Optional, Tuple
from urllib.parse import quote, urlencode


class V2ClientError(Exception):
    """ref: client/v2/client.go Error — the JSON error body."""

    def __init__(self, code: int, message: str, cause: str, index: int):
        super().__init__(f"{code}: {message} ({cause}) [{index}]")
        self.code = code
        self.message = message
        self.cause = cause
        self.index = index


@dataclass
class V2Node:
    key: str = ""
    value: str = ""
    dir: bool = False
    created_index: int = 0
    modified_index: int = 0
    ttl: int = 0
    nodes: List["V2Node"] = field(default_factory=list)


@dataclass
class V2Response:
    action: str = ""
    node: Optional[V2Node] = None
    prev_node: Optional[V2Node] = None
    etcd_index: int = 0


def _dec_node(d: Optional[dict]) -> Optional[V2Node]:
    if d is None:
        return None
    return V2Node(
        key=d.get("key", ""),
        value=d.get("value", ""),
        dir=d.get("dir", False),
        created_index=d.get("createdIndex", 0),
        modified_index=d.get("modifiedIndex", 0),
        ttl=d.get("ttl", 0),
        nodes=[_dec_node(c) for c in d.get("nodes", [])],
    )


class V2Client:
    """One-endpoint-at-a-time REST client with endpoint failover
    (client.go httpClusterClient round-robin)."""

    def __init__(self, endpoints: List[Tuple[str, int]],
                 timeout: float = 10.0):
        self.endpoints = list(endpoints)
        self._i = 0
        self.timeout = timeout

    def _request(self, method: str, path: str, query: dict = None,
                 body: dict = None, timeout: Optional[float] = None):
        query = {k: v for k, v in (query or {}).items() if v is not None}
        body = {k: v for k, v in (body or {}).items() if v is not None}
        url = "/v2/keys" + quote(path)
        if query:
            url += "?" + urlencode(query)
        payload = urlencode(body) if body else None
        if not self.endpoints:
            raise V2ClientError(0, "no endpoints configured", "", 0)
        last: Optional[Exception] = None
        for _ in range(len(self.endpoints)):
            host, port = self.endpoints[self._i % len(self.endpoints)]
            try:
                conn = http.client.HTTPConnection(
                    host, port, timeout=timeout or self.timeout)
                try:
                    headers = {}
                    if payload is not None:
                        headers["Content-Type"] = \
                            "application/x-www-form-urlencoded"
                    conn.request(method, url, body=payload, headers=headers)
                    resp = conn.getresponse()
                    data = json.loads(resp.read() or b"{}")
                    index = int(resp.headers.get("X-Etcd-Index") or 0)
                finally:
                    conn.close()
                if "errorCode" in data:
                    raise V2ClientError(
                        data["errorCode"], data.get("message", ""),
                        data.get("cause", ""), data.get("index", 0))
                return V2Response(
                    action=data.get("action", ""),
                    node=_dec_node(data.get("node")),
                    prev_node=_dec_node(data.get("prevNode")),
                    etcd_index=index,
                )
            except (OSError, TimeoutError) as e:
                last = e
                self._i += 1  # failover
        raise last  # type: ignore[misc]

    # -- KeysAPI (client/v2/keys.go) -------------------------------------------

    def get(self, key: str, recursive: bool = False,
            sorted_: bool = False) -> V2Response:
        return self._request("GET", key, query={
            "recursive": "true" if recursive else None,
            "sorted": "true" if sorted_ else None,
        })

    def set(self, key: str, value: str, ttl: Optional[int] = None,
            prev_value: Optional[str] = None, prev_index: int = 0,
            prev_exist: Optional[bool] = None) -> V2Response:
        body = {"value": value, "ttl": ttl}
        if prev_value is not None:
            body["prevValue"] = prev_value
        if prev_index:
            body["prevIndex"] = prev_index
        if prev_exist is not None:
            body["prevExist"] = "true" if prev_exist else "false"
        return self._request("PUT", key, body=body)

    def mkdir(self, key: str, ttl: Optional[int] = None) -> V2Response:
        return self._request("PUT", key, body={"dir": "true", "ttl": ttl})

    def create(self, key: str, value: str,
               ttl: Optional[int] = None) -> V2Response:
        return self.set(key, value, ttl=ttl, prev_exist=False)

    def create_in_order(self, dir_: str, value: str,
                        ttl: Optional[int] = None) -> V2Response:
        return self._request("POST", dir_, body={"value": value, "ttl": ttl})

    def update(self, key: str, value: str,
               ttl: Optional[int] = None) -> V2Response:
        return self.set(key, value, ttl=ttl, prev_exist=True)

    def delete(self, key: str, recursive: bool = False, dir_: bool = False,
               prev_value: Optional[str] = None,
               prev_index: int = 0) -> V2Response:
        return self._request("DELETE", key, query={
            "recursive": "true" if recursive else None,
            "dir": "true" if dir_ else None,
            "prevValue": prev_value,
            "prevIndex": prev_index or None,
        })

    def watch(self, key: str, recursive: bool = False, after_index: int = 0,
              timeout: float = 30.0) -> Optional[V2Response]:
        """One long-poll wait (keys.go Watcher.Next)."""
        out = self._request("GET", key, query={
            "wait": "true",
            "recursive": "true" if recursive else None,
            "waitIndex": after_index + 1 if after_index else None,
        }, timeout=timeout + 5.0)
        return out if out.node is not None else None
