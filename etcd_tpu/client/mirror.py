"""Mirroring: base snapshot + update stream of a key prefix
(ref: client/v3/mirror/syncer.go SyncBase/SyncUpdates;
etcdctl make-mirror command/make_mirror_command.go).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from ..server import api as sapi
from ..storage.mvcc.kv import Event, EventType
from .client import Client
from .util import prefix_end


class Syncer:
    """ref: mirror.NewSyncer(client, prefix, rev)."""

    def __init__(self, client: Client, prefix: bytes = b"",
                 rev: int = 0) -> None:
        self.c = client
        self.prefix = prefix
        self.rev = rev

    def sync_base(self) -> Tuple[int, List[sapi.KeyValue]]:
        """One consistent snapshot of the prefix: (revision, kvs)
        (ref: syncer.go SyncBase — paginated range pinned at one rev)."""
        # Empty prefix mirrors the whole keyspace: [\x00, open-end)
        # (syncer.go uses the same "\x00" + open-end sentinel pair).
        key = self.prefix if self.prefix else b"\x00"
        end = prefix_end(self.prefix)
        resp = self.c.get(key, end, revision=self.rev)
        at_rev = self.rev or resp.header.revision
        kvs = list(resp.kvs)
        # Paginate if the server limited the response.
        while resp.more and resp.kvs:
            next_key = resp.kvs[-1].key + b"\x00"
            resp = self.c.get(next_key, end, revision=at_rev)
            kvs.extend(resp.kvs)
        return at_rev, kvs

    def sync_updates(self):
        """WatchHandle streaming changes after the base revision
        (ref: syncer.go SyncUpdates — watch from rev+1)."""
        if self.rev == 0:
            raise ValueError("call sync_base first (rev unset)")
        key = self.prefix if self.prefix else b"\x00"
        end = prefix_end(self.prefix)
        return self.c.watch(key, end, start_rev=self.rev + 1)

    # -- make-mirror (etcdctl) -------------------------------------------------

    def mirror_to(self, dest: Client, dest_prefix: Optional[bytes] = None,
                  max_txns: int = 0, base_only: bool = False,
                  stop=None) -> int:
        """Copy base then stream updates into `dest`; returns keys
        mirrored. base_only skips the update stream; max_txns>0 bounds
        the update phase (testing/one-shot); max_txns=0 streams until
        interrupted (ref: make_mirror_command.go). `stop` is an optional
        threading.Event-like object checked between batches."""
        rev, kvs = self.sync_base()
        self.rev = rev

        def rewrite(key: bytes) -> bytes:
            if dest_prefix is not None and self.prefix:
                return dest_prefix + key[len(self.prefix):]
            return key

        count = 0
        for kv in kvs:
            dest.put(rewrite(kv.key), kv.value)
            count += 1
        if base_only:
            return count
        h = self.sync_updates()
        try:
            applied = 0
            while max_txns == 0 or applied < max_txns:
                if stop is not None and stop.is_set():
                    break
                got = h.get(timeout=0.5)
                if got is None:
                    continue
                _, events = got
                for ev in events:
                    if ev.type == EventType.PUT:
                        dest.put(rewrite(ev.kv.key), ev.kv.value)
                        count += 1
                    else:
                        dest.delete(rewrite(ev.kv.key))
                    applied += 1
                    if max_txns and applied >= max_txns:
                        break
            return count
        finally:
            h.cancel()
