"""Key-prefix namespacing (ref: client/v3/namespace/ — kv.go, watch.go:
every outgoing key/range_end gains the prefix, every returned key loses
it, so an application sees a private keyspace)."""

from __future__ import annotations

from typing import Optional

from ..server import api as sapi
from .client import Client, WatchHandle
from .util import prefix_end as _prefix_end


def _prefix_interval(pfx: bytes, key: bytes, end: bytes) -> tuple:
    """ref: namespace/util.go prefixInterval."""
    pkey = pfx + key
    if not end:
        pend = b""
    elif end == b"\x00":
        # "from key to end of keyspace" → end of prefix range.
        pend = _prefix_end(pfx)
    else:
        pend = pfx + end
    return pkey, pend


class NamespacedClient:
    """Wrap a Client so all KV/watch ops live under `prefix`."""

    def __init__(self, client: Client, prefix: bytes) -> None:
        self.c = client
        self.pfx = prefix

    def _strip(self, resp) -> None:
        for kv in getattr(resp, "kvs", []) or []:
            kv.key = kv.key[len(self.pfx):]
        pk = getattr(resp, "prev_kv", None)
        if pk is not None:
            pk.key = pk.key[len(self.pfx):]
        for kv in getattr(resp, "prev_kvs", []) or []:
            kv.key = kv.key[len(self.pfx):]

    def put(self, key: bytes, value: bytes, **kw) -> sapi.PutResponse:
        resp = self.c.put(self.pfx + key, value, **kw)
        self._strip(resp)
        return resp

    def get(self, key: bytes, range_end: Optional[bytes] = None, **kw):
        pkey, pend = _prefix_interval(self.pfx, key, range_end or b"")
        resp = self.c.get(pkey, range_end=pend or None, **kw)
        self._strip(resp)
        return resp

    def delete(self, key: bytes, range_end: Optional[bytes] = None, **kw):
        pkey, pend = _prefix_interval(self.pfx, key, range_end or b"")
        resp = self.c.delete(pkey, range_end=pend or None, **kw)
        self._strip(resp)
        return resp

    def watch(self, key: bytes, range_end: Optional[bytes] = None,
              start_rev: int = 0) -> "NamespacedWatch":
        pkey, pend = _prefix_interval(self.pfx, key, range_end or b"")
        return NamespacedWatch(
            self.c.watch(pkey, range_end=pend or None, start_rev=start_rev),
            self.pfx,
        )


class NamespacedWatch:
    def __init__(self, handle: WatchHandle, pfx: bytes) -> None:
        self.h = handle
        self.pfx = pfx

    def get(self, timeout=None):
        batch = self.h.get(timeout)
        if batch is None:
            return None
        rev, events = batch
        for ev in events:
            ev.kv.key = ev.kv.key[len(self.pfx):]
            if ev.prev_kv is not None:
                ev.prev_kv.key = ev.prev_kv.key[len(self.pfx):]
        return rev, events

    def cancel(self) -> None:
        self.h.cancel()
