"""Service naming over the KV space: register endpoints under a prefix
and resolve/watch them (ref: client/v3/naming/endpoints/endpoints_impl.go
+ naming/resolver — the gRPC resolver is the reference's transport glue;
the registry semantics live here).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from .client import Client
from .util import prefix_end


class Endpoints:
    """Manager for `target/instance -> {"Addr", "Metadata"}` records."""

    def __init__(self, client: Client, target: str) -> None:
        self.c = client
        self.target = target.rstrip("/")

    def _key(self, instance: str) -> bytes:
        return f"{self.target}/{instance}".encode()

    def add(self, instance: str, addr: str,
            metadata: Optional[Dict] = None, lease: int = 0) -> None:
        rec = {"Addr": addr, "Metadata": metadata or {}}
        self.c.put(self._key(instance), json.dumps(rec).encode(), lease=lease)

    def delete(self, instance: str) -> None:
        self.c.delete(self._key(instance))

    def list(self) -> Dict[str, Dict]:
        pfx = (self.target + "/").encode()
        resp = self.c.get(pfx, prefix_end(pfx))
        out = {}
        for kv in resp.kvs:
            inst = kv.key[len(pfx):].decode("utf-8", "replace")
            try:
                out[inst] = json.loads(kv.value)
            except ValueError:
                continue
        return out

    def addresses(self) -> List[str]:
        return [r["Addr"] for r in self.list().values()]

    def watch(self):
        """WatchHandle over the prefix; callers diff add/delete events
        to keep a resolver's address list current."""
        pfx = (self.target + "/").encode()
        return self.c.watch(pfx, prefix_end(pfx))
