"""Snapshot save over the maintenance API
(ref: client/v3/snapshot/v3_snapshot.go SaveWithVersion)."""

from __future__ import annotations

import os

from .client import Client


def save(client: Client, path: str) -> int:
    """Stream the backend snapshot to `path`; returns bytes written.
    Writes to a temp file then renames (partial downloads never appear
    at the final path, v3_snapshot.go:47-93)."""
    blob = client.snapshot()
    tmp = path + ".part"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(blob)
