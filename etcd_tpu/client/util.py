"""Compare + range helpers (ref: client/v3/clientv3util/util.go,
clientv3.GetPrefixRangeEnd)."""

from __future__ import annotations

from ..server import api as sapi


def prefix_end(prefix: bytes) -> bytes:
    """Exclusive upper bound of all keys with `prefix`
    (ref: clientv3/op.go getPrefix). Empty prefix → b"\\x00", the
    open-end sentinel covering the whole keyspace."""
    b = bytearray(prefix)
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1])
    return b"\x00"


def key_exists(key: bytes) -> sapi.Compare:
    """Txn guard: key has been created (CreateRevision > 0)."""
    return sapi.Compare(
        target=sapi.CompareTarget.CREATE,
        result=sapi.CompareResult.GREATER,
        key=key,
        create_revision=0,
    )


def key_missing(key: bytes) -> sapi.Compare:
    """Txn guard: key does not exist (CreateRevision == 0)."""
    return sapi.Compare(
        target=sapi.CompareTarget.CREATE,
        result=sapi.CompareResult.EQUAL,
        key=key,
        create_revision=0,
    )
