"""Distributed coordination recipes (ref: client/v3/concurrency/).

* ``Session`` — a lease kept alive for the client's lifetime
  (session.go);
* ``Mutex`` — lock ownership by lowest create-revision under a prefix,
  waiting on the predecessor's delete (mutex.go);
* ``Election`` — campaign/proclaim/resign/leader on the same ordering
  (election.go);
* ``STM`` — software transactional memory: read-set/write-set with
  mod-revision conflict detection and retry (stm.go, serializable
  level).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..server import api as sapi
from .client import Client, ClientError
from .util import prefix_end as _prefix_end


class Session:
    """ref: concurrency/session.go — lease + keepalive."""

    def __init__(self, client: Client, ttl: int = 10,
                 lease_id: int = 0) -> None:
        """With ``lease_id`` the session adopts an existing lease
        (concurrency.WithLease, session.go:32-38) instead of granting
        one; the caller owns its lifetime."""
        self.client = client
        if lease_id:
            self.lease_id = lease_id
        else:
            resp = client.lease_grant(ttl=ttl)
            self.lease_id = resp.id
        self._stop_keepalive = client.lease_keep_alive(self.lease_id)
        self._closed = False

    @classmethod
    def from_lease(cls, client: Client, lease_id: int) -> "Session":
        """An orphaned session around a caller-owned lease: no
        keepalive, no revoke-on-close (the pattern of the server-side
        lock/election services, ref v3lock.go:30-37 NewSession+Orphan)."""
        s = cls.__new__(cls)
        s.client = client
        s.lease_id = lease_id
        s._stop_keepalive = lambda: None
        s._closed = False
        return s

    def close(self) -> None:
        """Revoke the lease: all owned locks/leadership vanish at once."""
        if self._closed:
            return
        self._closed = True
        self._stop_keepalive()
        try:
            self.client.lease_revoke(self.lease_id)
        except ClientError:
            pass

    def orphan(self) -> None:
        """Stop keepalive but keep the lease (session.go Orphan)."""
        self._closed = True
        self._stop_keepalive()


def _put_if_absent_txn(key: bytes, value: bytes, lease: int) -> sapi.TxnRequest:
    return sapi.TxnRequest(
        compare=[
            sapi.Compare(
                result=sapi.CompareResult.EQUAL,
                target=sapi.CompareTarget.CREATE,
                key=key,
                create_revision=0,
            )
        ],
        success=[
            sapi.RequestOp(
                request_put=sapi.PutRequest(key=key, value=value, lease=lease)
            )
        ],
        failure=[sapi.RequestOp(request_range=sapi.RangeRequest(key=key))],
    )


class Mutex:
    """ref: concurrency/mutex.go."""

    def __init__(self, session: Session, prefix: str) -> None:
        self.session = session
        self.prefix = prefix.rstrip("/") + "/"
        self.my_key = (self.prefix + f"{session.lease_id:x}").encode()
        self.my_rev = 0
        self._owned = False

    def lock(self, timeout: Optional[float] = None) -> None:
        c = self.session.client
        resp = c.txn(_put_if_absent_txn(self.my_key, b"", self.session.lease_id))
        if resp.succeeded:
            self.my_rev = resp.header.revision
        else:
            self.my_rev = resp.responses[0].response_range.kvs[0].create_revision
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            # Owner = lowest create-revision under the prefix.
            rr = c.get(
                self.prefix.encode(),
                range_end=_prefix_end(self.prefix.encode()),
                sort_order=sapi.SortOrder.ASCEND,
                sort_target=sapi.SortTarget.CREATE,
                limit=1,
            )
            if rr.kvs and rr.kvs[0].key == self.my_key:
                self._owned = True
                return
            if deadline is not None and time.monotonic() > deadline:
                self.unlock()
                raise TimeoutError("mutex lock timeout")
            # Wait for the current owner's key to change (waitDeletes).
            h = c.watch(
                self.prefix.encode(),
                range_end=_prefix_end(self.prefix.encode()),
                start_rev=rr.header.revision + 1,
            )
            try:
                h.get(timeout=0.5)
            finally:
                h.cancel()

    def unlock(self) -> None:
        self._owned = False
        try:
            self.session.client.delete(self.my_key)
        except ClientError:
            pass

    def is_owner(self) -> bool:
        return self._owned

    def __enter__(self) -> "Mutex":
        self.lock()
        return self

    def __exit__(self, *exc) -> None:
        self.unlock()


class Election:
    """ref: concurrency/election.go."""

    def __init__(self, session: Session, prefix: str) -> None:
        self.session = session
        self.prefix = prefix.rstrip("/") + "/"
        self.leader_key: Optional[bytes] = None
        self.leader_rev = 0

    def campaign(self, value: bytes, timeout: Optional[float] = None) -> None:
        c = self.session.client
        key = (self.prefix + f"{self.session.lease_id:x}").encode()
        resp = c.txn(_put_if_absent_txn(key, value, self.session.lease_id))
        if resp.succeeded:
            self.leader_rev = resp.header.revision
        else:
            kv = resp.responses[0].response_range.kvs[0]
            self.leader_rev = kv.create_revision
            if kv.value != value:
                c.put(key, value, lease=self.session.lease_id)
        self.leader_key = key
        deadline = time.monotonic() + timeout if timeout else None
        while True:
            rr = c.get(
                self.prefix.encode(),
                range_end=_prefix_end(self.prefix.encode()),
                sort_order=sapi.SortOrder.ASCEND,
                sort_target=sapi.SortTarget.CREATE,
                limit=1,
            )
            if rr.kvs and rr.kvs[0].key == key:
                return  # elected
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("campaign timeout")
            h = c.watch(
                self.prefix.encode(),
                range_end=_prefix_end(self.prefix.encode()),
                start_rev=rr.header.revision + 1,
            )
            try:
                h.get(timeout=0.5)
            finally:
                h.cancel()

    def proclaim(self, value: bytes) -> None:
        if self.leader_key is None:
            raise RuntimeError("not campaigning")
        self.session.client.put(
            self.leader_key, value, lease=self.session.lease_id
        )

    def resign(self) -> None:
        if self.leader_key is not None:
            try:
                self.session.client.delete(self.leader_key)
            except ClientError:
                pass
            self.leader_key = None

    def leader(self) -> Optional[sapi.RangeResponse]:
        rr = self.session.client.get(
            self.prefix.encode(),
            range_end=_prefix_end(self.prefix.encode()),
            sort_order=sapi.SortOrder.ASCEND,
            sort_target=sapi.SortTarget.CREATE,
            limit=1,
        )
        return rr if rr.kvs else None


class STMConflict(Exception):
    pass


class STM:
    """Serializable software transactional memory
    (ref: concurrency/stm.go stmSerializable)."""

    def __init__(self, client: Client, max_retries: int = 64) -> None:
        self.client = client
        self.max_retries = max_retries

    def run(self, apply_fn: Callable[["STMTxn"], None]) -> sapi.TxnResponse:
        for _ in range(self.max_retries):
            txn = STMTxn(self.client)
            apply_fn(txn)
            resp = txn._commit()
            if resp is not None:
                return resp
        raise STMConflict("too many stm retries")


class STMTxn:
    def __init__(self, client: Client) -> None:
        self.c = client
        self.rset: Dict[bytes, Tuple[int, bytes]] = {}  # key -> (mod_rev, value)
        self.wset: Dict[bytes, bytes] = {}
        self._first_read_rev = 0

    def get(self, key: bytes) -> bytes:
        if key in self.wset:
            return self.wset[key]
        if key in self.rset:
            return self.rset[key][1]
        rr = self.c.get(key, revision=self._first_read_rev, serializable=True)
        if self._first_read_rev == 0:
            # Pin all later reads to the first read's revision
            # (stm.go firstRead rev pinning).
            self._first_read_rev = rr.header.revision
        if rr.kvs:
            self.rset[key] = (rr.kvs[0].mod_revision, rr.kvs[0].value)
            return rr.kvs[0].value
        self.rset[key] = (0, b"")
        return b""

    def put(self, key: bytes, value: bytes) -> None:
        self.wset[key] = value

    def _commit(self) -> Optional[sapi.TxnResponse]:
        cmps = [
            sapi.Compare(
                result=sapi.CompareResult.EQUAL,
                target=sapi.CompareTarget.MOD,
                key=k,
                mod_revision=rev,
            )
            for k, (rev, _v) in self.rset.items()
        ]
        puts = [
            sapi.RequestOp(request_put=sapi.PutRequest(key=k, value=v))
            for k, v in self.wset.items()
        ]
        resp = self.c.txn(sapi.TxnRequest(compare=cmps, success=puts, failure=[]))
        return resp if resp.succeeded else None


