"""The official client (ref: client/v3).

``Client`` speaks the v3rpc wire protocol with endpoint failover and
retry; watches re-establish across reconnects from the last delivered
revision (client/v3/watch.go's resume machinery); leases keep alive on
a background loop (client/v3/lease.go). Recipes — Session, Mutex,
Election, STM — live in ``concurrency``.
"""

from .client import Client, ClientError  # noqa: F401
