"""DNS SRV discovery (ref: client/pkg/srv/srv.go — GetCluster resolves
_etcd-server[-ssl]._tcp.<domain> into an initial-cluster string,
GetClient resolves _etcd-client[-ssl]._tcp.<domain> into endpoints).

Resolution is pluggable: the default resolver uses ``dns.resolver``
when the dnspython package is present and raises a clear error
otherwise — stdlib Python cannot issue SRV queries. Tests (and
air-gapped deployments) inject a resolver callable returning
[(target_host, port), ...]."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

# resolver(service_name) -> [(host, port)], e.g. for
# "_etcd-server._tcp.example.com"
SRVResolver = Callable[[str], List[Tuple[str, int]]]


class SRVLookupError(Exception):
    pass


def default_resolver(name: str) -> List[Tuple[str, int]]:
    try:
        import dns.resolver  # type: ignore[import-not-found]
    except ImportError as e:
        raise SRVLookupError(
            "SRV discovery needs the dnspython package (or an injected "
            "resolver)") from e
    try:
        answers = dns.resolver.resolve(name, "SRV")
    except Exception as e:  # noqa: BLE001 — NXDOMAIN etc.
        raise SRVLookupError(f"SRV lookup {name!r} failed: {e}") from e
    return [(str(rr.target).rstrip("."), int(rr.port)) for rr in answers]


@dataclass
class SRVClients:
    """ref: srv.go SRVClients."""

    endpoints: List[str] = field(default_factory=list)


def get_cluster(service: str, service_name: str, name: str, domain: str,
                resolver: Optional[SRVResolver] = None) -> List[str]:
    """Build the --initial-cluster list from SRV records
    (ref: srv.go:33-94 GetCluster). ``service`` is "etcd-server" or
    "etcd-server-ssl"; each SRV target becomes
    "<n>=<scheme>://host:port" with generated names for peers other
    than ``name``."""
    resolver = resolver or default_resolver
    scheme = "https" if service.endswith("-ssl") else "http"
    srv_name = f"_{service}._tcp.{domain}"
    if service_name:
        srv_name = f"_{service}-{service_name}._tcp.{domain}"
    # Names are positional; the CALLER renames its own entry by
    # matching its advertised peer URL (srv.go does the same — name
    # inference from hosts is ambiguous, e.g. infra1 vs infra10).
    out: List[str] = []
    for n, (host, port) in enumerate(resolver(srv_name)):
        out.append(f"{n}={scheme}://{host}:{port}")
    if not out:
        raise SRVLookupError(f"no SRV records for {srv_name!r}")
    return out


def get_client(service: str, domain: str, service_name: str = "",
               resolver: Optional[SRVResolver] = None) -> SRVClients:
    """Client endpoints from SRV (ref: srv.go:96-141 GetClient):
    "etcd-client" / "etcd-client-ssl"."""
    resolver = resolver or default_resolver
    scheme = "https" if service.endswith("-ssl") else "http"
    srv_name = f"_{service}._tcp.{domain}"
    if service_name:
        srv_name = f"_{service}-{service_name}._tcp.{domain}"
    eps = [f"{scheme}://{host}:{port}" for host, port in resolver(srv_name)]
    if not eps:
        raise SRVLookupError(f"no SRV records for {srv_name!r}")
    return SRVClients(endpoints=eps)
