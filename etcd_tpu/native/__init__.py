"""Native (C++) runtime components, loaded over ctypes.

The hot host-side I/O paths — WAL segment framing/CRC/fsync — are C++
(``src/walog.cc``), mirroring how the reference keeps its durable-log
machinery out of the request path's interpreted layers. The shared
library is built on first import with g++ and cached next to the
sources; rebuilds trigger automatically when a source file is newer
than the cached .so.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_LIB = os.path.join(_DIR, "lib")

_lock = threading.Lock()
_cache: dict[str, ctypes.CDLL] = {}


def _build(name: str) -> str:
    src = os.path.join(_SRC, f"{name}.cc")
    out = os.path.join(_LIB, f"lib{name}.so")
    os.makedirs(_LIB, exist_ok=True)
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    tmp = out + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-g", "-std=c++17", "-fPIC", "-shared",
        "-Wall", "-Wextra", "-o", tmp, src,
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(tmp, out)
    return out


def load(name: str) -> ctypes.CDLL:
    with _lock:
        lib = _cache.get(name)
        if lib is None:
            lib = ctypes.CDLL(_build(name))
            _cache[name] = lib
        return lib
