"""ctypes binding for the native segmented record log (src/walog.cc)."""

from __future__ import annotations

import ctypes
import os
import time
from typing import Callable, List, Optional, Tuple

from . import load

# Slow-disk emulation for benches/tests (the etcd idiom is a gofail
# sleep on the persistence path — tests/robustness uses it to model
# cloud/HDD-class disks): ETCD_TPU_FSYNC_DELAY_MS adds a GIL-released
# sleep to every sync flush, i.e. pure IO WAIT, which is what a real
# fsync is. Default 0 (off); benches that set it MUST label their
# artifacts with it. This is how the async WAL pipeline's group-commit
# win is measurable on boxes whose local disk syncs in microseconds.
_FSYNC_DELAY_S = float(
    os.environ.get("ETCD_TPU_FSYNC_DELAY_MS", "0") or 0) / 1e3

_REC_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_int,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ctypes.c_uint64, ctypes.c_uint64,
)


def _lib() -> ctypes.CDLL:
    lib = load("walog")
    if getattr(lib, "_walog_typed", False):
        return lib
    lib.walog_open.restype = ctypes.c_void_p
    lib.walog_open.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.walog_errmsg.restype = ctypes.c_char_p
    lib.walog_errmsg.argtypes = [ctypes.c_void_p]
    lib.walog_append.restype = ctypes.c_int
    lib.walog_append.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.walog_flush.restype = ctypes.c_int64
    lib.walog_flush.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.walog_cut.restype = ctypes.c_int
    lib.walog_cut.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.walog_release_before.restype = ctypes.c_int
    lib.walog_release_before.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.walog_read_all.restype = ctypes.c_int
    lib.walog_read_all.argtypes = [
        ctypes.c_char_p, ctypes.c_int, _REC_CB, ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.walog_tail_state.restype = ctypes.c_int
    lib.walog_tail_state.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.walog_close.argtypes = [ctypes.c_void_p]
    for fn in ("walog_tail_offset", "walog_tail_seq", "walog_last_sync_ns",
               "walog_total_syncs", "walog_total_sync_ns"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib._walog_typed = True
    return lib


class WalogError(Exception):
    pass


class Walog:
    """Segmented CRC-chained record log (native handle wrapper)."""

    def __init__(self, dirpath: str, segment_bytes: int = 64 << 20,
                 create: bool = False) -> None:
        self._lib = _lib()
        err = ctypes.create_string_buffer(512)
        self._h = self._lib.walog_open(
            dirpath.encode(), segment_bytes, 1 if create else 0, err, len(err)
        )
        if not self._h:
            raise WalogError(err.value.decode() or "walog_open failed")
        self.dirpath = dirpath

    def _check(self, rc: int) -> None:
        if rc < 0:
            raise WalogError(self._lib.walog_errmsg(self._h).decode())

    def append(self, rtype: int, data: bytes) -> None:
        self._check(self._lib.walog_append(self._h, rtype, data, len(data)))

    def flush(self, sync: bool = True) -> int:
        if sync and _FSYNC_DELAY_S > 0:
            time.sleep(_FSYNC_DELAY_S)  # slow-disk emulation (see top)
        rc = self._lib.walog_flush(self._h, 1 if sync else 0)
        self._check(rc)
        return rc

    def cut(self, meta: int) -> None:
        self._check(self._lib.walog_cut(self._h, meta))

    def release_before(self, meta: int) -> int:
        rc = self._lib.walog_release_before(self._h, meta)
        self._check(rc)
        return rc

    def tail_offset(self) -> int:
        return self._lib.walog_tail_offset(self._h)

    def tail_seq(self) -> int:
        return self._lib.walog_tail_seq(self._h)

    def last_sync_ns(self) -> int:
        return self._lib.walog_last_sync_ns(self._h)

    def sync_stats(self) -> Tuple[int, int]:
        """(total_syncs, total_sync_ns) for the fsync histogram."""
        return (
            self._lib.walog_total_syncs(self._h),
            self._lib.walog_total_sync_ns(self._h),
        )

    def close(self) -> None:
        if self._h:
            self._lib.walog_close(self._h)
            self._h = None

    def __enter__(self) -> "Walog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_all(dirpath: str, repair: bool = True) -> List[Tuple[int, bytes, int, int]]:
    """Validated records [(type, payload, seg_seq, seg_meta)] across all
    segments; truncates a torn tail when repair=True. Raises on
    corruption in non-tail segments."""
    lib = _lib()
    out: List[Tuple[int, bytes, int, int]] = []

    @_REC_CB
    def cb(_ctx, rtype, data, length, seq, meta):
        out.append((rtype, ctypes.string_at(data, length), seq, meta))

    err = ctypes.create_string_buffer(512)
    rc = lib.walog_read_all(
        dirpath.encode(), 1 if repair else 0, cb, None, err, len(err)
    )
    if rc < 0:
        raise WalogError(err.value.decode() or "walog_read_all failed")
    return out


# Tail-shape classification (walog_tail_state return values). The
# distinction matters for protocol-aware recovery (FAST'18): a CLEAN
# boundary proves only that no record was mid-write at the crash, while
# a TORN mid-record break proves bytes beyond the last whole record
# were destroyed — if the file's contents were fsync-acknowledged, that
# is lost durable data, a fault class raft's model does not cover.
TAIL_CLEAN, TAIL_TORN, TAIL_CORRUPT = 0, 1, 2
TAIL_NAMES = {TAIL_CLEAN: "clean", TAIL_TORN: "torn",
              TAIL_CORRUPT: "corrupt"}


def tail_state(dirpath: str) -> int:
    """Classify the LAST segment's tail: TAIL_CLEAN (ends exactly at a
    record boundary, chain valid), TAIL_TORN (ends inside a record —
    the mid-record CRC break / past-EOF shapes), or TAIL_CORRUPT (a
    complete record fails its crc). Call BEFORE read_all(repair=True):
    repair truncates the torn evidence back to a clean boundary."""
    lib = _lib()
    err = ctypes.create_string_buffer(512)
    rc = lib.walog_tail_state(dirpath.encode(), err, len(err))
    if rc < 0:
        raise WalogError(err.value.decode() or "walog_tail_state failed")
    return rc


def read_all_classified(
    dirpath: str, repair: bool = True,
) -> Tuple[List[Tuple[int, bytes, int, int]], int]:
    """read_all plus the tail classification taken BEFORE any repair:
    (records, TAIL_*). The recovery path (hosting._replay) uses the
    classification to distinguish a benign crash boundary from a
    mid-record break that destroyed bytes."""
    ts = tail_state(dirpath)
    return read_all(dirpath, repair=repair), ts


def segment_records(path: str) -> List[Tuple[int, int, int, int]]:
    """Frame-walk one segment file WITHOUT crc validation:
    [(offset, rtype, payload_len, padded_size)] for every complete
    record (the CRC-reset seed included). Tooling/test helper for
    locating record boundaries (e.g. to place a deterministic
    mid-record tear); stops at the first record running past EOF."""
    import struct as _struct

    out: List[Tuple[int, int, int, int]] = []
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off + 12 <= len(data):
        ln, rtype = _struct.unpack_from("<IB", data, off)
        padded = (12 + ln + 7) & ~7
        if off + padded > len(data):
            break
        out.append((off, rtype, ln, padded))
        off += padded
    return out


def verify(dirpath: str) -> bool:
    """Validate the whole chain without repairing (ref: wal.Verify
    wal.go:629). Returns True when every segment checks out."""
    try:
        read_all(dirpath, repair=False)
        return True
    except WalogError:
        return False
