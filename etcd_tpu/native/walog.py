"""ctypes binding for the native segmented record log (src/walog.cc)."""

from __future__ import annotations

import ctypes
import os
import time
from typing import Callable, List, Optional, Tuple

from . import load

# Slow-disk emulation for benches/tests (the etcd idiom is a gofail
# sleep on the persistence path — tests/robustness uses it to model
# cloud/HDD-class disks): ETCD_TPU_FSYNC_DELAY_MS adds a GIL-released
# sleep to every sync flush, i.e. pure IO WAIT, which is what a real
# fsync is. Default 0 (off); benches that set it MUST label their
# artifacts with it. This is how the async WAL pipeline's group-commit
# win is measurable on boxes whose local disk syncs in microseconds.
# Read PER Walog INSTANCE (at __init__), not latched at import: tests
# and benches vary it between members/episodes without a fresh
# interpreter (the ISSUE 15 satellite fix).


def _fsync_delay_s() -> float:
    return float(
        os.environ.get("ETCD_TPU_FSYNC_DELAY_MS", "0") or 0) / 1e3

_REC_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_void_p, ctypes.c_int,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ctypes.c_uint64, ctypes.c_uint64,
)


def _lib() -> ctypes.CDLL:
    lib = load("walog")
    if getattr(lib, "_walog_typed", False):
        return lib
    lib.walog_open.restype = ctypes.c_void_p
    lib.walog_open.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.walog_errmsg.restype = ctypes.c_char_p
    lib.walog_errmsg.argtypes = [ctypes.c_void_p]
    lib.walog_append.restype = ctypes.c_int
    lib.walog_append.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
    ]
    lib.walog_flush.restype = ctypes.c_int64
    lib.walog_flush.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.walog_cut.restype = ctypes.c_int
    lib.walog_cut.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.walog_release_before.restype = ctypes.c_int
    lib.walog_release_before.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.walog_read_all.restype = ctypes.c_int
    lib.walog_read_all.argtypes = [
        ctypes.c_char_p, ctypes.c_int, _REC_CB, ctypes.c_void_p,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.walog_tail_state.restype = ctypes.c_int
    lib.walog_tail_state.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.walog_close.argtypes = [ctypes.c_void_p]
    for fn in ("walog_tail_offset", "walog_tail_seq", "walog_last_sync_ns",
               "walog_total_syncs", "walog_total_sync_ns"):
        getattr(lib, fn).restype = ctypes.c_uint64
        getattr(lib, fn).argtypes = [ctypes.c_void_p]
    lib._walog_typed = True
    return lib


class WalogError(Exception):
    pass


class DiskFullError(WalogError):
    """ENOSPC-class WRITE failure raised at the fault-hook seam before
    the bytes touched the native buffer — provably nothing was written,
    so the caller may back-pressure and retry the same record. A
    failure surfacing from the native write/fsync itself never gets
    this type: a partial write or a failed fsync leaves the on-disk /
    page-cache state unknowable, and the IO-error contract
    (hosting.py) fail-stops instead (ATC'19: never retry-fsync over
    possibly-dropped dirty pages)."""


class InjectedIOError(WalogError):
    """Deterministic injected IO failure (DiskFaultPlan fsync/write
    errors). Carries the op name so fail-stop accounting can label the
    stage."""


def is_disk_full(exc: BaseException) -> bool:
    """Whether an exception is the retryable nothing-was-written
    ENOSPC class (see DiskFullError)."""
    return isinstance(exc, DiskFullError)


class Walog:
    """Segmented CRC-chained record log (native handle wrapper).

    ``fault_hook(op, nbytes)`` — the storage fault plane's seam
    (batched/faults.DiskFaultPlan): called BEFORE every file-affecting
    native call with op in {"append", "flush", "fsync"}. The hook may
    sleep (per-op latency injection — the slow-disk-as-a-fault
    generalization of ETCD_TPU_FSYNC_DELAY_MS) or raise
    (DiskFullError / InjectedIOError); a raise at the seam guarantees
    the native op was never started, which is what makes the
    DiskFullError retry contract sound."""

    def __init__(self, dirpath: str, segment_bytes: int = 64 << 20,
                 create: bool = False,
                 fault_hook: Optional[Callable[[str, int], None]] = None,
                 ) -> None:
        self._lib = _lib()
        err = ctypes.create_string_buffer(512)
        self._h = self._lib.walog_open(
            dirpath.encode(), segment_bytes, 1 if create else 0, err, len(err)
        )
        if not self._h:
            raise WalogError(err.value.decode() or "walog_open failed")
        self.dirpath = dirpath
        self.fault_hook = fault_hook
        # Per-instance (NOT import-latched): a test/bench can flip the
        # env var between member boots in one interpreter.
        self._fsync_delay_s = _fsync_delay_s()

    def _check(self, rc: int) -> None:
        if rc < 0:
            raise WalogError(self._lib.walog_errmsg(self._h).decode())

    def append(self, rtype: int, data: bytes) -> None:
        if self.fault_hook is not None:
            self.fault_hook("append", len(data))
        self._check(self._lib.walog_append(self._h, rtype, data, len(data)))

    def flush(self, sync: bool = True) -> int:
        if self.fault_hook is not None:
            self.fault_hook("fsync" if sync else "flush", 0)
        if sync and self._fsync_delay_s > 0:
            time.sleep(self._fsync_delay_s)  # slow-disk emulation (see top)
        rc = self._lib.walog_flush(self._h, 1 if sync else 0)
        self._check(rc)
        return rc

    def cut(self, meta: int) -> None:
        self._check(self._lib.walog_cut(self._h, meta))

    def release_before(self, meta: int) -> int:
        rc = self._lib.walog_release_before(self._h, meta)
        self._check(rc)
        return rc

    def tail_offset(self) -> int:
        return self._lib.walog_tail_offset(self._h)

    def tail_seq(self) -> int:
        return self._lib.walog_tail_seq(self._h)

    def last_sync_ns(self) -> int:
        return self._lib.walog_last_sync_ns(self._h)

    def sync_stats(self) -> Tuple[int, int]:
        """(total_syncs, total_sync_ns) for the fsync histogram."""
        return (
            self._lib.walog_total_syncs(self._h),
            self._lib.walog_total_sync_ns(self._h),
        )

    def close(self) -> None:
        if self._h:
            self._lib.walog_close(self._h)
            self._h = None

    def __enter__(self) -> "Walog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_all(dirpath: str, repair: bool = True) -> List[Tuple[int, bytes, int, int]]:
    """Validated records [(type, payload, seg_seq, seg_meta)] across all
    segments; truncates a torn tail when repair=True. Raises on
    corruption in non-tail segments."""
    lib = _lib()
    out: List[Tuple[int, bytes, int, int]] = []

    @_REC_CB
    def cb(_ctx, rtype, data, length, seq, meta):
        out.append((rtype, ctypes.string_at(data, length), seq, meta))

    err = ctypes.create_string_buffer(512)
    rc = lib.walog_read_all(
        dirpath.encode(), 1 if repair else 0, cb, None, err, len(err)
    )
    if rc < 0:
        raise WalogError(err.value.decode() or "walog_read_all failed")
    return out


# Tail-shape classification (walog_tail_state return values). The
# distinction matters for protocol-aware recovery (FAST'18): a CLEAN
# boundary proves only that no record was mid-write at the crash, while
# a TORN mid-record break proves bytes beyond the last whole record
# were destroyed — if the file's contents were fsync-acknowledged, that
# is lost durable data, a fault class raft's model does not cover.
TAIL_CLEAN, TAIL_TORN, TAIL_CORRUPT = 0, 1, 2
TAIL_NAMES = {TAIL_CLEAN: "clean", TAIL_TORN: "torn",
              TAIL_CORRUPT: "corrupt"}


def tail_state(dirpath: str) -> int:
    """Classify the LAST segment's tail: TAIL_CLEAN (ends exactly at a
    record boundary, chain valid), TAIL_TORN (ends inside a record —
    the mid-record CRC break / past-EOF shapes), or TAIL_CORRUPT (a
    complete record fails its crc). Call BEFORE read_all(repair=True):
    repair truncates the torn evidence back to a clean boundary."""
    lib = _lib()
    err = ctypes.create_string_buffer(512)
    rc = lib.walog_tail_state(dirpath.encode(), err, len(err))
    if rc < 0:
        raise WalogError(err.value.decode() or "walog_tail_state failed")
    return rc


def read_all_classified(
    dirpath: str, repair: bool = True,
) -> Tuple[List[Tuple[int, bytes, int, int]], int]:
    """read_all plus the tail classification taken BEFORE any repair:
    (records, TAIL_*). The recovery path (hosting._replay) uses the
    classification to distinguish a benign crash boundary from a
    mid-record break that destroyed bytes."""
    ts = tail_state(dirpath)
    return read_all(dirpath, repair=repair), ts


def segment_records(path: str) -> List[Tuple[int, int, int, int]]:
    """Frame-walk one segment file WITHOUT crc validation:
    [(offset, rtype, payload_len, padded_size)] for every complete
    record (the CRC-reset seed included). Tooling/test helper for
    locating record boundaries (e.g. to place a deterministic
    mid-record tear); stops at the first record running past EOF."""
    import struct as _struct

    out: List[Tuple[int, int, int, int]] = []
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off + 12 <= len(data):
        ln, rtype = _struct.unpack_from("<IB", data, off)
        padded = (12 + ln + 7) & ~7
        if off + padded > len(data):
            break
        out.append((off, rtype, ln, padded))
        off += padded
    return out


def verify(dirpath: str) -> bool:
    """Validate the whole chain without repairing (ref: wal.Verify
    wal.go:629). Returns True when every segment checks out."""
    try:
        read_all(dirpath, repair=False)
        return True
    except WalogError:
        return False


# -- at-rest corruption salvage (ISSUE 15) ------------------------------------
#
# The native reader treats a COMPLETE record failing its CRC as a hard
# error (walog.cc: "auto-truncating them would silently drop fsync'd
# raft entries") — correct as a default, but it leaves a bit-flipped
# at-rest record unbootable. The protocol-aware alternative (FAST'18):
# amputate the log at the first corrupt record, boot, and let the
# durable-watermark fence mark exactly the groups whose acked bytes the
# amputation destroyed (hosting._replay already does that for torn
# tails). salvage() is that amputation: a Python-side CRC32C chain walk
# that truncates the damaged segment at the last good record boundary
# and deletes every later segment, returning what it removed so the
# caller can log/fence honestly. It never runs implicitly — the boot
# path invokes it only after the native reader refused.

_CRC32C_TABLE: Optional[List[int]] = None


def _crc32c_table() -> List[int]:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else c >> 1
            tbl.append(c)
        _CRC32C_TABLE = tbl
    return _CRC32C_TABLE


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C (Castagnoli), matching walog.cc's chain function.
    Byte-at-a-time — recovery/tooling path only, never hot."""
    tbl = _crc32c_table()
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _is_torn_region(data: bytes, off: int, padded: int) -> bool:
    """Mirror of walog.cc is_torn_record: any >=8-byte disk-sector
    piece of the record region being all zeros means a torn
    preallocated-segment write, not at-rest corruption."""
    end = min(off + padded, len(data))
    pos = off
    while pos < end:
        piece_end = min((pos // 512 + 1) * 512, end)
        if piece_end - pos >= 8 and not any(data[pos:piece_end]):
            return True
        pos = piece_end
    return False


def scan_chain(dirpath: str) -> Optional[dict]:
    """Walk every segment's CRC chain Python-side; return the FIRST
    at-rest corruption found as {"segment", "path", "offset"(=last good
    boundary), "bad_record_off"} or None when the chain is clean/merely
    torn (torn tails are the native repair's job, not salvage's)."""
    import struct as _struct

    segs = sorted(f for f in os.listdir(dirpath) if f.endswith(".wal"))
    crc = 0
    chain_started = False
    for name in segs:
        path = os.path.join(dirpath, name)
        with open(path, "rb") as f:
            data = f.read()
        off = 0
        good = 0
        first = True
        while off + 12 <= len(data):
            ln, rtype = _struct.unpack_from("<IB", data, off)
            (rcrc,) = _struct.unpack_from("<I", data, off + 8)
            padded = (12 + ln + 7) & ~7
            if off + padded > len(data):
                return None  # torn tail: native repair handles it
            if first:
                if rtype != 0:  # missing CRC-reset seed record
                    return {"segment": name, "path": path,
                            "offset": 0, "bad_record_off": off}
                if not chain_started:
                    crc = rcrc
                    chain_started = True
                elif rcrc != crc:
                    # Chain mismatch across the segment boundary: the
                    # seed itself is the damaged record.
                    return {"segment": name, "path": path,
                            "offset": 0, "bad_record_off": off}
                first = False
            else:
                want = crc32c(data[off + 12:off + 12 + ln], crc)
                if want != rcrc:
                    if _is_torn_region(data, off, padded):
                        return None  # torn, not corrupt
                    return {"segment": name, "path": path,
                            "offset": good, "bad_record_off": off}
                crc = want
            off += padded
            good = off
    return None


def salvage(dirpath: str) -> Optional[dict]:
    """Amputate at-rest corruption: truncate the damaged segment at the
    last good record boundary and DELETE every later segment (their
    chain seeds no longer match). Returns
    {"segment", "truncated_at", "bytes_dropped", "removed_segments"}
    or None when the chain held no complete-record corruption. The
    caller owns the consequences: every fsync'd record at-or-beyond
    the cut is gone, and only a durable-watermark fence
    (hosting._replay) makes that loss protocol-visible instead of
    silent."""
    bad = scan_chain(dirpath)
    if bad is None:
        return None
    segs = sorted(f for f in os.listdir(dirpath) if f.endswith(".wal"))
    si = segs.index(bad["segment"])
    if bad["offset"] == 0 and si == 0:
        # The very first segment's SEED record is damaged: no valid
        # prefix exists at all. Refuse — truncating to zero bytes
        # would leave an unbootable husk after destroying the (intact)
        # later segments, and booting EMPTY would forget the member's
        # vote/term, re-opening double-vote windows. Total log loss is
        # operator territory (rejoin as a fresh member), not salvage's.
        return None
    later = segs[si + 1:]
    dropped = 0
    if bad["offset"] == 0:
        # A non-first segment's seed is the damaged record: nothing in
        # this segment survives, but the chain through the PREVIOUS
        # segment is whole — drop the damaged segment entirely (a
        # zero-byte truncation would fail walog_open's seed check) and
        # everything after it; the previous segment becomes the tail.
        later = [bad["segment"]] + later
    else:
        size = os.path.getsize(bad["path"])
        dropped += size - bad["offset"]
        os.truncate(bad["path"], bad["offset"])
        fd = os.open(bad["path"], os.O_RDWR)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    for name in later:
        p = os.path.join(dirpath, name)
        dropped += os.path.getsize(p)
        os.remove(p)
    # Make the amputation itself durable (file sizes + dir entries)
    # before anyone replays the survivor prefix.
    dfd = os.open(dirpath, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)
    return {
        "segment": bad["segment"],
        "truncated_at": bad["offset"],
        "bytes_dropped": dropped,
        "removed_segments": later,
    }
