// walog — segmented, CRC-chained, fsync'd append-only record log.
//
// The native core of the WAL (analog of the reference's
// server/storage/wal: wal.go Create/Open/ReadAll/Save/cut/sync,
// encoder/decoder framing, fileutil locking/preallocation). The Python
// facade (etcd_tpu/storage/wal.py) maps raft records onto this layer;
// this file owns everything that touches the filesystem:
//
//  * record framing: [u32 len][u8 type][u8 pad3][u32 crc] + payload,
//    padded to 8 bytes; crc is CRC32C chained across records *and*
//    segment boundaries (each segment opens with a CRC-reset record
//    carrying the running crc, like the reference's crcType records);
//  * segment files "%016llx-%016llx.wal" (seq, meta) preallocated to
//    segment_bytes; cut() rolls to the next seq;
//  * torn-tail recovery: read_all validates the chain and truncates the
//    LAST segment at the first bad/short record; corruption in earlier
//    segments is a hard error;
//  * dir-level advisory lock (flock) so two processes can't own a WAL;
//  * fdatasync with a last-sync-duration gauge for the fsync histogram.
//
// Exposed as a C ABI for ctypes.

#include <algorithm>
#include <cerrno>
#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <dirent.h>
#include <fcntl.h>
#include <sys/file.h>
#include <sys/stat.h>
#include <sys/time.h>
#include <ctime>
#include <unistd.h>

namespace {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, reflected 0x82F63B78), table-driven.
uint32_t kCrcTable[8][256];
bool kCrcInit = false;

void crc_init() {
  if (kCrcInit) return;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 8; s++)
      kCrcTable[s][i] =
          (kCrcTable[s - 1][i] >> 8) ^ kCrcTable[0][kCrcTable[s - 1][i] & 0xFF];
  kCrcInit = true;
}

uint32_t crc32c(uint32_t crc, const uint8_t* p, size_t n) {
  crc ^= 0xFFFFFFFFu;
  // slicing-by-8
  while (n >= 8) {
    uint32_t lo;
    uint32_t hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= crc;
    crc = kCrcTable[7][lo & 0xFF] ^ kCrcTable[6][(lo >> 8) & 0xFF] ^
          kCrcTable[5][(lo >> 16) & 0xFF] ^ kCrcTable[4][lo >> 24] ^
          kCrcTable[3][hi & 0xFF] ^ kCrcTable[2][(hi >> 8) & 0xFF] ^
          kCrcTable[1][(hi >> 16) & 0xFF] ^ kCrcTable[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = kCrcTable[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
constexpr uint8_t kTypeCrcReset = 0;  // internal: segment-start chain seed
constexpr size_t kHeader = 12;        // u32 len | u8 type | pad3 | u32 crc

uint64_t now_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return uint64_t(ts.tv_sec) * 1000000000ull + uint64_t(ts.tv_nsec);
}

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    snprintf(err, size_t(errlen), "%s", msg.c_str());
  }
}

// A complete-looking record that fails its crc in the tail segment may
// still be a torn write: preallocated segments are zero-filled, so a
// crash between header and payload flush leaves zero sectors inside the
// record region. If any 512-byte disk sector covered by the record is
// all zeros, classify as torn (repairable), else as corruption (ref:
// wal/decoder.go isTornEntry).
bool is_torn_record(const std::vector<uint8_t>& data, size_t off,
                    size_t padded) {
  size_t end = std::min(off + padded, data.size());
  size_t pos = off;
  while (pos < end) {
    size_t piece_end = std::min(((pos / 512) + 1) * 512, end);
    bool all_zero = true;
    for (size_t i = pos; i < piece_end; i++) {
      if (data[i] != 0) {
        all_zero = false;
        break;
      }
    }
    // Ignore sub-8-byte pieces: they can be legitimate record padding.
    if (all_zero && piece_end - pos >= 8) return true;
    pos = piece_end;
  }
  return false;
}

// Make directory entries durable (after create/rename/unlink) — without
// this a crash can lose a whole fdatasync'd segment file (ref:
// fileutil.Fsync on the parent dir in wal cut/create).
void fsync_dir(const std::string& dir) {
  int dfd = open(dir.c_str(), O_RDONLY | O_DIRECTORY);
  if (dfd >= 0) {
    fsync(dfd);
    close(dfd);
  }
}

std::string seg_name(uint64_t seq, uint64_t meta) {
  char buf[64];
  snprintf(buf, sizeof buf, "%016" PRIx64 "-%016" PRIx64 ".wal", seq, meta);
  return buf;
}

bool parse_seg_name(const char* name, uint64_t* seq, uint64_t* meta) {
  size_t len = strlen(name);
  if (len != 16 + 1 + 16 + 4) return false;
  if (strcmp(name + 33, ".wal") != 0 || name[16] != '-') return false;
  char* end = nullptr;
  *seq = strtoull(std::string(name, 16).c_str(), &end, 16);
  *meta = strtoull(std::string(name + 17, 16).c_str(), &end, 16);
  return true;
}

struct Segment {
  uint64_t seq;
  uint64_t meta;  // caller-defined (the Python layer stores a raft index)
  std::string path;
};

int list_segments(const std::string& dir, std::vector<Segment>* out,
                  std::string* errmsg) {
  DIR* d = opendir(dir.c_str());
  if (!d) {
    *errmsg = "opendir " + dir + ": " + strerror(errno);
    return -1;
  }
  out->clear();
  while (struct dirent* de = readdir(d)) {
    uint64_t seq, meta;
    if (parse_seg_name(de->d_name, &seq, &meta))
      out->push_back({seq, meta, dir + "/" + de->d_name});
  }
  closedir(d);
  std::sort(out->begin(), out->end(),
            [](const Segment& a, const Segment& b) { return a.seq < b.seq; });
  for (size_t i = 0; i + 1 < out->size(); i++) {
    if ((*out)[i].seq + 1 != (*out)[i + 1].seq) {
      *errmsg = "wal segments not sequential at seq " +
                std::to_string((*out)[i].seq);
      return -1;
    }
  }
  return 0;
}

struct Walog {
  std::string dir;
  uint64_t segment_bytes;
  int lock_fd = -1;
  int fd = -1;          // current (tail) segment
  uint64_t seq = 0;     // current segment seq
  uint64_t offset = 0;  // write offset in current segment
  uint32_t crc = 0;     // running chain crc
  uint64_t last_sync_ns = 0;
  uint64_t total_syncs = 0;
  uint64_t total_sync_ns = 0;
  std::vector<uint8_t> buf;  // pending (unflushed) bytes
  std::string err;
};

int write_all(int fd, const uint8_t* p, size_t n) {
  while (n > 0) {
    ssize_t w = write(fd, p, n);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -1;
    }
    p += w;
    n -= size_t(w);
  }
  return 0;
}

// Append one framed record to w->buf (not yet written to the fd).
void frame_record(Walog* w, uint8_t type, const uint8_t* data, uint64_t len) {
  w->crc = crc32c(w->crc, data, size_t(len));
  uint8_t hdr[kHeader] = {0};
  uint32_t len32 = uint32_t(len);
  memcpy(hdr, &len32, 4);
  hdr[4] = type;
  memcpy(hdr + 8, &w->crc, 4);
  w->buf.insert(w->buf.end(), hdr, hdr + kHeader);
  w->buf.insert(w->buf.end(), data, data + len);
  size_t pad = (8 - ((kHeader + len) & 7)) & 7;
  w->buf.insert(w->buf.end(), pad, 0);
}

int flush_buf(Walog* w) {
  if (w->buf.empty()) return 0;
  if (write_all(w->fd, w->buf.data(), w->buf.size()) != 0) {
    w->err = std::string("write: ") + strerror(errno);
    return -1;
  }
  w->offset += w->buf.size();
  w->buf.clear();
  return 0;
}

// Open a fresh segment file `seq` and seed it with a CRC-reset record.
int open_segment(Walog* w, uint64_t seq, uint64_t meta) {
  std::string tmp = w->dir + "/." + seg_name(seq, meta) + ".tmp";
  int fd = open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) {
    w->err = "create " + tmp + ": " + strerror(errno);
    return -1;
  }
  // Preallocate so appends don't grow file metadata on every sync
  // (ref: fileutil.Preallocate, wal.go cut path).
  if (w->segment_bytes > 0) {
    if (posix_fallocate(fd, 0, off_t(w->segment_bytes)) != 0) {
      // Not fatal: some filesystems don't support it.
    }
    if (ftruncate(fd, 0) != 0) { /* keep blocks, zero length */
    }
  }
  std::string path = w->dir + "/" + seg_name(seq, meta);
  if (rename(tmp.c_str(), path.c_str()) != 0) {
    w->err = "rename " + path + ": " + strerror(errno);
    close(fd);
    return -1;
  }
  fsync_dir(w->dir);
  if (w->fd >= 0) {
    // Durable hand-off: sync the previous tail before switching.
    fdatasync(w->fd);
    close(w->fd);
  }
  w->fd = fd;
  w->seq = seq;
  w->offset = 0;
  // Chain seed record: payload is empty; stored crc = running crc.
  frame_record(w, kTypeCrcReset, nullptr, 0);
  if (flush_buf(w) != 0) return -1;
  return 0;
}

}  // namespace

extern "C" {

typedef void (*walog_rec_cb)(void* ctx, int type, const uint8_t* data,
                             uint64_t len, uint64_t seg_seq, uint64_t seg_meta);

// Create a new WAL dir (must not already contain segments) or open the
// existing one positioned for appends at the tail. Returns NULL on error.
void* walog_open(const char* dir_c, uint64_t segment_bytes, int create,
                 char* err, int errlen) {
  crc_init();
  auto* w = new Walog();
  w->dir = dir_c;
  w->segment_bytes = segment_bytes;

  if (create) {
    if (mkdir(dir_c, 0700) != 0 && errno != EEXIST) {
      set_err(err, errlen, std::string("mkdir: ") + strerror(errno));
      delete w;
      return nullptr;
    }
  }
  std::string lock_path = w->dir + "/wal.lock";
  w->lock_fd = open(lock_path.c_str(), O_WRONLY | O_CREAT, 0600);
  if (w->lock_fd < 0 || flock(w->lock_fd, LOCK_EX | LOCK_NB) != 0) {
    set_err(err, errlen, "wal dir locked by another process");
    if (w->lock_fd >= 0) close(w->lock_fd);
    delete w;
    return nullptr;
  }

  std::vector<Segment> segs;
  std::string emsg;
  if (list_segments(w->dir, &segs, &emsg) != 0) {
    set_err(err, errlen, emsg);
    close(w->lock_fd);
    delete w;
    return nullptr;
  }
  if (create) {
    if (!segs.empty()) {
      set_err(err, errlen, "wal dir not empty");
      close(w->lock_fd);
      delete w;
      return nullptr;
    }
    if (open_segment(w, 0, 0) != 0) {
      set_err(err, errlen, w->err);
      close(w->lock_fd);
      delete w;
      return nullptr;
    }
    return w;
  }
  if (segs.empty()) {
    set_err(err, errlen, "no wal segments");
    close(w->lock_fd);
    delete w;
    return nullptr;
  }
  // Position at the tail: replay the last segment's chain to recover the
  // running crc and append offset (read_all has already truncated torn
  // tails if the caller ran it first — we re-validate here regardless).
  const Segment& tail = segs.back();
  int fd = open(tail.path.c_str(), O_RDWR);
  if (fd < 0) {
    set_err(err, errlen, std::string("open tail: ") + strerror(errno));
    close(w->lock_fd);
    delete w;
    return nullptr;
  }
  struct stat st;
  fstat(fd, &st);
  std::vector<uint8_t> data(size_t(st.st_size));
  ssize_t rd = pread(fd, data.data(), data.size(), 0);
  if (rd < 0) {
    set_err(err, errlen, std::string("pread: ") + strerror(errno));
    close(fd);
    close(w->lock_fd);
    delete w;
    return nullptr;
  }
  data.resize(size_t(rd));
  // Recover the chain crc entering this segment from its seed record.
  // A record running past EOF is a torn tail (truncate); a COMPLETE
  // record failing its crc is corruption (refuse to open — see the
  // rationale in walog_read_all).
  size_t off = 0;
  uint32_t crc = 0;
  bool first = true;
  bool corrupt = false;
  size_t good = 0;
  while (off + kHeader <= data.size()) {
    uint32_t len32, rcrc;
    memcpy(&len32, &data[off], 4);
    uint8_t type = data[off + 4];
    memcpy(&rcrc, &data[off + 8], 4);
    size_t total = kHeader + len32;
    size_t padded = (total + 7) & ~size_t(7);
    if (off + padded > data.size()) break;  // torn tail
    if (first) {
      if (type != kTypeCrcReset) {
        corrupt = true;
        break;
      }
      crc = rcrc;  // seed
      first = false;
    } else {
      uint32_t want = crc32c(crc, &data[off + kHeader], len32);
      if (want != rcrc) {
        if (is_torn_record(data, off, padded))
          break;  // torn: truncate below
        corrupt = true;
        break;
      }
      crc = want;
    }
    off += padded;
    good = off;
  }
  if (good == 0 || corrupt) {
    set_err(err, errlen, corrupt
                             ? "corruption in tail segment " + tail.path
                             : "tail segment has no valid seed record");
    close(fd);
    close(w->lock_fd);
    delete w;
    return nullptr;
  }
  if (good < data.size()) {
    if (ftruncate(fd, off_t(good)) != 0) {
      set_err(err, errlen, std::string("truncate tail: ") + strerror(errno));
      close(fd);
      close(w->lock_fd);
      delete w;
      return nullptr;
    }
  }
  lseek(fd, off_t(good), SEEK_SET);
  w->fd = fd;
  w->seq = tail.seq;
  w->offset = good;
  w->crc = crc;
  return w;
}

const char* walog_errmsg(void* wp) { return static_cast<Walog*>(wp)->err.c_str(); }

int walog_append(void* wp, int type, const uint8_t* data, uint64_t len) {
  auto* w = static_cast<Walog*>(wp);
  if (type <= 0 || type > 255) {
    w->err = "record type must be 1..255";
    return -1;
  }
  frame_record(w, uint8_t(type), data, len);
  return 0;
}

// Flush buffered records to the fd; optionally fdatasync. Returns bytes
// in the tail segment, or -1.
int64_t walog_flush(void* wp, int sync) {
  auto* w = static_cast<Walog*>(wp);
  if (flush_buf(w) != 0) return -1;
  if (sync) {
    uint64_t t0 = now_ns();
    if (fdatasync(w->fd) != 0) {
      w->err = std::string("fdatasync: ") + strerror(errno);
      return -1;
    }
    w->last_sync_ns = now_ns() - t0;
    w->total_syncs++;
    w->total_sync_ns += w->last_sync_ns;
  }
  return int64_t(w->offset);
}

// Roll to a new segment whose name carries `meta` (the Python layer
// passes last_index+1). Implies flush+sync of the old tail.
int walog_cut(void* wp, uint64_t meta) {
  auto* w = static_cast<Walog*>(wp);
  if (flush_buf(w) != 0) return -1;
  return open_segment(w, w->seq + 1, meta);
}

uint64_t walog_tail_offset(void* wp) { return static_cast<Walog*>(wp)->offset; }
uint64_t walog_tail_seq(void* wp) { return static_cast<Walog*>(wp)->seq; }
uint64_t walog_last_sync_ns(void* wp) { return static_cast<Walog*>(wp)->last_sync_ns; }
uint64_t walog_total_syncs(void* wp) { return static_cast<Walog*>(wp)->total_syncs; }
uint64_t walog_total_sync_ns(void* wp) { return static_cast<Walog*>(wp)->total_sync_ns; }

// Delete segments strictly older than the one containing `meta`
// boundaries: keep the newest segment whose meta <= given meta, drop all
// before it (ref: wal.ReleaseLockTo semantics over file locks — here we
// reclaim space directly).
int walog_release_before(void* wp, uint64_t meta) {
  auto* w = static_cast<Walog*>(wp);
  std::vector<Segment> segs;
  std::string emsg;
  if (list_segments(w->dir, &segs, &emsg) != 0) {
    w->err = emsg;
    return -1;
  }
  // Find the last segment with seg.meta <= meta; everything before it
  // can go.
  size_t keep_from = 0;
  for (size_t i = 0; i < segs.size(); i++)
    if (segs[i].meta <= meta) keep_from = i;
  for (size_t i = 0; i < keep_from; i++) unlink(segs[i].path.c_str());
  if (keep_from > 0) fsync_dir(w->dir);
  return int(keep_from);
}

// Classify the shape of the LAST segment's tail WITHOUT repairing —
// the protocol-aware recovery detector (ref: "Protocol-Aware Recovery
// for Consensus-Based Storage", FAST'18: lost durable data must be
// treated as a distinct fault, not silently truncated away). Call
// BEFORE walog_read_all(repair=1): repair truncates the evidence.
//
// Return codes (keep in sync with walog.py TAIL_*):
//   0 = clean: the segment ends exactly at a record boundary with a
//       valid chain — either nothing was being written at the crash,
//       or fsync'd whole records were sheared off at a boundary (which
//       only a higher-level durability watermark can detect);
//   1 = torn: the tail ends INSIDE a record — a header or payload
//       running past EOF, a zero-sector torn write, or sub-header
//       garbage. Bytes beyond the last whole record are gone;
//   2 = corrupt: a complete record fails its crc (non-repairable;
//       walog_read_all refuses these too);
//  <0 = error (err filled in).
int walog_tail_state(const char* dir_c, char* err, int errlen) {
  crc_init();
  std::vector<Segment> segs;
  std::string emsg;
  if (list_segments(dir_c, &segs, &emsg) != 0) {
    set_err(err, errlen, emsg);
    return -1;
  }
  if (segs.empty()) {
    set_err(err, errlen, "no wal segments");
    return -1;
  }
  const Segment& tail = segs.back();
  int fd = open(tail.path.c_str(), O_RDONLY);
  if (fd < 0) {
    set_err(err, errlen, "open " + tail.path + ": " + strerror(errno));
    return -1;
  }
  struct stat st;
  fstat(fd, &st);
  std::vector<uint8_t> data(size_t(st.st_size));
  ssize_t rd = pread(fd, data.data(), data.size(), 0);
  close(fd);
  if (rd < 0) {
    set_err(err, errlen, std::string("pread: ") + strerror(errno));
    return -1;
  }
  data.resize(size_t(rd));
  // Validate the segment standalone: the seed record carries the chain
  // crc entering this segment, so the per-record checks need no
  // earlier segments.
  size_t off = 0;
  uint32_t crc = 0;
  bool first = true;
  while (off + kHeader <= data.size()) {
    uint32_t len32, rcrc;
    memcpy(&len32, &data[off], 4);
    uint8_t type = data[off + 4];
    memcpy(&rcrc, &data[off + 8], 4);
    size_t total = kHeader + len32;
    size_t padded = (total + 7) & ~size_t(7);
    if (off + padded > data.size()) return 1;  // record past EOF: torn
    if (first) {
      if (type != kTypeCrcReset) return 2;
      crc = rcrc;
      first = false;
    } else {
      uint32_t want = crc32c(crc, &data[off + kHeader], len32);
      if (want != rcrc) return is_torn_record(data, off, padded) ? 1 : 2;
      crc = want;
    }
    off += padded;
  }
  if (off < data.size()) return 1;  // sub-header tail garbage: torn
  if (first) return 1;  // no complete seed record survived
  return 0;
}

// Stream every record of every segment (in order) through cb, after
// validating the crc chain. Torn tails in the LAST segment are truncated
// (repair=1) or reported as the stop point; corruption elsewhere is an
// error. Standalone — does not require an open handle (used by Verify
// and by ReadAll-before-open).
int walog_read_all(const char* dir_c, int repair, walog_rec_cb cb, void* ctx,
                   char* err, int errlen) {
  crc_init();
  std::vector<Segment> segs;
  std::string emsg;
  if (list_segments(dir_c, &segs, &emsg) != 0) {
    set_err(err, errlen, emsg);
    return -1;
  }
  uint32_t crc = 0;
  bool chain_started = false;
  for (size_t si = 0; si < segs.size(); si++) {
    const bool last = si + 1 == segs.size();
    int fd = open(segs[si].path.c_str(), repair && last ? O_RDWR : O_RDONLY);
    if (fd < 0) {
      set_err(err, errlen, "open " + segs[si].path + ": " + strerror(errno));
      return -1;
    }
    struct stat st;
    fstat(fd, &st);
    std::vector<uint8_t> data(size_t(st.st_size));
    ssize_t rd = pread(fd, data.data(), data.size(), 0);
    if (rd < 0) {
      set_err(err, errlen, std::string("pread: ") + strerror(errno));
      close(fd);
      return -1;
    }
    data.resize(size_t(rd));
    size_t off = 0, good = 0;
    bool torn = false;     // record runs past EOF — normal after a crash
    bool corrupt = false;  // complete record fails its crc — real damage
    bool first = true;
    while (off + kHeader <= data.size()) {
      uint32_t len32, rcrc;
      memcpy(&len32, &data[off], 4);
      uint8_t type = data[off + 4];
      memcpy(&rcrc, &data[off + 8], 4);
      size_t total = kHeader + len32;
      size_t padded = (total + 7) & ~size_t(7);
      if (off + padded > data.size()) {
        torn = true;
        break;
      }
      if (first) {
        if (type != kTypeCrcReset) {
          corrupt = true;
          break;
        }
        if (!chain_started) {
          crc = rcrc;  // very first segment seeds the chain
          chain_started = true;
        } else if (rcrc != crc) {
          corrupt = true;  // chain mismatch across segment boundary
          break;
        }
        first = false;
      } else {
        uint32_t want = crc32c(crc, &data[off + kHeader], len32);
        if (want != rcrc) {
          if (is_torn_record(data, off, padded))
            torn = true;
          else
            corrupt = true;
          break;
        }
        crc = want;
        if (cb) cb(ctx, type, &data[off + kHeader], len32, segs[si].seq,
                   segs[si].meta);
      }
      off += padded;
      good = off;
    }
    if (off < data.size() && !corrupt) torn = true;  // sub-header tail garbage
    if (torn || corrupt) {
      if (!last || corrupt) {
        // Non-tail damage is always fatal, and so is a failed crc on a
        // COMPLETE record anywhere — those bytes were acknowledged as
        // durable, so auto-truncating them would silently drop
        // fsync'd raft entries. Only a torn tail (record running past
        // EOF — a crash mid-write) is benign and repairable (ref:
        // wal.Repair handling only io.ErrUnexpectedEOF).
        set_err(err, errlen, "corruption in segment " + segs[si].path);
        close(fd);
        return -1;
      }
      if (repair) {
        if (ftruncate(fd, off_t(good)) != 0) {
          set_err(err, errlen,
                  std::string("truncate tail: ") + strerror(errno));
          close(fd);
          return -1;
        }
        fdatasync(fd);
      }
    }
    close(fd);
  }
  return int(segs.size());
}

void walog_close(void* wp) {
  auto* w = static_cast<Walog*>(wp);
  if (w->fd >= 0) {
    flush_buf(w);
    fdatasync(w->fd);
    close(w->fd);
  }
  if (w->lock_fd >= 0) {
    flock(w->lock_fd, LOCK_UN);
    close(w->lock_fd);
  }
  delete w;
}

}  // extern "C"
