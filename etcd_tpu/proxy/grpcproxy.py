"""Caching/coalescing L7 proxy (ref: server/proxy/grpcproxy/:
kv.go request cache, watch.go + watch_broadcast.go coalescing,
lease.go keepalive forwarding, cluster.go/maintenance.go passthrough).

Speaks the same framed-RPC wire protocol as V3RPCServer, so clients
point at the proxy unchanged. Backed by one upstream ``Client`` (which
already does endpoint failover):

* **serializable Range cache** — responses keyed by the request shape,
  invalidated on writes through the proxy and on compaction
  (grpcproxy/kv.go:44-103, cache/store.go);
* **watch coalescing** — one upstream watch per (key, range_end) fans
  out to every proxy-side watcher that joined at "current" (start_rev
  0); historical watchers get a dedicated upstream watch
  (watch_broadcast.go);
* everything else forwards.
"""

from __future__ import annotations

import socket
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..client.client import Client, ClientError
from ..v3rpc import wire
from ..v3rpc.connbase import FramedServerConn

DEFAULT_CACHE_ENTRIES = 2048  # ref: cache/store.go DefaultMaxEntries


class _RangeCache:
    """LRU of serializable range responses (ref: cache/store.go)."""

    def __init__(self, max_entries: int = DEFAULT_CACHE_ENTRIES) -> None:
        self._lock = threading.Lock()
        self._od: "OrderedDict[str, Dict]" = OrderedDict()
        self.max_entries = max_entries
        self.compact_rev = 0
        self.hits = 0
        self.misses = 0
        self.gen = 0  # bumped on invalidate; stale fetches don't re-insert

    @staticmethod
    def _key(params: Dict) -> str:
        return "|".join(
            f"{k}={params.get(k)}"
            for k in sorted(
                ("key", "range_end", "limit", "revision", "sort_order",
                 "sort_target", "count_only", "keys_only",
                 "min_mod_revision", "max_mod_revision",
                 "min_create_revision", "max_create_revision")
            )
        )

    def get(self, params: Dict) -> Optional[Dict]:
        rev = params.get("revision", 0) or 0
        with self._lock:
            if 0 < rev < self.compact_rev:
                return None  # compacted: let the server answer with the error
            k = self._key(params)
            resp = self._od.get(k)
            if resp is None:
                self.misses += 1
                return None
            self._od.move_to_end(k)
            self.hits += 1
            return resp

    def put(self, params: Dict, resp: Dict, gen: int) -> None:
        """Insert only if no invalidation happened since `gen` was read
        (a concurrent write may have made this response stale)."""
        rev = params.get("revision", 0) or 0
        with self._lock:
            if gen != self.gen:
                return
            if 0 < rev < self.compact_rev:
                return
            k = self._key(params)
            self._od[k] = resp
            self._od.move_to_end(k)
            while len(self._od) > self.max_entries:
                self._od.popitem(last=False)

    def invalidate(self) -> None:
        # The reference invalidates by key interval (cache.Invalidate);
        # dropping everything is strictly safer and keeps this host-side
        # path simple.
        with self._lock:
            self.gen += 1
            self._od.clear()

    def compacted(self, rev: int) -> None:
        with self._lock:
            self.gen += 1
            self.compact_rev = max(self.compact_rev, rev)
            self._od.clear()


class _Broadcast:
    """One upstream watch fanned out to many proxy watchers
    (ref: watch_broadcast.go)."""

    def __init__(self, proxy: "GrpcProxy", key: bytes,
                 end: Optional[bytes]) -> None:
        self.proxy = proxy
        self.handle = proxy.client.watch(key, end)
        self.subs: Dict[Tuple[int, int], "_ProxyConn"] = {}  # (conn_id, wid)
        self.lock = threading.Lock()
        self.stopped = False
        self.thread = threading.Thread(target=self._pump, daemon=True)
        self.thread.start()

    def add(self, conn: "_ProxyConn", wid: int) -> None:
        with self.lock:
            self.subs[(id(conn), wid)] = conn

    def remove(self, conn: "_ProxyConn", wid: int) -> bool:
        """Returns True when the broadcast became empty."""
        with self.lock:
            self.subs.pop((id(conn), wid), None)
            return not self.subs

    def stop(self) -> None:
        self.stopped = True
        self.handle.cancel()

    def _pump(self) -> None:
        while not self.stopped and not self.proxy._stopped.is_set():
            got = self.handle.get(timeout=0.2)
            if got is None:
                continue
            rev, events = got
            with self.lock:
                subs = list(self.subs.items())
            for (cid, wid), conn in subs:
                if not conn.push_event(wid, rev, events):
                    # Dead or stalled downstream (send timed out): drop
                    # this subscriber so others keep receiving.
                    self.proxy.release_broadcast(
                        conn=conn, wid=wid, key=None, end=None, bcast=self
                    )


class GrpcProxy:
    def __init__(
        self,
        endpoints: List[Tuple[str, int]],
        bind: Tuple[str, int] = ("127.0.0.1", 0),
    ) -> None:
        self.client = Client(endpoints)
        self.cache = _RangeCache()
        self._stopped = threading.Event()
        self._bcasts: Dict[Tuple[bytes, Optional[bytes]], _Broadcast] = {}
        self._bcast_lock = threading.Lock()
        self._conns: set = set()

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(bind)
        self._listener.listen(128)
        self.addr = self._listener.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def stop(self) -> None:
        self._stopped.set()
        with self._bcast_lock:
            for b in self._bcasts.values():
                b.stop()
            self._bcasts.clear()
        for s in (self._listener, *list(self._conns)):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self.client.close()

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._conns.add(conn)
            _ProxyConn(self, conn)

    # -- broadcast registry ----------------------------------------------------

    def broadcast_join(self, key: bytes, end: Optional[bytes],
                       conn: "_ProxyConn", wid: int) -> _Broadcast:
        """Get-or-create the broadcast AND subscribe under one lock, so
        a concurrent last-watcher teardown can't stop it in between."""
        with self._bcast_lock:
            b = self._bcasts.get((key, end))
            if b is None or b.stopped:
                b = _Broadcast(self, key, end)
                self._bcasts[(key, end)] = b
            b.add(conn, wid)
            return b

    def release_broadcast(self, key: Optional[bytes], end: Optional[bytes],
                          conn: "_ProxyConn", wid: int,
                          bcast: Optional[_Broadcast] = None) -> None:
        with self._bcast_lock:
            if bcast is not None:
                b = bcast
                keys = [k for k, v in self._bcasts.items() if v is b]
                key_tuple = keys[0] if keys else None
            else:
                key_tuple = (key, end)
                b = self._bcasts.get(key_tuple)
            if b is not None and b.remove(conn, wid):
                b.stop()
                if key_tuple is not None:
                    self._bcasts.pop(key_tuple, None)


class _ProxyConn(FramedServerConn):
    """One downstream client connection."""

    SEND_TIMEOUT_S = 5  # stalled-watcher bound: sendall fails after this

    def __init__(self, proxy: GrpcProxy, sock: socket.socket) -> None:
        self.p = proxy
        self._wstate = threading.Lock()  # guards watch bookkeeping below
        self._next_wid = 0
        self._wlocal: Dict[int, Tuple[bytes, Optional[bytes], Any]] = {}
        self._ready_wids: set = set()  # create response on the wire
        self._buffered: Dict[int, list] = {}  # wid -> [(rev, events)]
        import struct as _struct

        try:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                _struct.pack("ll", self.SEND_TIMEOUT_S, 0),
            )
        except OSError:
            pass
        super().__init__(sock, proxy._stopped)

    def push_event(self, wid: int, revision: int, events) -> bool:
        # Until the WatchCreate response is on the wire the client can't
        # route this wid — buffer instead of dropping (flushed by
        # after_send).
        with self._wstate:
            if wid in self._wlocal and wid not in self._ready_wids:
                self._buffered.setdefault(wid, []).append((revision, events))
                return True
        return self.send_frame({
            "stream": wid,
            "event": {
                "revision": revision,
                "events": [wire.enc_event(ev) for ev in events],
            },
        })

    def encode_error(self, e: Exception) -> Dict[str, str]:
        if isinstance(e, ClientError):
            return {"type": e.etype, "msg": e.msg}
        return super().encode_error(e)

    def on_close(self) -> None:
        with self._wstate:
            wids = list(self._wlocal)
        for wid in wids:
            self._cancel_watch(wid)
        self.p._conns.discard(self.sock)

    def after_send(self, method: str, params: Dict, result: Any) -> None:
        # The create response is on the wire: flush anything buffered
        # while the client couldn't route this wid yet.
        if method != "WatchCreate":
            return
        wid = result.get("watch_id")
        # Drain-then-mark-ready loop: concurrent pumps keep buffering
        # until the buffer is empty, so event order is preserved.
        while True:
            with self._wstate:
                pending = self._buffered.pop(wid, [])
                if not pending:
                    self._ready_wids.add(wid)
                    return
            for revision, events in pending:
                self.send_frame({
                    "stream": wid,
                    "event": {
                        "revision": revision,
                        "events": [wire.enc_event(ev) for ev in events],
                    },
                })

    def dispatch(self, method: str, params: Dict,
                 token: Optional[str] = None) -> Any:
        return self._dispatch(method, params, token)

    # -- dispatch --------------------------------------------------------------

    def _dispatch(self, method: str, params: Dict,
                  token: Optional[str] = None) -> Any:
        p = self.p
        if method == "Range" and params.get("serializable") and token is None:
            # Auth'd requests bypass the shared cache (per-user
            # permissions must not leak across callers).
            cached = p.cache.get(params)
            if cached is not None:
                return cached
            gen = p.cache.gen
            resp = p.client._request("Range", params)
            p.cache.put(params, resp, gen)
            return resp
        if method in ("Put", "DeleteRange", "Txn"):
            resp = p.client._request(method, params, token=token)
            p.cache.invalidate()
            return resp
        if method == "Compact":
            resp = p.client._request(method, params, token=token)
            p.cache.compacted(params.get("revision", 0))
            return resp
        if method == "WatchCreate":
            return self._watch_create(params)
        if method == "WatchCancel":
            self._cancel_watch(params.get("watch_id", -1))
            return {"canceled": True}
        # Lease/Cluster/Maintenance/Auth passthrough.
        return p.client._request(method, params, token=token)

    # -- watch coalescing ------------------------------------------------------

    def _watch_create(self, params: Dict) -> Dict:
        key = bytes.fromhex(params["key"])
        end_hex = params.get("range_end", "")
        end = bytes.fromhex(end_hex) if end_hex else None
        start_rev = params.get("start_revision", 0)
        with self._wstate:
            wid = self._next_wid
            self._next_wid += 1
        if start_rev == 0:
            with self._wstate:
                self._wlocal[wid] = (key, end, None)
            # Join NOW — no event gap; deliveries buffer until the
            # create response frame goes out (push_event).
            self.p.broadcast_join(key, end, self, wid)
        else:
            h = self.p.client.watch(key, end, start_rev=start_rev)
            with self._wstate:
                self._wlocal[wid] = (key, end, h)
            threading.Thread(
                target=self._dedicated_pump, args=(wid, h), daemon=True
            ).start()
        return {"watch_id": wid, "revision": 0}

    def _dedicated_pump(self, wid: int, h) -> None:
        while not self.p._stopped.is_set() and wid in self._wlocal:
            got = h.get(timeout=0.2)
            if got is None:
                continue
            rev, events = got
            if not self.push_event(wid, rev, events):
                return

    def _cancel_watch(self, wid: int) -> None:
        with self._wstate:
            ent = self._wlocal.pop(wid, None)
            self._ready_wids.discard(wid)
            self._buffered.pop(wid, None)
        if ent is None:
            return
        key, end, dedicated = ent
        if dedicated is not None:
            dedicated.cancel()
        else:
            self.p.release_broadcast(key, end, self, wid)


def start_grpc_proxy(
    endpoints: List[Tuple[str, int]],
    bind: Tuple[str, int] = ("127.0.0.1", 0),
) -> GrpcProxy:
    return GrpcProxy(endpoints, bind)
