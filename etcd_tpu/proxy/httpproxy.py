"""v2 HTTP reverse proxy (ref: server/proxy/httpproxy — the legacy
mode started by `etcd --proxy on`): forwards /v2/* to cluster members,
failing over to the next endpoint only while the request has not been
sent (a replayed non-idempotent v2 write could double-apply)."""

from __future__ import annotations

import http.client
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Tuple

_HOP_HEADERS = {
    "connection", "keep-alive", "proxy-authenticate",
    "proxy-authorization", "te", "trailers", "transfer-encoding",
    "upgrade", "host", "content-length",
}


class HTTPProxy:
    """Forwarding proxy for the v2 REST surface; a failed endpoint is
    rotated out of first position (ref: proxy/httpproxy/proxy.go +
    director.go)."""

    def __init__(self, endpoints: List[Tuple[str, int]],
                 bind: Tuple[str, int] = ("127.0.0.1", 0)):
        if not endpoints:
            raise ValueError("no endpoints")
        self.endpoints = list(endpoints)
        self._i = 0
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _fwd(self):
                outer._forward(self)

            do_GET = do_PUT = do_POST = do_DELETE = _fwd

        self.httpd = ThreadingHTTPServer(bind, Handler)
        self.addr = self.httpd.server_address
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)

    def _forward(self, h: BaseHTTPRequestHandler) -> None:
        ln = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(ln) if ln else None
        headers = {k: v for k, v in h.headers.items()
                   if k.lower() not in _HOP_HEADERS}
        with self._lock:
            order = [self.endpoints[(self._i + j) % len(self.endpoints)]
                     for j in range(len(self.endpoints))]
        last_err = None
        for host, port in order:
            conn = http.client.HTTPConnection(host, port, timeout=10)
            try:
                # Connect-phase failures fail over; anything after the
                # request is on the wire must NOT be replayed (the v2
                # surface carries non-idempotent writes).
                conn.connect()
            except OSError as e:
                last_err = e
                conn.close()
                with self._lock:
                    self._i = (self._i + 1) % len(self.endpoints)
                continue
            try:
                conn.request(h.command, h.path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (OSError, http.client.HTTPException) as e:
                conn.close()
                try:
                    h.send_error(502, f"upstream failed mid-request: {e}")
                except OSError:
                    pass
                return
            conn.close()
            try:
                h.send_response(resp.status)
                for k, v in resp.getheaders():
                    if k.lower() not in _HOP_HEADERS:
                        h.send_header(k, v)
                h.send_header("Content-Length", str(len(payload)))
                h.end_headers()
                h.wfile.write(payload)
            except OSError:
                pass
            return
        try:
            h.send_error(502, f"no endpoint reachable: {last_err}")
        except OSError:
            pass
