"""Userspace L4 forwarder: the `etcd gateway`
(ref: server/proxy/tcpproxy/userspace.go, etcdmain/gateway.go).

Each accepted connection is forwarded whole to one backend endpoint,
picked round-robin over the healthy set. A dial failure marks the
endpoint inactive for ``monitor_interval`` and the dial retries the
next endpoint (userspace.go remote.inactivate/tryReactivate).
"""

from __future__ import annotations

import socket
import threading
import time
from typing import List, Optional, Tuple


class _Remote(object):
    def __init__(self, addr: Tuple[str, int]) -> None:
        self.addr = addr
        self.active = True
        self.deactivated_at = 0.0

    def inactivate(self) -> None:
        self.active = False
        self.deactivated_at = time.monotonic()


class TCPProxy:
    def __init__(
        self,
        endpoints: List[Tuple[str, int]],
        bind: Tuple[str, int] = ("127.0.0.1", 0),
        monitor_interval: float = 5.0,
    ) -> None:
        if not endpoints:
            raise ValueError("no endpoints")
        self.remotes = [_Remote(tuple(ep)) for ep in endpoints]
        self.monitor_interval = monitor_interval
        self._next = 0
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(bind)
        self._listener.listen(128)
        self.addr = self._listener.getsockname()
        self._threads = [threading.Thread(target=self._accept_loop, daemon=True)]
        self._threads[0].start()

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass

    # -- internals -------------------------------------------------------------

    def _pick(self) -> Optional[_Remote]:
        """Round-robin over active remotes; reactivate expired ones
        (userspace.go pick + tryReactivate)."""
        with self._lock:
            now = time.monotonic()
            for r in self.remotes:
                if not r.active and now - r.deactivated_at > self.monitor_interval:
                    r.active = True
            actives = [r for r in self.remotes if r.active]
            if not actives:
                return None
            r = actives[self._next % len(actives)]
            self._next += 1
            return r

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve, args=(conn,), daemon=True
            ).start()

    def _serve(self, conn: socket.socket) -> None:
        out: Optional[socket.socket] = None
        for _ in range(len(self.remotes)):
            r = self._pick()
            if r is None:
                break
            try:
                out = socket.create_connection(r.addr, timeout=2.0)
                break
            except OSError:
                r.inactivate()
                out = None
        if out is None:
            try:
                conn.close()
            except OSError:
                pass
            return
        t1 = threading.Thread(target=self._pipe, args=(conn, out), daemon=True)
        t2 = threading.Thread(target=self._pipe, args=(out, conn), daemon=True)
        t1.start()
        t2.start()

    @staticmethod
    def _pipe(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
