"""Proxies (ref: server/proxy/): tcpproxy (the `etcd gateway` L4
forwarder) and grpcproxy (the caching/coalescing L7 proxy)."""
