"""The legacy v2 HTTP API (ref: server/etcdserver/api/v2http/client.go —
keysHandler serveKeys, /v2/keys REST semantics).

Request grammar (client.go parseKeyRequest):

    GET    /v2/keys/foo?recursive=&sorted=&wait=&waitIndex=
    PUT    /v2/keys/foo  value=&ttl=&dir=&prevValue=&prevIndex=&prevExist=
    POST   /v2/keys/foo  value=&ttl=           (in-order unique create)
    DELETE /v2/keys/foo  ?recursive=&dir=&prevValue=&prevIndex=

Writes are proposed through raft (EtcdServer.v2_write → apply_v2);
reads and waits serve from the local v2 store. Errors travel as the
reference's JSON error body {errorCode, message, cause, index}."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import parse_qs, urlparse

from .v2store.store import Event, NodeExtern, V2Error

_ERROR_MESSAGES = {
    100: "Key not found",
    101: "Compare failed",
    102: "Not a file",
    104: "Not a directory",
    105: "Key already exists",
    107: "Root is read only",
    108: "Directory not empty",
}


def _enc_node(n: NodeExtern) -> dict:
    out: dict = {"key": n.key,
                 "createdIndex": n.created_index,
                 "modifiedIndex": n.modified_index}
    if n.dir:
        out["dir"] = True
    else:
        out["value"] = n.value or ""
    if n.ttl:
        out["ttl"] = n.ttl
    if n.nodes:
        out["nodes"] = [_enc_node(c) for c in n.nodes]
    return out


def _enc_event(ev: Event) -> dict:
    out = {"action": ev.action, "node": _enc_node(ev.node)}
    if ev.prev_node is not None:
        out["prevNode"] = _enc_node(ev.prev_node)
    return out


def _flag(q: dict, name: str) -> bool:
    v = q.get(name, ["false"])[0]
    return v in ("true", "1", "")


class V2HTTP:
    """One member's /v2/keys endpoint (plus /v2/stats placeholders)."""

    def __init__(self, server, bind: Tuple[str, int] = ("127.0.0.1", 0)):
        self.s = server
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                outer._handle(self, "GET")

            def do_PUT(self):
                outer._handle(self, "PUT")

            def do_POST(self):
                outer._handle(self, "POST")

            def do_DELETE(self):
                outer._handle(self, "DELETE")

        self.httpd = ThreadingHTTPServer(bind, Handler)
        self.addr = self.httpd.server_address
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)

    # -- request handling ------------------------------------------------------

    def _handle(self, h: BaseHTTPRequestHandler, method: str) -> None:
        u = urlparse(h.path)
        if not u.path.startswith("/v2/keys"):
            self._reply(h, 404, {"message": "404 page not found"})
            return
        path = u.path[len("/v2/keys"):] or "/"
        q = parse_qs(u.query, keep_blank_values=True)
        # PUT/POST/DELETE carry form-encoded bodies (client.go).
        ln = int(h.headers.get("Content-Length") or 0)
        if ln:
            q.update(parse_qs(h.rfile.read(ln).decode(),
                              keep_blank_values=True))
        try:
            if method == "GET":
                self._get(h, path, q)
            elif method == "PUT":
                self._put(h, path, q)
            elif method == "POST":
                self._post(h, path, q)
            else:
                self._delete(h, path, q)
        except V2Error as e:
            self._reply(h, 404 if e.code == 100 else 412 if e.code == 101
                        else 403 if e.code == 107 else 400, {
                            "errorCode": e.code,
                            "message": _ERROR_MESSAGES.get(e.code, "error"),
                            "cause": e.cause,
                            "index": e.index,
                        })
        except Exception as e:  # noqa: BLE001 — raft-level errors
            self._reply(h, 500, {"errorCode": 300,
                                 "message": "Raft Internal Error",
                                 "cause": str(e), "index": 0})

    def _get(self, h, path: str, q) -> None:
        if _flag(q, "wait"):
            since = int(q.get("waitIndex", ["0"])[0] or 0)
            w = self.s.v2store.watch(
                path, recursive=_flag(q, "recursive"), since=since)
            ev = w.wait(timeout=30.0)
            if ev is None:
                self._reply(h, 200, None)  # long-poll timeout: empty
                return
            self._reply(h, 200, _enc_event(ev))
            return
        ev = self.s.v2_get(path, recursive=_flag(q, "recursive"),
                           sorted_=_flag(q, "sorted"))
        self._reply(h, 200, _enc_event(ev))

    def _put(self, h, path: str, q) -> None:
        value = q.get("value", [""])[0]
        ttl = self._ttl(q)
        dir_ = _flag(q, "dir")
        prev_value = q.get("prevValue", [None])[0]
        prev_index = int(q.get("prevIndex", ["0"])[0] or 0)
        prev_exist = q.get("prevExist", [None])[0]
        if prev_value is not None or prev_index:
            ev = self.s.v2_write("cas", path, value=value, ttl=ttl,
                                 prev_value=prev_value,
                                 prev_index=prev_index)
            code = 200
        elif prev_exist == "true":
            ev = self.s.v2_write("update", path, value=value, ttl=ttl)
            code = 200
        elif prev_exist == "false":
            ev = self.s.v2_write("create", path, value=value, ttl=ttl,
                                 dir=dir_)
            code = 201
        else:
            ev = self.s.v2_write("set", path, value=value, ttl=ttl, dir=dir_)
            code = 201 if ev.prev_node is None else 200
        self._reply(h, code, _enc_event(ev))

    def _post(self, h, path: str, q) -> None:
        ev = self.s.v2_write("create", path, value=q.get("value", [""])[0],
                             ttl=self._ttl(q), unique=True)
        self._reply(h, 201, _enc_event(ev))

    def _delete(self, h, path: str, q) -> None:
        prev_value = q.get("prevValue", [None])[0]
        prev_index = int(q.get("prevIndex", ["0"])[0] or 0)
        if prev_value is not None or prev_index:
            ev = self.s.v2_write("cad", path, prev_value=prev_value,
                                 prev_index=prev_index)
        else:
            ev = self.s.v2_write("delete", path,
                                 recursive=_flag(q, "recursive"),
                                 dir=_flag(q, "dir"))
        self._reply(h, 200, _enc_event(ev))

    @staticmethod
    def _ttl(q) -> Optional[float]:
        raw = q.get("ttl", [None])[0]
        return float(raw) if raw else None

    def _reply(self, h, code: int, body: Optional[dict]) -> None:
        data = json.dumps(body).encode() if body is not None else b"{}"
        try:
            h.send_response(code)
            h.send_header("Content-Type", "application/json")
            h.send_header("X-Etcd-Index", str(self.s.v2store.index))
            h.send_header("Content-Length", str(len(data)))
            h.end_headers()
            h.wfile.write(data)
        except OSError:
            pass
