#!/usr/bin/env python
"""Consolidate the scattered bench artifacts into one trajectory.

The repo accumulates one-off bench JSONs per PR round — ``BENCH_r*.json``
(CPU/TPU kernel runs via bench.py), ``TPU_BENCH_r*.json`` (tunnel
captures), ``HOSTED_BENCH.json`` + ``artifacts/hosted_*.json`` (hosted
service rate), ``MULTICHIP_r*.json`` (mesh dry-runs) — and the perf
trajectory is otherwise reconstructible only by reading BENCH_NOTES
prose. This tool scans them all and emits:

* ``artifacts/bench_history.json`` — one normalized row per artifact
  (kind, round, headline value, unit, config, captured_at, source);
* ``artifacts/BENCH_HISTORY.md`` — the trajectory as markdown tables.

Re-emitted by ``tools/check.sh``, so the history tracks the tree.
Corrections are honored: a ``<NAME>.CORRECTION.md`` next to an
artifact flags its row (the r4 TPU 675M/s fence artifact stays in the
record, marked as corrected, instead of silently winning the table).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional


def _load(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"bench_history: skipping unreadable {path}: {e}",
              file=sys.stderr)
        return None


def _round_of(path: str) -> Optional[int]:
    m = re.search(r"_r0*(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else None


def _corrected(path: str) -> Optional[str]:
    base = re.sub(r"\.json$", "", path)
    corr = f"{base}.CORRECTION.md"
    return os.path.basename(corr) if os.path.exists(corr) else None


def collect(repo: str) -> List[Dict]:
    rows: List[Dict] = []

    def add(kind, path, value, unit, config="", captured_at="",
            extra=None):
        row = {
            "kind": kind,
            "round": _round_of(path),
            "source": os.path.relpath(path, repo),
            "value": value,
            "unit": unit,
            "config": config,
            "captured_at": captured_at,
        }
        corr = _corrected(path)
        if corr:
            row["corrected"] = corr
        if extra:
            row.update(extra)
        rows.append(row)

    # Kernel rate series: BENCH_r*.json wrap the parsed bench.py line;
    # TPU_BENCH_r*.json are the bare parsed object from the tunnel.
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        d = _load(path)
        if not d:
            continue
        p = d.get("parsed") or {}
        if "value" in p:
            add("kernel", path, p.get("value"), p.get("unit", ""),
                extra={"metric": p.get("metric", ""),
                       "vs_baseline": p.get("vs_baseline")})
    for path in sorted(glob.glob(os.path.join(repo,
                                              "TPU_BENCH_r*.json"))):
        d = _load(path)
        if d and "value" in d:
            add("kernel_tpu", path, d.get("value"), d.get("unit", ""),
                extra={"metric": d.get("metric", ""),
                       "vs_baseline": d.get("vs_baseline")})

    # Hosted service rate: the headline artifact plus per-run samples
    # and the CI floor under artifacts/.
    hosted = ([os.path.join(repo, "HOSTED_BENCH.json")]
              + sorted(glob.glob(os.path.join(
                  repo, "artifacts", "hosted_*.json"))))
    for path in hosted:
        d = _load(path) if os.path.exists(path) else None
        if not d or "puts_per_sec" not in d:
            continue
        extra = {"p50_ms": d.get("p50_ms"), "p99_ms": d.get("p99_ms"),
                 "lost": d.get("lost"),
                 "restart_catchup_s": d.get("restart_catchup_s")}
        # Transport provenance (ISSUE 16): hosted_shm_* rows carry the
        # fabric explicitly; older artifacts are implicitly tcp.
        if d.get("fabric"):
            extra["fabric"] = d["fabric"]
        add("hosted", path, d["puts_per_sec"], "puts/s",
            config=d.get("config", ""),
            captured_at=d.get("captured_at", ""),
            extra=extra)

    # Multi-chip dry-runs: ok/skip status per round (plus hosted-shape
    # numbers when the round captured them).
    for path in sorted(glob.glob(os.path.join(repo,
                                              "MULTICHIP_r*.json"))):
        d = _load(path)
        if not d:
            continue
        if "puts_per_sec" in d:
            add("multichip", path, d["puts_per_sec"], "puts/s",
                config=d.get("config", ""),
                captured_at=d.get("captured_at", ""))
        else:
            add("multichip", path,
                None, "",
                extra={"ok": d.get("ok"), "rc": d.get("rc"),
                       "skipped": d.get("skipped"),
                       "n_devices": d.get("n_devices")})

    # Observability overhead rows: fleet-summary A/B cells
    # (tools/fleet_overhead.py — interleaved on/off, one per G).
    path = os.path.join(repo, "artifacts", "fleet_overhead.json")
    d = _load(path) if os.path.exists(path) else None
    if d:
        for c in d.get("cells", ()):
            add("overhead_fleet", path, c.get("overhead_pct"),
                "% (off->on, interleaved)",
                config=f"G={c.get('groups')} ({d.get('platform', '')})",
                captured_at=d.get("captured_at", ""),
                extra={"off_median": c.get("off_median"),
                       "on_median": c.get("on_median")})

    rows.sort(key=lambda r: (r["kind"], r["round"] or 0, r["source"]))
    return rows


def markdown(rows: List[Dict]) -> str:
    out = ["# Bench trajectory (tools/bench_history.py)", ""]

    def fmt_val(r):
        v = r.get("value")
        if v is None:
            return f"ok={r.get('ok')} rc={r.get('rc')}"
        s = f"{v:,.1f}" if isinstance(v, (int, float)) else str(v)
        if r.get("corrected"):
            s += f" ⚠ (see {r['corrected']})"
        return s

    kernel = [r for r in rows if r["kind"].startswith("kernel")]
    if kernel:
        out += ["## Kernel (group-rounds/s)", "",
                "| round | source | value | unit/config |", "|---|---|---|---|"]
        for r in kernel:
            out.append(f"| {r['round'] if r['round'] is not None else ''} "
                       f"| {r['source']} | {fmt_val(r)} | {r['unit']} |")
        out.append("")
    hosted = [r for r in rows if r["kind"] == "hosted"]
    if hosted:
        out += ["## Hosted service rate (puts/s)", "",
                "| source | puts/s | p50 ms | p99 ms | lost | config "
                "| captured |", "|---|---|---|---|---|---|---|"]
        for r in hosted:
            out.append(
                f"| {r['source']} | {fmt_val(r)} | {r.get('p50_ms')} "
                f"| {r.get('p99_ms')} | {r.get('lost')} "
                f"| {r['config']} | {r['captured_at']} |")
        out.append("")
    mc = [r for r in rows if r["kind"] == "multichip"]
    if mc:
        out += ["## Multi-chip dry-runs", "",
                "| round | source | status |", "|---|---|---|"]
        for r in mc:
            out.append(f"| {r['round']} | {r['source']} | {fmt_val(r)} |")
        out.append("")
    ov = [r for r in rows if r["kind"].startswith("overhead_")]
    if ov:
        out += ["## Observability overhead (interleaved A/B)", "",
                "| source | overhead % | off | on | config | captured |",
                "|---|---|---|---|---|---|"]
        for r in ov:
            out.append(
                f"| {r['source']} | {fmt_val(r)} | {r.get('off_median')} "
                f"| {r.get('on_median')} | {r['config']} "
                f"| {r['captured_at']} |")
        out.append("")
    return "\n".join(out) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="consolidate bench artifacts into one history")
    ap.add_argument("--repo", default=os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    ap.add_argument("--out-dir", default=None,
                    help="default: <repo>/artifacts")
    args = ap.parse_args(argv)
    out_dir = args.out_dir or os.path.join(args.repo, "artifacts")
    rows = collect(args.repo)
    if not rows:
        print("bench_history: no bench artifacts found", file=sys.stderr)
        return 1
    os.makedirs(out_dir, exist_ok=True)
    out_json = os.path.join(out_dir, "bench_history.json")
    with open(out_json, "w") as f:
        json.dump({"rows": rows}, f, indent=1)
        f.write("\n")
    out_md = os.path.join(out_dir, "BENCH_HISTORY.md")
    with open(out_md, "w") as f:
        f.write(markdown(rows))
    print(f"bench_history: {len(rows)} rows -> {out_json}, {out_md}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
