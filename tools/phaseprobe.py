#!/usr/bin/env python
"""Device round-segment attribution: time each named_scope phase of the
batched round as its own jitted program and write the per-segment table
to ``artifacts/`` — the "deliver scan dominates the round" claim as a
tracked artifact instead of one ad-hoc probe's folklore.

Method: a warmed ``MultiRaftEngine`` supplies a realistic steady-state
(leaders elected, proposals staged, inbox populated); each phase
function (``step._deliver_all`` / ``_tick`` / ``_control`` /
``_propose`` / ``_emit``, vmapped over instances, plus ``route`` and
``pack_outbox``) is jitted in isolation, warmed once, then timed over
K dispatches with the result fenced — every timed call runs inside the
PR 7 transfer guard (``warm_guard``), so a smuggled host sync can't
fake a fast segment the way the r4 bench artifact did. Caveat recorded
in the artifact: the fused full round lets XLA overlap phases, so
isolated segments are an attribution of *relative* cost; their sum can
differ from the fused round time (both are reported).

Usage:
    python tools/phaseprobe.py [--groups 512] [--layout minor|major]
        [--rounds 32] [--out-dir artifacts] [--xprof DIR]

``--xprof DIR`` additionally captures a JAX profiler trace of the
fused-round timing loop (the named_scope annotations attribute device
time per phase in xprof — the capture that produced
artifacts/tpu_r05/xprof). This absorbs the old ad-hoc
tests/batched/phaseprobe.py probe.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from etcd_tpu.analysis.sentinels import warm_guard  # noqa: E402
from etcd_tpu.batched import step as step_mod  # noqa: E402
from etcd_tpu.batched.engine import MultiRaftEngine  # noqa: E402
from etcd_tpu.batched.state import BatchedConfig, I32  # noqa: E402


def _time_calls(name: str, fn, args, rounds: int) -> float:
    """Per-call seconds over `rounds` dispatches, first call unwarmed
    (compile, unguarded), the timed loop fenced + transfer-guarded."""
    key = f"phaseprobe/{name}"
    with warm_guard(key):
        jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    with warm_guard(key):
        for _ in range(rounds):
            out = fn(*args)
        jax.block_until_ready(out)  # the timing fence IS the measurement
    return (time.perf_counter() - t0) / rounds


def main() -> int:
    ap = argparse.ArgumentParser(
        description="per-phase device round attribution")
    ap.add_argument("--groups", type=int, default=512)
    ap.add_argument("--layout", choices=("minor", "major"),
                    default="minor")
    ap.add_argument("--deliver-shape",
                    choices=("auto", "lanes", "merged", "vectorized"),
                    default="auto",
                    help="deliver shape to probe (auto = the platform "
                         "default, state.default_deliver_shape)")
    ap.add_argument("--rounds", type=int, default=32)
    ap.add_argument("--out-dir", default="artifacts")
    ap.add_argument("--xprof", default="", metavar="DIR",
                    help="capture a JAX profiler trace of the fused-"
                         "round loop into DIR (xprof attributes device "
                         "time per named_scope phase)")
    args = ap.parse_args()

    g = args.groups
    cfg = BatchedConfig(
        num_groups=g, num_replicas=3, window=32, max_ents_per_msg=4,
        max_props_per_round=2, election_timeout=1 << 20,
        heartbeat_timeout=4, auto_compact=True,
        lanes_minor=args.layout == "minor",
        deliver_shape=args.deliver_shape,
    ).resolved()
    eng = MultiRaftEngine(cfg)
    eng.campaign([i * 3 for i in range(g)])
    eng.run_rounds(4, tick=False)
    assert (eng.leaders() == 0).all(), "warmup did not elect leaders"
    n = cfg.num_instances
    props = jnp.zeros((n,), I32).at[jnp.arange(g) * 3].set(2)
    ticks = jnp.ones((n,), bool)
    zb = jnp.zeros((n,), bool)
    zi = jnp.zeros((n,), I32)
    iids = jnp.arange(n, dtype=I32)
    slots = iids % 3
    st, inbox = eng.state, eng.inbox

    # Per-phase jitted programs over the SAME live state/inbox. The
    # per-instance phase functions vmap exactly as the round does
    # (major layout — segment ratios are what the probe tracks; the
    # lanes_minor transpose belongs to the fused round, measured via
    # the full-round reference below).
    phase_fns = {
        # deliver takes the batch-level lane-occupancy vector exactly
        # as the production round does (computed outside the vmap →
        # the vectorized shape's lane skips stay real branches).
        "deliver": (
            jax.jit(lambda _iids, _slots, _st, _inbox: jax.vmap(
                lambda iid, slot, sti, inb, la:
                step_mod._deliver_all(cfg, iid, slot, sti, inb, la),
                in_axes=(0, 0, 0, 0, None))(
                _iids, _slots, _st, _inbox,
                jnp.any(_inbox.valid, axis=(0, 1)))),
            (iids, slots, st, inbox)),
        "tick": (
            jax.jit(jax.vmap(
                lambda iid, slot, sti, dt, dc:
                step_mod._tick(cfg, iid, slot, sti, dt, dc))),
            (iids, slots, st, ticks, zb)),
        "control": (
            jax.jit(jax.vmap(
                lambda slot, sti, tr, rr:
                step_mod._control(cfg, slot, sti, tr, rr))),
            (slots, st, zi, zb)),
        "propose": (
            jax.jit(jax.vmap(
                lambda slot, sti, nn:
                step_mod._propose(cfg, slot, sti, nn))),
            (slots, st, props)),
        "emit": (
            jax.jit(jax.vmap(
                lambda slot, sti: step_mod._emit(cfg, slot, sti))),
            (slots, st)),
    }
    order = [name for name, _scope in step_mod.ROUND_PHASE_SCOPES]
    seg_s = {}
    for name in order:
        if name in phase_fns:
            fn, fargs = phase_fns[name]
            seg_s[name] = _time_calls(name, fn, fargs, args.rounds)
            print(f"{name}: {seg_s[name] * 1e3:.3f} ms", flush=True)
    # route runs on a real outbox (emit's output), like the round does.
    _st2, outbox = phase_fns["emit"][0](slots, st)
    route_fn = jax.jit(lambda ob: step_mod.route(cfg, ob))
    seg_s["route"] = _time_calls("route", route_fn, (outbox,),
                                 args.rounds)
    print(f"route: {seg_s['route'] * 1e3:.3f} ms", flush=True)
    # pack_outbox: the hosted collect's on-device half (PR 6).
    seg_s["pack_outbox"] = _time_calls(
        "pack_outbox", step_mod.pack_outbox, (outbox, slots),
        args.rounds)
    print(f"pack_outbox: {seg_s['pack_outbox'] * 1e3:.3f} ms",
          flush=True)
    # Fused full-round reference (the program production actually runs).
    if args.xprof:
        with jax.profiler.trace(args.xprof):
            full_s = _time_calls(
                "full_round", eng._step,
                (st, inbox, ticks, zb, props, zb), args.rounds)
        print(f"xprof trace captured in {args.xprof}", flush=True)
    else:
        full_s = _time_calls(
            "full_round", eng._step, (st, inbox, ticks, zb, props, zb),
            args.rounds)
    print(f"full_round (fused): {full_s * 1e3:.3f} ms", flush=True)

    total = sum(seg_s.values())
    segments = [
        {
            "segment": name,
            "scope": dict(step_mod.ROUND_PHASE_SCOPES).get(name, name),
            "ms": round(seg_s[name] * 1e3, 4),
            "pct_of_segments": round(100 * seg_s[name] / total, 1),
        }
        for name in order + ["pack_outbox"] if name in seg_s
    ]
    backend = jax.devices()[0]
    result = {
        "metric": "round_segment_attribution",
        "config": (f"G={g} R=3 W=32 E=4 layout={args.layout} "
                   f"deliver={cfg.deliver_shape} "
                   f"platform={backend.platform}"),
        "device": str(backend),
        "rounds_per_segment": args.rounds,
        "segments": segments,
        "segments_sum_ms": round(total * 1e3, 4),
        "full_round_fused_ms": round(full_s * 1e3, 4),
        "note": ("segments timed as isolated jitted programs under the "
                 "transfer guard; the fused round overlaps phases, so "
                 "the sum is an attribution baseline, not a wall-time "
                 "identity"),
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "captured_by": "tools/phaseprobe.py",
    }
    os.makedirs(args.out_dir, exist_ok=True)
    out_json = os.path.join(args.out_dir, "phaseprobe.json")
    with open(out_json, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    lines = [
        "# Device round-segment attribution (tools/phaseprobe.py)",
        "",
        f"Config: `{result['config']}`, {args.rounds} timed rounds per "
        f"segment; fused full round {result['full_round_fused_ms']} ms.",
        "",
        "| segment | named_scope | ms | % of segments |",
        "|---|---|---|---|",
    ]
    for s in segments:
        lines.append(f"| {s['segment']} | {s['scope']} | {s['ms']} "
                     f"| {s['pct_of_segments']} |")
    lines.append("")
    lines.append(result["note"] + ".")
    out_md = os.path.join(args.out_dir, "PHASEPROBE.md")
    with open(out_md, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out_json} and {out_md}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
