#!/usr/bin/env python
"""Rebalancer daemon over the hosting admin API (ISSUE 11, ROADMAP 5).

Closes the fleet-observatory loop as a standalone process: scrapes
every member's ``fleet`` rollup (the device SummaryFrame the members
already emit — no bespoke probes), and when leadership skew crosses the
threshold (the same quantity the ``leader_skew`` anomaly flags) moves
donor-led groups to under-loaded members via the admin ``transfer`` op
— observatory-flagged groups (``commit_frozen``, top-K laggards) first,
each move awaited with a bounded timeout, retried at most
``--max-retries`` times, and quarantined by a per-group cooldown so a
noisy signal stream can never flap leadership.

    python tools/rebalancerd.py --admin 1=127.0.0.1:8001 \
        --admin 2=127.0.0.1:8002 --admin 3=127.0.0.1:8003

``--once --json`` runs a single observe→move→re-observe pass and prints
the machine-readable report (the scripting/CI contract —
tools/rebalance_smoke.py validates it); exit code 0 means the cluster
is at-or-below the skew threshold after the pass.

Member ids: pass ``--admin id=host:port``; bare ``host:port`` entries
are numbered 1..N in order.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

REPORT_KEYS = (
    "triggered", "ratio_before", "ratio_after", "balance_before",
    "balance_after", "moves", "moved", "failed", "cooldown_vetoed",
    "members_seen", "converged",
)


def validate_report(rep: Dict) -> List[str]:
    """Schema check for the --once --json contract; returns problems,
    empty == valid."""
    probs = [f"missing key {k!r}" for k in REPORT_KEYS if k not in rep]
    for mv in rep.get("moves", ()):
        for k in ("group", "frm", "to", "attempts", "ok"):
            if k not in mv:
                probs.append(f"move missing {k!r}: {mv}")
    return probs


def _parse_admins(specs: List[str]) -> Dict[int, Tuple[str, int]]:
    addrs: Dict[int, Tuple[str, int]] = {}
    auto = 1
    for spec in specs:
        for part in spec.split(","):
            if not part:
                continue
            mid_s, sep, addr = part.partition("=")
            if sep:
                mid = int(mid_s)
            else:
                addr = part
                mid = auto
            auto = mid + 1
            host, _, port = addr.rpartition(":")
            addrs[mid] = (host, int(port))
    return addrs


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="rebalancerd",
                                description=__doc__)
    p.add_argument("--admin", action="append", default=[],
                   help="member admin endpoint [id=]host:port "
                        "(repeatable or comma-separated)")
    p.add_argument("--once", action="store_true",
                   help="one pass, then exit (0 iff converged)")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable pass report")
    p.add_argument("--interval", type=float, default=5.0)
    p.add_argument("--skew-ratio", type=float, default=1.5,
                   help="trigger/convergence bar: max leaders over "
                        "fair share")
    p.add_argument("--cooldown", type=float, default=10.0,
                   help="per-group re-move quarantine seconds")
    p.add_argument("--max-moves", type=int, default=64)
    p.add_argument("--max-retries", type=int, default=3)
    p.add_argument("--wait", type=float, default=5.0,
                   help="bounded wait per transfer completion")
    p.add_argument("--out", default="",
                   help="also write each report as JSON to this path")
    args = p.parse_args(argv)
    addrs = _parse_admins(args.admin)
    if not addrs:
        print("need at least one --admin [id=]host:port",
              file=sys.stderr)
        return 2

    from etcd_tpu.batched.rebalance import (
        AdminActuator,
        RebalanceConfig,
        Rebalancer,
    )

    act = AdminActuator(addrs)
    reb = Rebalancer(act, RebalanceConfig(
        skew_ratio=args.skew_ratio, cooldown_s=args.cooldown,
        max_moves_per_pass=args.max_moves,
        max_retries=args.max_retries, transfer_wait_s=args.wait))

    def emit(rep: Dict) -> None:
        if args.json:
            print(json.dumps(rep), flush=True)
        else:
            print(f"[{time.strftime('%H:%M:%S')}] "
                  f"ratio {rep['ratio_before']} -> "
                  f"{rep['ratio_after']}  moved {rep['moved']} "
                  f"(failed {rep['failed']}, cooldown "
                  f"{rep['cooldown_vetoed']})  "
                  f"balance {rep['balance_after']}", flush=True)
        if args.out:
            with open(args.out, "w") as fh:
                json.dump(rep, fh, indent=1)
                fh.write("\n")

    try:
        if args.once:
            rep = reb.run_once()
            emit(rep)
            return 0 if rep["converged"] else 1
        while True:
            emit(reb.run_once())
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        act.close()


if __name__ == "__main__":
    sys.exit(main())
