#!/usr/bin/env python
"""Trace smoke for tools/check.sh (ISSUE 9): drive one traced
proposal through a tiny 3-member in-process round set, then validate
the merged export is Perfetto-loadable Chrome-trace JSON. One tiny
compile (~seconds on CPU), no sockets, no threads — a broken stamp
hook or exporter regression fails the static gate, not a hosted run.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from etcd_tpu.batched.rawnode import BatchedRawNode  # noqa: E402
from etcd_tpu.batched.state import BatchedConfig  # noqa: E402
from etcd_tpu.obs.export import validate_chrome_trace  # noqa: E402
from etcd_tpu.obs.merge import merge  # noqa: E402
from etcd_tpu.obs.tracer import STAGES, Tracer  # noqa: E402

G, R = 2, 3


def main() -> int:
    cfg = BatchedConfig(
        num_groups=G, num_replicas=R, window=8, max_ents_per_msg=2,
        max_props_per_round=1, election_timeout=1 << 20,
        heartbeat_timeout=4,
    )
    rns = {}
    for mid in range(1, R + 1):
        rn = BatchedRawNode(
            cfg, groups=np.arange(G, dtype=np.int32),
            slots=np.full(G, mid - 1, np.int32))
        rn.tracer = Tracer(member=str(mid), sample=1)
        rns[mid] = rn

    def pump(rounds):
        for _ in range(rounds):
            for mid, rn in rns.items():
                rd = rn.advance_round()
                blk = rd.msg_block
                if blk is not None and len(blk):
                    for to, sub in blk.split_by_target().items():
                        rns[to].step_block(sub)
                for row, m in rd.messages:
                    rns[m.to].step(row, m)
                rn.tracer.stamp_many(rd.traced_entries, "fsync_wait")
                rn.tracer.stamp_many(rd.traced_entries, "fsync")
                rn.tracer.stamp_many(rd.traced_entries, "send")
                rn.tracer.stamp_many(rd.traced_commit, "apply")
                rn.advance()

    rns[1].campaign(np.arange(G))
    pump(5)
    for g in range(G):
        rns[1].propose(g, b"smoke")
    pump(6)

    payloads = [rn.tracer.to_payload() for rn in rns.values()]
    trace, stats = merge(payloads)
    slices = validate_chrome_trace(trace)
    origin = [sp for sp in payloads[0]["spans"]
              if sp.get("complete") and "propose" in sp["stages"]]
    if len(origin) != G:
        print(f"trace smoke: expected {G} completed proposal spans on "
              f"the leader, got {len(origin)}", file=sys.stderr)
        return 1
    missing = set(STAGES) - set(origin[0]["stages"])
    if missing:
        print(f"trace smoke: span missing stages {missing}",
              file=sys.stderr)
        return 1
    if stats["spans_peer_decomposed"] < G:
        print(f"trace smoke: only {stats['spans_peer_decomposed']}/{G} "
              f"spans peer-decomposed", file=sys.stderr)
        return 1
    print(f"trace smoke OK: {stats['spans_joined']} spans joined, "
          f"{len(slices)} slices, hop sum "
          f"{stats['hop_p50_sum_ms']}ms / e2e "
          f"{stats['e2e_apply'].get('p50_ms')}ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
