#!/usr/bin/env python
"""Merge per-member trace-ring dumps into one cross-member timeline.

Input: two or more tracer payloads (``Tracer.to_payload()`` JSON — the
admin 'trace' op's inline payload, or ``tracering_*.json`` dumps from
``Tracer.dump`` / the chaos harness). Output: a Perfetto-loadable
Chrome-trace JSON of every member's spans on one aligned clock, plus a
per-hop latency table decomposing commit latency into named hops
(propose→stage→step→fsync→send→peer-fsync→ack→commit→apply).

The join/offset-estimation machinery lives in ``etcd_tpu.obs.merge``
(importable — tools/hosted_bench.py builds its SLO table from it); this
is the command-line face:

    python tools/trace_merge.py m1.json m2.json m3.json \
        [-o merged_trace.json] [--table HOPS.md] [--json stats.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from etcd_tpu.obs.export import validate_chrome_trace  # noqa: E402
from etcd_tpu.obs.merge import (  # noqa: E402
    hops_markdown,
    load_payload,
    merge,
)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="merge per-member trace dumps into one timeline")
    ap.add_argument("dumps", nargs="+", help="tracering_*.json paths")
    ap.add_argument("-o", "--out", default="artifacts/merged_trace.json",
                    help="merged Chrome-trace JSON (Perfetto-loadable)")
    ap.add_argument("--table", default="",
                    help="also write the hop table as markdown")
    ap.add_argument("--json", dest="stats_json", default="",
                    help="also write hop stats as JSON")
    args = ap.parse_args(argv)
    payloads = [load_payload(p) for p in args.dumps]
    trace, stats = merge(payloads)
    validate_chrome_trace(trace)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(trace, f)
        f.write("\n")
    md = hops_markdown(stats)
    if args.table:
        with open(args.table, "w") as f:
            f.write(f"# Commit-path hop decomposition\n\n{md}")
    if args.stats_json:
        with open(args.stats_json, "w") as f:
            json.dump(stats, f, indent=1)
            f.write("\n")
    print(md)
    print(f"merged trace: {args.out} "
          f"({stats['spans_joined']} spans, offsets "
          f"{stats['clock_offsets_ns']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
