#!/usr/bin/env python
"""Device apply-plane smoke for tools/check.sh (ISSUE 19): a tiny
in-proc cluster runs with ``apply_plane=True`` (tensorized KV +
revision lanes, watch compare lanes, lease ticks) and drives the whole
surface once: mixed puts land in both tiers, a lease-held linearizable
read serves from the leader with ZERO quorum rounds (counted as a
lease hit), an armed watch slot emits a fixed-shape event frame with
the right revision, a TTL'd put expires on the plane clock and the
masked read stops serving it, and a leadership transfer forces the
read path back to ReadIndex (counted as a fallback — never a stale
serve). One tiny compile (~seconds on CPU); a lease-safety, watch
or routing regression fails the static gate, not a hosted run.

Writes artifacts/applyplane_smoke.json (uploaded by lint.yml on
failure).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from etcd_tpu.batched.hosting import (  # noqa: E402
    MultiRaftCluster, NotLeaderError)
from etcd_tpu.batched.state import BatchedConfig  # noqa: E402

G, R = 4, 3

OUT = os.path.join("artifacts", "applyplane_smoke.json")


def _write(report) -> None:
    os.makedirs("artifacts", exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def _fail(report, msg: str) -> int:
    report["ok"] = False
    report["error"] = msg
    _write(report)
    print(f"applyplane smoke: {msg}", file=sys.stderr)
    return 1


def lin_read(cl, g, key, timeout=30.0):
    """Redirect-style client read: try every member, retrying on
    NotLeaderError/TimeoutError — leadership placement is the
    cluster's business, not the client's."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        for m in cl.members.values():
            try:
                return m, m.linearizable_get(g, key, timeout=5.0)
            except (NotLeaderError, TimeoutError):
                continue
        time.sleep(0.05)
    raise TimeoutError(f"no member served the read for group {g}")


def main() -> int:
    cfg = BatchedConfig(
        num_groups=G, num_replicas=R, window=16, max_ents_per_msg=4,
        max_props_per_round=4, election_timeout=10,
        heartbeat_timeout=1, pre_vote=True, check_quorum=True,
        auto_compact=True,
        apply_plane=True, apply_capacity=64, apply_watch_slots=4,
        apply_records=4,
    )
    report = {"groups": G, "members": R, "ok": False,
              "capacity": cfg.apply_capacity,
              "watch_slots": cfg.apply_watch_slots}
    with tempfile.TemporaryDirectory(prefix="applyplane-smoke-") as d:
        cl = MultiRaftCluster(d, num_members=R, num_groups=G, cfg=cfg)
        try:
            cl.wait_leaders(timeout=120.0)

            # Watches are member-local: arm a slot on member 1 before
            # the write so the apply dispatch sees the armed compare.
            wm = cl.members[1]
            wm.watch(0, b"wk")

            # Mixed workload: plain puts, the watched key, a TTL'd put.
            for i in range(6):
                cl.put(0, b"k%d" % i, b"v%d" % i, timeout=30.0)
            cl.put(0, b"wk", b"wv", timeout=30.0)
            cl.put(1, b"lk", b"lv", lease_ttl=8, timeout=30.0)

            # Lease-held linearizable read: the steady leader serves
            # from the applied host tier under its lease — zero quorum
            # rounds, counted as a lease hit.
            m0, v = lin_read(cl, 0, b"k3")
            if v != b"v3":
                return _fail(report, f"lease read returned {v!r}")
            hits = sum(m.stats.get("lease_read_hits", 0)
                       for m in cl.members.values())
            if hits < 1:
                return _fail(report, "no lease-hit read counted")
            report["apply_plane_health"] = m0.health()["apply_plane"]

            # Watch frame: the armed slot must emit a PUT event with
            # the key's hash and a sane revision.
            deadline = time.monotonic() + 10.0
            evs = []
            while time.monotonic() < deadline and not evs:
                evs = wm.watch_events()
                time.sleep(0.05)
            hit = [e for e in evs
                   if e["key"] == b"wk".hex() and e["op"] == "PUT"]
            if not hit:
                return _fail(report, f"watch event missing: {evs}")
            report["watch_event"] = hit[0]

            # Lease expiry: the plane tick lane passes the TTL and the
            # masked read stops serving the key (host bytes remain —
            # cross-member byte parity is not disturbed).
            def masked():
                for m in cl.members.values():
                    if m.is_leader(1):
                        return m._lease_masked_get(1, b"lk")
                return b"<noleader>"

            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and masked() is not None:
                time.sleep(0.1)
            if masked() is not None:
                return _fail(report, "TTL'd key never expired on the "
                             "plane clock")

            # Transfer: the departing leader must FALL BACK to
            # ReadIndex (or refuse), never serve a stale lease read.
            old = next(m for m in cl.members.values()
                       if m.is_leader(2))
            target = (old.id % R) + 1
            if not old.transfer_leader(2, target):
                return _fail(report, "leadership transfer failed")
            try:
                old.linearizable_get(2, b"x", timeout=3.0)
            except (NotLeaderError, TimeoutError):
                pass
            if old.stats.get("lease_read_fallbacks", 0) < 1:
                return _fail(report, "post-transfer read did not fall "
                             "back to ReadIndex")
            report["post_transfer_fallbacks"] = (
                old.stats.get("lease_read_fallbacks", 0))
            report["lease_read_hits_total"] = sum(
                m.stats.get("lease_read_hits", 0)
                for m in cl.members.values())
        finally:
            cl.stop()

    report["ok"] = True
    _write(report)
    h = report["apply_plane_health"]
    print(f"applyplane smoke OK: kv hw {h['slots_high_water']}/"
          f"{h['capacity']}, leases {h['active_leases']}, "
          f"lease hits {report['lease_read_hits_total']}, "
          f"watch rev {report['watch_event']['rev']}, "
          f"transfer fallbacks {report['post_transfer_fallbacks']} "
          f"({OUT})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
