#!/usr/bin/env python
"""Fleet smoke for tools/check.sh (ISSUE 10): boot a tiny in-process
3-member hosting cluster with the fleet observatory on, serve each
member's admin API in-process, and validate ``fleet_console --once
--json`` end to end — a broken device SummaryFrame, admin 'fleet' op,
or console rollup fails the static gate, not a live hosted run. One
tiny compile (the chaos suite's config shape), no worker processes.
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
import time
from contextlib import redirect_stdout

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

G, R = 8, 3


def main() -> int:
    from etcd_tpu.batched.hosting import MultiRaftCluster
    from etcd_tpu.batched.hosting_proc import AdminServer
    from etcd_tpu.batched.state import BatchedConfig

    import fleet_console

    cfg = BatchedConfig(
        num_groups=G, num_replicas=R, window=16, max_ents_per_msg=4,
        max_props_per_round=4, election_timeout=10, heartbeat_timeout=1,
        pre_vote=True, check_quorum=True, auto_compact=True,
        telemetry=True, fleet_summary=True,
    )
    tmp = tempfile.mkdtemp(prefix="fleet_smoke_")
    cluster = MultiRaftCluster(tmp, num_members=R, num_groups=G,
                               cfg=cfg)
    admins = []
    try:
        cluster.wait_leaders(timeout=120.0)
        for g in range(G):
            cluster.put(g, b"k%d" % g, b"v%d" % g, timeout=30.0)
        # At least one summary frame folded on every member.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(m.fleet is not None and m.fleet.frames() > 0
                   for m in cluster.members.values()):
                break
            time.sleep(0.05)
        else:
            print("fleet smoke: members never folded a summary frame",
                  file=sys.stderr)
            return 1

        for m in cluster.members.values():
            admins.append(AdminServer(m, cluster.router,
                                      ("127.0.0.1", 0)))
        addrs = [f"127.0.0.1:{a.addr[1]}" for a in admins]

        # leaders_total is an instantaneous census from each member's
        # latest frame — retry the exact-G check briefly rather than
        # flake on a scrape that lands mid-frame on a loaded CI box.
        deadline = time.monotonic() + 60.0
        while True:
            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = fleet_console.main(
                    ["--once", "--json"]
                    + [x for a in addrs for x in ("--admin", a)])
            if rc != 0:
                print(f"fleet smoke: console exited {rc}",
                      file=sys.stderr)
                print(buf.getvalue()[-2000:], file=sys.stderr)
                return 1
            data = json.loads(buf.getvalue())
            probs = fleet_console.validate_rollup(data)
            if probs:
                print(f"fleet smoke: invalid rollup: {probs}",
                      file=sys.stderr)
                return 1
            cl = data["cluster"]
            if cl["members_live"] != R:
                print(f"fleet smoke: {cl['members_live']}/{R} "
                      f"members live", file=sys.stderr)
                return 1
            if cl["leaders_total"] == G:
                break
            if time.monotonic() > deadline:
                print(f"fleet smoke: leaders_total "
                      f"{cl['leaders_total']} != {G}", file=sys.stderr)
                return 1
            time.sleep(0.5)
        if cl["invariant_trips_total"] != 0:
            print(f"fleet smoke: invariant trips "
                  f"{cl['invariant_trips_total']}", file=sys.stderr)
            return 1
        # The table renderer must hold together on the same data too.
        table = fleet_console.render(data)
        if "top-8 laggards" not in table:
            print("fleet smoke: table render incomplete",
                  file=sys.stderr)
            return 1
        print(f"fleet smoke OK: {cl['members_live']} members, "
              f"{cl['leaders_total']} leaders, lag_max "
              f"{cl['lag_max']}, anomalies {cl['anomalies']}")
        return 0
    finally:
        for a in admins:
            a.close()
        cluster.stop()


if __name__ == "__main__":
    sys.exit(main())
