#!/usr/bin/env python
"""Live cluster console over the fleet observatory (ISSUE 10).

Scrapes the hosting admin endpoints (``fleet``, ``health``, ``stats``
ops) of every member of a live multi-raft cluster and renders a
refreshing terminal rollup: leader balance per member, the cluster-wide
top-K laggards with group ids, fenced-group counts, on-device invariant
trips, router loss, and the fleet anomaly flags (commit_frozen /
leader_skew — the signal the ROADMAP item 5 rebalancer consumes).

    python tools/fleet_console.py --admin 127.0.0.1:8001 \
        --admin 127.0.0.1:8002 --admin 127.0.0.1:8003

``--once --json`` emits one machine-readable cluster rollup and exits —
the scripting/CI mode (tools/check.sh's fleet smoke and the proc e2e
test both validate it via :func:`validate_rollup`).

Members must be started with ``--fleet`` (and ideally ``--telemetry``
for invariant trips); a member with the plane off is reported as
``err`` rather than silently dropped from the view.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _hp(s: str) -> Tuple[str, int]:
    h, _, p = s.rpartition(":")
    return h, int(p)


def _call(addr: Tuple[str, int], timeout: float, **req) -> Dict:
    """One line-JSON admin round trip (fresh connection per call: the
    console is a scraper, not a client — members crash and restart
    under it and a stale socket must not wedge the refresh loop)."""
    with socket.create_connection(addr, timeout=timeout) as s:
        f = s.makefile("rwb")
        f.write(json.dumps(req).encode() + b"\n")
        f.flush()
        line = f.readline()
    if not line:
        raise ConnectionError("admin connection closed")
    return json.loads(line)


def _sum_numeric(obj) -> int:
    """Total of every numeric leaf (router loss dicts differ in shape
    between the in-proc and TCP fabrics; the rollup wants one number)."""
    if isinstance(obj, bool):
        return 0
    if isinstance(obj, (int, float)):
        return int(obj)
    if isinstance(obj, dict):
        return sum(_sum_numeric(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return sum(_sum_numeric(v) for v in obj)
    return 0


def collect(addrs: List[str], timeout: float = 10.0,
            top: int = 8) -> Dict:
    """Scrape every member once and build the cluster rollup."""
    members: Dict[str, Dict] = {}
    for spec in addrs:
        addr = _hp(spec)
        ent: Dict = {"addr": spec}
        try:
            fl = _call(addr, timeout, op="fleet")
            hl = _call(addr, timeout, op="health")
            st = _call(addr, timeout, op="stats")
        except (OSError, ConnectionError, ValueError) as e:
            ent["err"] = f"{type(e).__name__}: {e}"
            members[spec] = ent
            continue
        if not fl.get("ok"):
            ent["err"] = fl.get("err", "fleet op failed")
            members[spec] = ent
            continue
        roll = fl["rollup"]
        mid = str(roll.get("member", spec))
        ent.update({
            "member": mid,
            "frames": roll.get("frames", 0),
            "groups": roll.get("groups"),
            "leaders": roll.get("leaders_total", 0),
            "leader_slot": roll.get("leader_slot", []),
            "fenced": roll.get("fenced", 0),
            "lag_max": roll.get("lag_max", 0),
            "role_census": roll.get("role_census", {}),
            "top": [dict(e2, member=mid)
                    for e2 in roll.get("top", [])],
            "anomalies": roll.get("anomalies", {}),
            "invariant_trips": fl.get("invariant_trips"),
            "wal_tail": hl.get("wal_tail") if hl.get("ok") else None,
            "health_fenced": (len(hl.get("fenced_groups", []))
                              if hl.get("ok") else None),
            # Membership control plane (ISSUE 11): live joint/learner
            # census + applied conf-change total from the health op.
            "joint": (hl.get("joint_groups", 0)
                      if hl.get("ok") else None),
            "learners": (hl.get("learner_slots", 0)
                         if hl.get("ok") else None),
            "conf_applied": (hl.get("conf_applied", 0)
                             if hl.get("ok") else None),
            # Async WAL pipeline (ISSUE 13): group-commit amortization
            # ratio (device rounds per fsync) + live queue depth from
            # the health op; None when the member predates the field,
            # {"enabled": False, ...} when it runs inline persistence.
            "wal_pipeline": (hl.get("wal_pipeline")
                             if hl.get("ok") else None),
            # Storage fault plane (ISSUE 15): live ENOSPC back-pressure
            # + fail-stop cause from the health op, gray-failure limp
            # state from the fleet rollup.
            "disk_full": (hl.get("disk_full", False)
                          if hl.get("ok") else None),
            "fail_stop": (hl.get("fail_stop")
                          if hl.get("ok") else None),
            "limp": roll.get("limp") or {},
            "router_loss": (_sum_numeric(st.get("router", {}))
                            if st.get("ok") else None),
            # Transport plane (ISSUE 16): fabric kind from the stats
            # op, plus the shm fabric's per-lane ring depth/high-water
            # (absent on tcp/inproc — their backlog lives in queues).
            "fabric": ((st.get("fabric") or {}).get("kind")
                       if st.get("ok") else None),
            "fabric_lanes": ((st.get("fabric") or {}).get("lanes")
                             if st.get("ok") else None),
            # Log-lifecycle plane (ISSUE 17): WAL segments + bytes on
            # disk, the oldest still-pinned sealed segment and the
            # group pinning it, snapshot-file census, and the ring
            # back-pressure high-water from the health op. None when
            # the member predates the fields, {"enabled": False, ...}
            # when the plane is off (WAL grows unboundedly).
            "lifecycle": (hl.get("lifecycle")
                          if hl.get("ok") else None),
            "ring": hl.get("ring") if hl.get("ok") else None,
            # Device apply plane (ISSUE 19): KV slot high-water vs
            # capacity, active lease census, watch-event total, and
            # the lease-read hit/fallback split from the health op.
            # None when the member predates the field,
            # {"enabled": False} when the plane is off.
            "apply_plane": (hl.get("apply_plane")
                            if hl.get("ok") else None),
        })
        members[mid] = ent

    live = [m for m in members.values() if "err" not in m]
    merged_top = sorted(
        (e for m in live for e in m["top"]),
        key=lambda e: (-e["lag"], e["group"]))[:top]
    anomalies: Dict[str, int] = {}
    for m in live:
        for k, v in m.get("anomalies", {}).items():
            anomalies[k] = anomalies.get(k, 0) + int(v)
    # Unmeasured must stay distinguishable from verified-clean: a
    # member without --telemetry reports invariant_trips=None, and
    # summing `or 0` would print "0 trips" for a cluster where trips
    # were never measured. None propagates when NO member measured.
    trip_vals = [m["invariant_trips"] for m in live
                 if m["invariant_trips"] is not None]
    cluster = {
        "members_live": len(live),
        "members_total": len(members),
        "groups": max((m.get("groups") or 0 for m in live), default=0),
        "leader_balance": {m["member"]: m["leaders"] for m in live},
        "leaders_total": sum(m["leaders"] for m in live),
        "fenced_total": sum(m["fenced"] for m in live),
        # Joint/learner censuses count the REPLICATED config, which
        # every member holds a copy of — merge by max (summing would
        # triple-count a converged cluster; a member lagging behind a
        # conf apply under-reports, and max keeps the true census).
        "joint_total": max((m.get("joint") or 0 for m in live),
                           default=0),
        "learners_total": max((m.get("learners") or 0 for m in live),
                              default=0),
        "invariant_trips_total": (sum(trip_vals) if trip_vals
                                  else None),
        "router_loss_total": sum(m["router_loss"] or 0 for m in live),
        "lag_max": max((m["lag_max"] for m in live), default=0),
        # Storage fault plane rollup (ISSUE 15): members currently in
        # ENOSPC back-pressure / limping / dead by fail-stop.
        "disk_full_members": sorted(
            m["member"] for m in live if m.get("disk_full")),
        "limping_members": sorted(
            m["member"] for m in live
            if (m.get("limp") or {}).get("limping")),
        "failstop_members": sorted(
            m["member"] for m in live if m.get("fail_stop")),
        # Log-lifecycle rollup (ISSUE 17): total WAL bytes on disk
        # across members, and members whose sealed-segment backlog is
        # pinned by a stuck/fenced group (the wal_pinned anomaly).
        "wal_bytes_total": sum(
            (m.get("lifecycle") or {}).get("wal_bytes", 0)
            for m in live),
        "snap_files_total": sum(
            (m.get("lifecycle") or {}).get("snap_files", 0)
            for m in live),
        "wal_pinned_members": sorted(
            m["member"] for m in live
            if (m.get("lifecycle") or {}).get("wal_pinned")),
        "top": merged_top,
        "anomalies": anomalies,
        # Apply-plane rollup (ISSUE 19): leases are leader-local (one
        # holder per led group), so summing across members is the true
        # cluster census; the hit ratio pools every member's reads.
        "active_leases_total": sum(
            (m.get("apply_plane") or {}).get("active_leases", 0)
            for m in live),
        "lease_read_hits_total": sum(
            (m.get("apply_plane") or {}).get("lease_read_hits", 0)
            for m in live),
        "lease_read_fallbacks_total": sum(
            (m.get("apply_plane") or {}).get("lease_read_fallbacks", 0)
            for m in live),
    }
    return {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "members": members, "cluster": cluster}


def validate_rollup(data: Dict) -> List[str]:
    """Schema check for the --once --json contract (used by the
    check.sh fleet smoke and the proc e2e test); returns problems,
    empty == valid."""
    probs: List[str] = []
    for key in ("ts", "members", "cluster"):
        if key not in data:
            probs.append(f"missing key {key!r}")
    cl = data.get("cluster", {})
    for key in ("members_live", "leader_balance", "leaders_total",
                "fenced_total", "joint_total", "learners_total",
                "top", "anomalies",
                "invariant_trips_total", "router_loss_total"):
        if key not in cl:
            probs.append(f"cluster missing {key!r}")
    for e in cl.get("top", ()):
        for key in ("group", "lag", "commit", "term", "role", "member"):
            if key not in e:
                probs.append(f"top entry missing {key!r}: {e}")
    for mid, m in data.get("members", {}).items():
        if "err" in m:
            continue
        for key in ("member", "frames", "leaders", "top"):
            if key not in m:
                probs.append(f"member {mid} missing {key!r}")
    return probs


# -- rendering -----------------------------------------------------------------


def render(data: Dict, top: int = 8) -> str:
    cl = data["cluster"]
    lines = [
        f"fleet console @ {data['ts']}  "
        f"members {cl['members_live']}/{cl['members_total']}  "
        f"groups {cl['groups']}  leaders {cl['leaders_total']}  "
        f"fenced {cl['fenced_total']}  "
        f"joint {cl['joint_total']}  learners {cl['learners_total']}  "
        f"leases {cl.get('active_leases_total', 0)}  "
        f"inv-trips "
        f"{'n/a' if cl['invariant_trips_total'] is None else cl['invariant_trips_total']}  "
        f"router-loss {cl['router_loss_total']}",
        "",
        f"{'member':>8} {'frames':>8} {'leaders':>8} {'fenced':>7} "
        f"{'joint':>6} {'lrnr':>5} "
        f"{'lag max':>8} {'inv':>5} {'loss':>6} {'r/fsync':>8} "
        f"{'fsync ms':>9} {'wal seg/MiB':>12} {'snaps':>6} "
        f"{'ring hw':>8} {'kv hw':>9} {'leases':>7} {'watch ev':>9} "
        f"{'rd hit':>7} {'transport':>14}  wal tail / disk state",
    ]
    for mid in sorted(data["members"]):
        m = data["members"][mid]
        if "err" in m:
            lines.append(f"{mid:>8} ERR {m['err']}")
            continue
        wp = m.get("wal_pipeline") or {}
        rpf = (f"{wp.get('rounds_per_fsync', 0):.1f}"
               if wp.get("enabled") else "-")
        limp = m.get("limp") or {}
        ewma = limp.get("fsync_ewma_ms")
        fsync_ms = f"{ewma:.1f}" if ewma is not None else "-"
        # Transport column: fabric kind; for shm, the worst outbound
        # ring's current depth / high-water (KiB) — the backlog signal
        # that precedes ring_full_drop.
        fab = m.get("fabric") or "?"
        lanes = m.get("fabric_lanes") or {}
        if lanes:
            depth = max(v.get("depth", 0) for v in lanes.values())
            hw = max(v.get("high_water", 0) for v in lanes.values())
            fab = f"{fab} {depth // 1024}/{hw // 1024}K"
        # Log-lifecycle columns: segments/MiB on disk, snapshot files,
        # ring-occupancy high-water vs window. "-" when the plane is
        # off or the member predates it.
        lc = m.get("lifecycle") or {}
        ring = m.get("ring") or {}
        if lc.get("enabled"):
            seg = (f"{lc.get('wal_segments', 0)}/"
                   f"{lc.get('wal_bytes', 0) / (1 << 20):.1f}")
            snaps = str(lc.get("snap_files", 0))
        else:
            seg, snaps = "-", "-"
        ring_hw = (f"{ring.get('occ_high_water', 0)}/"
                   f"{ring.get('window', 0)}" if ring else "-")
        # Apply-plane columns (ISSUE 19): KV slot high-water vs
        # capacity, active leases, watch events delivered, and the
        # lease-read hit ratio. "-" when the plane is off or the
        # member predates it.
        ap = m.get("apply_plane") or {}
        if ap.get("enabled"):
            kv_hw = (f"{ap.get('slots_high_water', 0)}/"
                     f"{ap.get('capacity', 0)}")
            if ap.get("overflow_rows", 0):
                kv_hw += "!"
            leases = str(ap.get("active_leases", 0))
            wev = str(ap.get("watch_events", 0))
            reads = (ap.get("lease_read_hits", 0)
                     + ap.get("lease_read_fallbacks", 0))
            rd_hit = (f"{ap.get('lease_read_hits', 0) / reads:.2f}"
                      if reads else "-")
        else:
            kv_hw, leases, wev, rd_hit = "-", "-", "-", "-"
        # The disk-state tail: wal tail classification, plus any live
        # fault-plane condition (limping / disk_full / fail-stop /
        # a pinned WAL backlog and the group pinning it).
        disk = str(m["wal_tail"])
        if limp.get("limping"):
            disk += " LIMPING"
        if m.get("disk_full"):
            disk += " DISK_FULL"
        if m.get("fail_stop"):
            disk += f" FAILSTOP({m['fail_stop']})"
        if lc.get("wal_pinned"):
            disk += (f" WAL_PINNED(g{lc.get('pinned_group')}"
                     f"@seq{lc.get('oldest_pinned_seq')})")
        lines.append(
            f"{m['member']:>8} {m['frames']:>8} {m['leaders']:>8} "
            f"{m['fenced']:>7} {str(m.get('joint')):>6} "
            f"{str(m.get('learners')):>5} {m['lag_max']:>8} "
            f"{str(m['invariant_trips']):>5} "
            f"{str(m['router_loss']):>6} {rpf:>8} {fsync_ms:>9} "
            f"{seg:>12} {snaps:>6} {ring_hw:>8} "
            f"{kv_hw:>9} {leases:>7} {wev:>9} {rd_hit:>7} "
            f"{fab:>14}  {disk}")
    lines.append("")
    lines.append(f"top-{top} laggards (cluster-wide):")
    if cl["top"]:
        lines.append(
            f"{'group':>8} {'member':>7} {'lag':>6} {'commit':>8} "
            f"{'applied':>8} {'term':>6}  role")
        for e in cl["top"]:
            lines.append(
                f"{e['group']:>8} {e['member']:>7} {e['lag']:>6} "
                f"{e['commit']:>8} {e['applied']:>8} {e['term']:>6}  "
                f"{e['role']}")
    else:
        lines.append("  (none — no row has uncommitted backlog)")
    if cl["anomalies"]:
        lines.append("")
        lines.append("anomaly flags: " + ", ".join(
            f"{k}={v}" for k, v in sorted(cl["anomalies"].items())))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="fleet-console",
                                description=__doc__)
    p.add_argument("--admin", action="append", default=[],
                   help="member admin endpoint host:port (repeatable "
                        "or comma-separated)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh interval in seconds")
    p.add_argument("--once", action="store_true",
                   help="scrape and print once, then exit")
    p.add_argument("--json", action="store_true",
                   help="emit the machine-readable rollup instead of "
                        "the table")
    p.add_argument("--top", type=int, default=8,
                   help="laggard rows to show cluster-wide")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    addrs = [a for spec in args.admin for a in spec.split(",") if a]
    if not addrs:
        print("need at least one --admin host:port", file=sys.stderr)
        return 2
    while True:
        data = collect(addrs, timeout=args.timeout, top=args.top)
        if args.json:
            out = json.dumps(data, indent=None if args.once else 1)
        else:
            out = render(data, top=args.top)
        if not args.once:
            # Clear + home, like watch(1): a refreshing console, not a
            # scrolling log.
            sys.stdout.write("\x1b[2J\x1b[H")
        print(out, flush=True)
        if args.once:
            live = data["cluster"]["members_live"]
            return 0 if live == data["cluster"]["members_total"] else 1
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
