#!/usr/bin/env python
"""Rebalance smoke for tools/check.sh (ISSUE 11): boot a tiny in-proc
3-member hosting cluster with the fleet observatory on, seed a gross
leader skew (every group's leadership transferred to member 1), then
run ``rebalancerd --once --json`` against in-process AdminServers and
require it to (a) emit a schema-valid report and (b) converge the
cluster below the skew threshold — a broken fleet signal, admin
transfer op, or rebalance policy fails the static gate, not a live
hosted run. Writes ``artifacts/rebalance_smoke.json`` (seeded-skew
shape, per-pass report, convergence wall time) — the artifact the
BENCH_NOTES rebalance-convergence row cites; lint.yml uploads it on
failure.

``--groups N`` scales the cell (default 24; the BENCH_NOTES row runs
1024).
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys
import tempfile
import time
from contextlib import redirect_stdout

_TOOLS = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_TOOLS))  # repo root: etcd_tpu
sys.path.insert(0, _TOOLS)  # rebalancerd lives beside this script

os.environ.setdefault("JAX_PLATFORMS", "cpu")

R = 3
SKEW_BAR = 1.5  # rebalancerd trigger/convergence threshold


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--groups", type=int, default=24)
    p.add_argument("--out", default="artifacts/rebalance_smoke.json")
    args = p.parse_args(argv)
    g = args.groups

    from etcd_tpu.batched.hosting import MultiRaftCluster
    from etcd_tpu.batched.hosting_proc import AdminServer
    from etcd_tpu.batched.state import BatchedConfig

    import rebalancerd

    cfg = BatchedConfig(
        num_groups=g, num_replicas=R, window=16, max_ents_per_msg=4,
        max_props_per_round=4, election_timeout=10, heartbeat_timeout=1,
        pre_vote=True, check_quorum=True, auto_compact=True,
        telemetry=True, fleet_summary=True,
    )
    tmp = tempfile.mkdtemp(prefix="rebalance_smoke_")
    t_boot = time.monotonic()
    cluster = MultiRaftCluster(tmp, num_members=R, num_groups=g,
                               cfg=cfg)
    admins = []
    try:
        cluster.wait_leaders(timeout=180.0)
        m1 = cluster.members[1]

        # -- seed the skew: every leadership onto member 1 ------------
        t_skew = time.monotonic()
        deadline = t_skew + 120.0
        while time.monotonic() < deadline:
            own = sum(1 for gi in range(g) if m1.is_leader(gi))
            if own == g:
                break
            for gi in range(g):
                for m in cluster.members.values():
                    if m.id != 1 and m.is_leader(gi):
                        m.transfer_leader(gi, 1)
            time.sleep(0.2)
        else:
            print(f"rebalance smoke: seeded skew incomplete "
                  f"({own}/{g} on member 1)", file=sys.stderr)
            return 1

        # Fleet frames must reflect the skew before the daemon reads
        # them (the rollup is the daemon's ONLY input).
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            roll = m1.fleet.snapshot() if m1.fleet else {}
            if roll.get("leaders_total", 0) == g:
                break
            time.sleep(0.2)
        else:
            print("rebalance smoke: fleet rollup never showed the "
                  "seeded skew", file=sys.stderr)
            return 1

        for m in cluster.members.values():
            admins.append(AdminServer(m, cluster.router,
                                      ("127.0.0.1", 0)))
        specs = [f"{m.id}=127.0.0.1:{a.addr[1]}"
                 for m, a in zip(cluster.members.values(), admins)]

        # -- one rebalancerd pass must converge -----------------------
        t_reb = time.monotonic()
        buf = io.StringIO()
        with redirect_stdout(buf):
            # One-shot convergence needs per-pass headroom for ~2G/3
            # moves at scale; the 64-move default cap is the DAEMON's
            # per-interval churn bound, not a one-shot limit.
            rc = rebalancerd.main(
                ["--once", "--json", "--skew-ratio", str(SKEW_BAR),
                 "--max-moves", str(max(64, g))]
                + [x for s in specs for x in ("--admin", s)])
        out = buf.getvalue()
        try:
            report = json.loads(out)
        except ValueError:
            print(f"rebalance smoke: unparseable report: {out[-500:]}",
                  file=sys.stderr)
            return 1
        probs = rebalancerd.validate_report(report)
        if probs:
            print(f"rebalance smoke: invalid report: {probs}",
                  file=sys.stderr)
            return 1
        t_done = time.monotonic()
        artifact = {
            "groups": g,
            "members": R,
            "skew_bar": SKEW_BAR,
            "seed_skew_s": round(t_reb - t_skew, 3),
            "rebalance_s": round(t_done - t_reb, 3),
            "boot_s": round(t_skew - t_boot, 3),
            "report": report,
        }
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as fh:
            json.dump(artifact, fh, indent=1)
            fh.write("\n")
        if rc != 0 or not report["converged"]:
            print(f"rebalance smoke: did not converge "
                  f"(rc={rc}, ratio {report['ratio_before']} -> "
                  f"{report['ratio_after']}, balance "
                  f"{report['balance_after']})", file=sys.stderr)
            return 1
        if not report["triggered"] or report["moved"] == 0:
            print(f"rebalance smoke: seeded skew never triggered "
                  f"moves: {report}", file=sys.stderr)
            return 1
        print(f"rebalance smoke OK: G={g} ratio "
              f"{report['ratio_before']} -> {report['ratio_after']}, "
              f"{report['moved']} moves in {artifact['rebalance_s']}s "
              f"(balance {report['balance_after']})")
        return 0
    finally:
        for a in admins:
            a.close()
        cluster.stop()


if __name__ == "__main__":
    sys.exit(main())
