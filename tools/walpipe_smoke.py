#!/usr/bin/env python
"""WAL-pipeline smoke for tools/check.sh (ISSUE 13): a tiny in-proc
cluster flies with the async group-commit pipeline on (dwell window
armed so coalescing is deterministic), commits a put per group, and the
gate asserts the pipeline actually amortized — fsync coverage (device
rounds per fsync) strictly > 1 on every member — then stops, replays
from the WALs and verifies nothing acked was lost. One tiny compile
(~seconds on CPU); a release-barrier or stop-drain regression fails the
static gate, not a hosted run.

Writes artifacts/walpipe_smoke.json (uploaded by lint.yml on failure).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from etcd_tpu.batched.hosting import MultiRaftCluster  # noqa: E402
from etcd_tpu.batched.state import BatchedConfig  # noqa: E402
from etcd_tpu.pkg import metrics as pmet  # noqa: E402

G, R = 4, 3


OUT = os.path.join("artifacts", "walpipe_smoke.json")


def _fail(report, msg: str) -> int:
    """Report the failure INTO the artifact too: lint.yml uploads it
    under if: failure(), so the forensics must reflect the failing
    run, not a stale prior success."""
    report["ok"] = False
    report["error"] = msg
    _write(report)
    print(f"walpipe smoke: {msg}", file=sys.stderr)
    return 1


def _write(report) -> None:
    os.makedirs("artifacts", exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def main() -> int:
    cfg = BatchedConfig(
        num_groups=G, num_replicas=R, window=8, max_ents_per_msg=2,
        max_props_per_round=2, election_timeout=10,
        heartbeat_timeout=1, pre_vote=True, check_quorum=True,
        auto_compact=True,
    )
    data_dir = tempfile.mkdtemp(prefix="walpipe-smoke-")
    report = {"groups": G, "members": R, "ok": False}
    c = MultiRaftCluster(data_dir, num_members=R, num_groups=G,
                         cfg=cfg, wal_pipeline=True,
                         wal_group_max_delay=0.05)
    try:
        c.wait_leaders(timeout=120.0)
        for g in range(G):
            for i in range(3):
                c.put(g, b"k%d" % i, b"g%d-v%d" % (g, i), timeout=30.0)
        coverage = {}
        for m in c.members.values():
            hp = m.health()["wal_pipeline"]
            coverage[m.id] = hp
        report["coverage"] = {str(k): v for k, v in coverage.items()}
        for mid, hp in coverage.items():
            if not hp["enabled"]:
                return _fail(report, f"member {mid} pipeline OFF")
            if hp["fsyncs"] < 1 or hp["rounds_per_fsync"] <= 1.0:
                return _fail(
                    report,
                    f"member {mid} never amortized an fsync: {hp}")
        text = pmet.DEFAULT.expose()
        missing = [f for f in (
            "etcd_tpu_wal_pipeline_queue_depth",
            "etcd_tpu_wal_pipeline_batches_per_fsync",
            "etcd_tpu_wal_pipeline_bytes_per_fsync",
            "etcd_tpu_wal_pipeline_ack_release_seconds",
        ) if f not in text]
        if missing:
            return _fail(report, f"metric families missing: {missing}")
    finally:
        c.stop()

    # Stop drained the pipeline: a cold replay must serve every put.
    c2 = MultiRaftCluster(data_dir, num_members=R, num_groups=G,
                          cfg=cfg, wal_pipeline=True)
    try:
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(m.get(g, b"k%d" % i) == b"g%d-v%d" % (g, i)
                   for m in c2.members.values()
                   for g in range(G) for i in range(3)):
                break
            time.sleep(0.05)
        else:
            return _fail(report,
                         "acked writes lost across stop+replay")
    finally:
        c2.stop()

    report["ok"] = True
    _write(report)
    rpf = {k: v["rounds_per_fsync"]
           for k, v in report["coverage"].items()}
    print(f"walpipe smoke OK: rounds/fsync per member {rpf}, "
          f"replay clean ({OUT})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
