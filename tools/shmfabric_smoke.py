#!/usr/bin/env python
"""Shm-fabric smoke for tools/check.sh (ISSUE 16): boot a tiny
3-member cluster whose peers talk over the mmap ring fabric (one
ShmFabric per member, all lanes under one shared directory — the same
wiring hosting_proc --fabric=shm uses, minus the processes), drive one
put wave across G=4 groups, and validate the full observability path:
``fleet_console --once --json`` rollup with the shm transport column
populated, per-lane frame counters moving, the etcd_tpu_shm_* metric
families present in the exposition, and zero corrupt/undelivered
frames. A broken ring layout, lane wiring, admin fabric stats, or
console column fails the static gate, not a hosted run. One tiny
compile (G=4); no worker processes.

Writes artifacts/shmfabric_smoke.json (uploaded by lint.yml on
failure).
"""

from __future__ import annotations

import io
import json
import os
import sys
import tempfile
import time
from contextlib import redirect_stdout

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

G, R = 4, 3

OUT = os.path.join("artifacts", "shmfabric_smoke.json")


def _fail(report, msg: str) -> int:
    """Report the failure INTO the artifact too: lint.yml uploads it
    under if: failure(), so the forensics must reflect the failing
    run, not a stale prior success."""
    report["ok"] = False
    report["error"] = msg
    _write(report)
    print(f"shmfabric smoke: {msg}", file=sys.stderr)
    return 1


def _write(report) -> None:
    os.makedirs("artifacts", exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def main() -> int:
    from etcd_tpu.batched.hosting import (
        GroupKV,
        MultiRaftMember,
        wait_group_leaders,
    )
    from etcd_tpu.batched.hosting_proc import AdminServer
    from etcd_tpu.batched.shmfabric import ShmFabric
    from etcd_tpu.batched.state import BatchedConfig
    from etcd_tpu.pkg import metrics as pmet

    import fleet_console

    cfg = BatchedConfig(
        num_groups=G, num_replicas=R, window=8, max_ents_per_msg=2,
        max_props_per_round=2, election_timeout=10,
        heartbeat_timeout=1, pre_vote=True, check_quorum=True,
        auto_compact=True, telemetry=True, fleet_summary=True,
    )
    tmp = tempfile.mkdtemp(prefix="shmfabric_smoke_")
    shm_dir = os.path.join(tmp, "shmfabric")
    report = {"ok": False, "groups": G, "members": R,
              "shm_dir_relpath": "shmfabric"}

    # MultiRaftCluster hard-wires InProcRouter, so build the members by
    # hand: one ShmFabric each, every ordered pair wired as a lane.
    members, fabrics, admins = {}, {}, []
    try:
        for mid in range(1, R + 1):
            m = MultiRaftMember(mid, R, G, tmp, cfg=cfg)
            fab = ShmFabric(m, shm_dir)
            members[mid], fabrics[mid] = m, fab
        for mid, fab in fabrics.items():
            for other in members:
                if other != mid:
                    fab.add_peer(other)
        for m in members.values():
            m.start()

        leads = wait_group_leaders(members.values, G, timeout=120.0)
        report["leaders"] = [int(x) for x in leads]

        # One put wave: a write per group, committed over the rings.
        def put(group: int, key: bytes, value: bytes,
                timeout: float = 30.0) -> bool:
            payload = GroupKV.put_payload(key, value)
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                for m in members.values():
                    if not m.propose(group, payload):
                        continue
                    sub = min(deadline, time.monotonic() + 2.0)
                    while time.monotonic() < sub:
                        if m.get(group, key) == value:
                            return True
                        time.sleep(0.005)
                time.sleep(0.02)
            return False

        for g in range(G):
            if not put(g, b"k%d" % g, b"v%d" % g):
                return _fail(report, f"put for group {g} never committed")

        # Every committed write must be visible on every member — the
        # proof the rings actually replicated, not just elected.
        deadline = time.monotonic() + 60.0
        lagged = True
        while time.monotonic() < deadline and lagged:
            lagged = any(
                m.get(g, b"k%d" % g) != b"v%d" % g
                for m in members.values() for g in range(G))
            if lagged:
                time.sleep(0.05)
        if lagged:
            return _fail(report, "replication over shm never converged")

        # The fabric's own books: frames flowed on live AND bulk rings,
        # and nothing was corrupted or silently dropped.
        lanes = {f"{mid}/{k}": v
                 for mid, fab in fabrics.items()
                 for k, v in fab.lane_stats().items()}
        report["lanes"] = lanes
        if not any(v["frames"] > 0 and k.endswith(":live")
                   for k, v in lanes.items()):
            return _fail(report, f"no live-ring frames: {lanes}")
        if not any(v["frames"] > 0 and k.endswith(":bulk")
                   for k, v in lanes.items()):
            return _fail(report, f"no bulk-ring frames: {lanes}")
        losses = {mid: fab.stats() for mid, fab in fabrics.items()}
        report["losses"] = losses
        for mid, st in losses.items():
            for k in ("recv_corrupt", "deliver_error", "oversize_drop",
                      "no_route"):
                if st.get(k, 0):
                    return _fail(report, f"member {mid} {k}={st[k]}")

        # The shm metric families must be live in the exposition —
        # dump_metrics/--watch consumers see the same registry.
        expo = pmet.DEFAULT.expose()
        for fam in ("etcd_tpu_shm_frames_total",
                    "etcd_tpu_shm_copy_bytes_total",
                    "etcd_tpu_shm_ring_bytes"):
            if f"\n{fam}{{" not in expo and not expo.startswith(
                    f"{fam}{{"):
                return _fail(report, f"{fam} series missing from expose()")

        # At least one summary frame folded per member, then the
        # console rollup end to end (same contract as fleet_smoke).
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(m.fleet is not None and m.fleet.frames() > 0
                   for m in members.values()):
                break
            time.sleep(0.05)
        else:
            return _fail(report, "members never folded a summary frame")

        for mid, m in members.items():
            admins.append(AdminServer(m, fabrics[mid], ("127.0.0.1", 0)))
        addrs = [f"127.0.0.1:{a.addr[1]}" for a in admins]

        deadline = time.monotonic() + 60.0
        while True:
            buf = io.StringIO()
            with redirect_stdout(buf):
                rc = fleet_console.main(
                    ["--once", "--json"]
                    + [x for a in addrs for x in ("--admin", a)])
            if rc != 0:
                return _fail(report, f"console exited {rc}: "
                             f"{buf.getvalue()[-1500:]}")
            data = json.loads(buf.getvalue())
            probs = fleet_console.validate_rollup(data)
            if probs:
                return _fail(report, f"invalid rollup: {probs}")
            cl = data["cluster"]
            if cl["members_live"] != R:
                return _fail(report,
                             f"{cl['members_live']}/{R} members live")
            if cl["leaders_total"] == G:
                break
            if time.monotonic() > deadline:
                return _fail(report, f"leaders_total "
                             f"{cl['leaders_total']} != {G}")
            time.sleep(0.5)
        if cl["invariant_trips_total"] != 0:
            return _fail(report, f"invariant trips "
                         f"{cl['invariant_trips_total']}")

        # Transport column (satellite 4): every member reports the shm
        # fabric kind + per-lane ring stats through the admin 'stats'
        # op, and the rendered table carries it.
        for mid, ent in data["members"].items():
            if ent.get("fabric") != "shm":
                return _fail(report, f"member {mid} fabric != shm: "
                             f"{ent.get('fabric')}")
            if not ent.get("fabric_lanes"):
                return _fail(report,
                             f"member {mid} missing fabric_lanes")
        table = fleet_console.render(data)
        if "shm " not in table:
            return _fail(report, "transport column missing from table")

        report["ok"] = True
        report["rollup"] = cl
        _write(report)
        total = sum(v["frames"] for v in lanes.values())
        print(f"shmfabric smoke OK: {cl['members_live']} members, "
              f"{cl['leaders_total']} leaders over shm, "
              f"{total} ring frames, losses "
              f"{ {m: sum(s.values()) for m, s in losses.items()} }")
        return 0
    finally:
        for a in admins:
            a.close()
        for fab in fabrics.values():
            fab.stop()
        for m in members.values():
            m.stop()


if __name__ == "__main__":
    sys.exit(main())
