#!/usr/bin/env python
"""Disk-fault smoke for tools/check.sh (ISSUE 15): one fsync-error
fail-stop episode and one ENOSPC-recover episode against a tiny in-proc
cluster, asserting the IO-error contract end to end:

* a sticky injected fsync failure kills its member FAIL-STOP (crash-
  shaped death, recorded cause, nothing released from the failed
  window — the doomed write proposed after arming never acks), while
  the survivor quorum keeps serving and loses zero acked writes;
* a sticky injected ENOSPC puts its member into ``disk_full``
  write-back-pressure (health-visible, proposals refuse, member stays
  alive), and healing it recovers in place — zero acked writes lost,
  no crash-loop;
* the ``etcd_tpu_disk_fault_*`` metric families actually move.

One tiny compile (~seconds on CPU); a contract regression fails the
static gate, not a hosted run. Writes artifacts/diskfault_smoke.json
(uploaded by lint.yml on failure).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from etcd_tpu.batched.faults import DiskFaultPlan  # noqa: E402
from etcd_tpu.batched.hosting import MultiRaftCluster  # noqa: E402
from etcd_tpu.batched.state import BatchedConfig  # noqa: E402
from etcd_tpu.pkg import metrics as pmet  # noqa: E402

G, R = 4, 3
OUT = os.path.join("artifacts", "diskfault_smoke.json")


def _write(report) -> None:
    os.makedirs("artifacts", exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def _fail(report, msg: str) -> int:
    report["ok"] = False
    report["error"] = msg
    _write(report)
    print(f"diskfault smoke: {msg}", file=sys.stderr)
    return 1


def _wait(pred, timeout: float) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def main() -> int:
    cfg = BatchedConfig(
        num_groups=G, num_replicas=R, window=8, max_ents_per_msg=2,
        max_props_per_round=2, election_timeout=10,
        heartbeat_timeout=1, pre_vote=True, check_quorum=True,
        auto_compact=True,
    )
    report = {"groups": G, "members": R, "ok": False}
    acked = {}  # (g, key) -> value; every entry must survive

    def put_all(c, tag: str, n: int = 2) -> None:
        for g in range(G):
            for i in range(n):
                k, v = b"%s-k%d" % (tag.encode(), i), b"%s-g%d-v%d" % (
                    tag.encode(), g, i)
                c.put(g, k, v, timeout=60.0)
                acked[(g, k)] = v

    def survivors_hold_everything(members) -> bool:
        return all(m.get(g, k) == v for m in members
                   for (g, k), v in acked.items())

    # -- episode 1: fsync error => fail-stop -----------------------------------
    plan = DiskFaultPlan(seed=15)
    data_dir = tempfile.mkdtemp(prefix="diskfault-smoke-")
    c = MultiRaftCluster(data_dir, num_members=R, num_groups=G,
                         cfg=cfg, disk_fault_hook_fn=plan.hook_for)
    try:
        c.wait_leaders(timeout=120.0)
        put_all(c, "pre")
        victim = c.members[2]
        plan.arm_fsync_error(2, sticky=True)
        # The doomed write: proposed at the victim (if it leads
        # anything) AFTER arming — it must never ack.
        doomed_g = next((g for g in range(G)
                         if victim.is_leader(g)), None)
        if doomed_g is not None:
            victim.propose(doomed_g, b"P" + b"doomed\x00never")
        if not _wait(lambda: victim._stopped.is_set(), 30.0):
            # No organic fsync traffic: force some via the survivors.
            put_all(c, "nudge", n=1)
            if not _wait(lambda: victim._stopped.is_set(), 30.0):
                return _fail(report, "victim never fail-stopped")
        hl = victim.health()
        report["failstop"] = {
            "cause": hl["fail_stop"], "crashed": hl["crashed"],
            "injected": plan.stats(),
        }
        if not (hl["crashed"] and hl["fail_stop"]
                and hl["fail_stop"].startswith("fsync:")):
            return _fail(report, f"not a fail-stop death: {hl}")
        if doomed_g is not None and victim.get(
                doomed_g, b"doomed") is not None:
            return _fail(report,
                         "apply released from the failed fsync window")
        survivors = [m for m in c.members.values() if m.id != 2]
        put_all(c, "post")  # quorum keeps serving
        if not _wait(lambda: survivors_hold_everything(survivors),
                     60.0):
            return _fail(report, "acked writes lost after fail-stop")
    finally:
        c.stop()

    # -- episode 2: ENOSPC => back-pressure, heal => recover -------------------
    plan2 = DiskFaultPlan(seed=16)
    data_dir2 = tempfile.mkdtemp(prefix="diskfault-smoke-enospc-")
    acked.clear()
    c2 = MultiRaftCluster(data_dir2, num_members=R, num_groups=G,
                          cfg=cfg, disk_fault_hook_fn=plan2.hook_for)
    try:
        c2.wait_leaders(timeout=120.0)
        put_all(c2, "pre")
        m1 = c2.members[1]
        plan2.arm_enospc(1)

        def nudge_writes():
            # The hook fires at the WAL seam, so the member must have
            # append traffic to notice the full disk; untracked dummy
            # proposals at every member provide it (the one landing on
            # an m1-led group stalls un-acked behind the dwell, which
            # is the contract).
            for g in range(G):
                for m in c2.members.values():
                    m.propose(g, b"P" + b"nudge\x001")

        if not _wait(lambda: (nudge_writes()
                              or m1.health()["disk_full"]), 30.0):
            return _fail(report, "member never entered disk_full")
        if m1.propose(0, b"P" + b"x\x00y"):
            return _fail(report, "disk_full member accepted a proposal")
        put_all(c2, "mid", n=1)  # quorum serves around the stall
        plan2.heal_enospc(1)
        if not _wait(lambda: not m1.health()["disk_full"], 30.0):
            return _fail(report, "member never left disk_full")
        if m1._stopped.is_set():
            return _fail(report, "ENOSPC crash-looped the member")
        put_all(c2, "post", n=1)
        if not _wait(lambda: survivors_hold_everything(
                c2.members.values()), 60.0):
            return _fail(report, "acked writes lost across ENOSPC")
        report["enospc"] = {
            "injected": plan2.stats(),
            "waits": m1.health()["disk_full_waits"],
        }
        if report["enospc"]["waits"] < 1:
            return _fail(report, "back-pressure dwell never ran")
    finally:
        c2.stop()

    text = pmet.DEFAULT.expose()
    missing = [f for f in (
        "etcd_tpu_disk_fault_failstop_total",
        "etcd_tpu_disk_fault_disk_full",
        "etcd_tpu_disk_fault_injected_total",
    ) if f not in text]
    if missing:
        return _fail(report, f"metric families missing: {missing}")

    report["ok"] = True
    _write(report)
    print(f"diskfault smoke OK: fail-stop cause "
          f"{report['failstop']['cause']!r}, ENOSPC recovered after "
          f"{report['enospc']['waits']} dwells, zero acked loss "
          f"({OUT})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
