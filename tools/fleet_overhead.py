#!/usr/bin/env python
"""Fleet-summary overhead measurement (ISSUE 10 bench honesty).

Measures the closed-loop kernel rate with the fleet observatory
compiled OFF and ON, **interleaved in one process on one box** (the
box drifts tens of percent day to day — BENCH_NOTES discipline: never
compare across runs, always A/B within one), at G=512 and G=1024 on
the canonical bench config (tools/benchlib), and writes
``artifacts/fleet_overhead.json`` — the row ``tools/bench_history.py``
ingests and BENCH_NOTES quotes.

    JAX_PLATFORMS=cpu python tools/fleet_overhead.py [--reps 3]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _note(msg: str) -> None:
    print(f"[fleet_overhead {time.strftime('%H:%M:%S')}] {msg}",
          file=sys.stderr, flush=True)


def measure_cell(groups: int, reps: int) -> Dict:
    """One A/B cell: build both engines, then alternate off/on rate
    measurements so box drift hits both arms equally."""
    from etcd_tpu.tools.benchlib import make_bench_engine, measure_rate

    t0 = time.perf_counter()
    eng_off, props_off = make_bench_engine(groups, lanes_minor=False,
                                           fleet=False)
    eng_on, props_on = make_bench_engine(groups, lanes_minor=False,
                                         fleet=True)
    _note(f"G={groups}: engines built+compiled in "
          f"{time.perf_counter() - t0:.1f}s")
    off: List[float] = []
    on: List[float] = []
    for i in range(reps):
        off.append(measure_rate(eng_off, props_off, 8, 2))
        on.append(measure_rate(eng_on, props_on, 8, 2))
        _note(f"G={groups} rep {i + 1}/{reps}: off {off[-1]:.0f} "
              f"on {on[-1]:.0f} group-rounds/s")
    off_med = statistics.median(off)
    on_med = statistics.median(on)
    # Overhead = MEDIAN OF THE PER-REP PAIRWISE RATIOS, not the ratio
    # of medians: this 2-core box load-flakes by tens of percent, and
    # a spike landing on one arm of one rep would otherwise dominate
    # the cross-arm medians (each rep's off/on pair runs back to back,
    # so within a pair the load is as equal as it gets).
    pair_pct = [(o - n) / o * 100 for o, n in zip(off, on)]
    return {
        "groups": groups,
        "reps": reps,
        "off_rates": [round(x, 1) for x in off],
        "on_rates": [round(x, 1) for x in on],
        "off_median": round(off_med, 1),
        "on_median": round(on_med, 1),
        "pairwise_pct": [round(x, 2) for x in pair_pct],
        # Positive = fleet summary costs throughput.
        "overhead_pct": round(statistics.median(pair_pct), 2),
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="fleet-overhead",
                                description=__doc__)
    p.add_argument("--reps", type=int, default=3,
                   help="interleaved A/B repetitions per cell")
    p.add_argument("--groups", default="512,1024",
                   help="comma-separated G cells")
    p.add_argument("--out", default="artifacts/fleet_overhead.json")
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("ETCD_TPU_TRANSFER_GUARD", "disallow")
    import jax

    platform = jax.devices()[0].platform
    cells = [measure_cell(int(g), args.reps)
             for g in args.groups.split(",")]
    payload = {
        "metric": "fleet_summary_overhead",
        "platform": platform,
        "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "method": ("interleaved on/off measure_rate(8x2) in one "
                   "process (benchlib canonical config, layout=major); "
                   "medians of the A/B pairs — same-box same-minute, "
                   "so day-to-day box drift cancels"),
        "cells": cells,
    }
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    for c in cells:
        print(f"G={c['groups']}: off {c['off_median']:.0f} vs on "
              f"{c['on_median']:.0f} group-rounds/s -> overhead "
              f"{c['overhead_pct']:+.2f}%")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
