#!/usr/bin/env bash
# One-shot static gate (ISSUE 7, grown by ISSUEs 9/10): ruff + jitlint
# + runtime-sentinel smoke (transfer guard, recompile budget, lock
# order) + trace smoke (one traced in-proc round, exporter validated)
# + fleet smoke (tiny in-proc cluster with the fleet observatory on,
# fleet_console --once --json validated) + rebalance smoke (seeded
# leader skew, rebalancerd --once --json must converge it) + walpipe
# smoke (async group-commit WAL pipeline: fsync coverage > 1, clean
# stop-drain replay) + diskfault smoke (ISSUE 15 IO-error contract:
# fsync-error fail-stop + ENOSPC back-pressure recover, zero acked
# loss) + shmfabric smoke (ISSUE 16 mmap ring transport: 3-member shm
# cluster, put wave, console transport column + shm metric families)
# + lifecycle smoke (ISSUE 17 log-lifecycle plane: rotation, cadence
# snapshots, fleet-min release, restart replay from snapshot files)
# + applyplane smoke (ISSUE 19 device apply plane: lease-hit read,
# watch frame, TTL expiry on the plane clock, transfer fallback)
# + bench-history re-emit. CI
# runs exactly this script
# (.github/workflows/lint.yml); run it locally before pushing anything
# that touches the batched hot path.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ruff (ruff.toml: error-class rules over the hot-path scope) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check .
elif python -m ruff --version >/dev/null 2>&1; then
    python -m ruff check .
else
    echo "ruff not installed in this environment -- SKIPPED (CI enforces it)"
fi

echo "== jitlint (trace safety / dtype discipline / purity) =="
python tools/jitlint.py \
    etcd_tpu/batched/ etcd_tpu/analysis/ etcd_tpu/tools/ tools/ bench.py

echo "== sentinel smoke (transfer guard, recompile budget, lock order) =="
python -m pytest tests/analysis tests/batched/test_sentinels.py -q

echo "== trace smoke (one traced in-proc round, exporter validates) =="
python tools/trace_smoke.py

echo "== fleet smoke (in-proc cluster with fleet on, console --once --json) =="
python tools/fleet_smoke.py

echo "== rebalance smoke (seeded leader skew, rebalancerd --once --json) =="
python tools/rebalance_smoke.py

echo "== walpipe smoke (async group-commit WAL pipeline, fsync coverage > 1) =="
python tools/walpipe_smoke.py

echo "== diskfault smoke (fsync-error fail-stop + ENOSPC recover, IO-error contract) =="
python tools/diskfault_smoke.py

echo "== fused-round smoke (all deliver shapes agree, transfer guard disallow) =="
python tools/fused_smoke.py

echo "== shmfabric smoke (3-member shm ring cluster, console transport column) =="
python tools/shmfabric_smoke.py

echo "== lifecycle smoke (WAL rotation -> cadence snapshot -> release -> replay) =="
python tools/lifecycle_smoke.py

echo "== applyplane smoke (lease-hit read, watch frame, TTL expiry, transfer fallback) =="
python tools/applyplane_smoke.py

echo "== bench history (artifacts/bench_history.json + BENCH_HISTORY.md) =="
python tools/bench_history.py

echo "check.sh: all gates green"
