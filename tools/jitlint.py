#!/usr/bin/env python3
"""jitlint CLI: trace-safety / dtype-discipline lint for the batched
hot path.

    python tools/jitlint.py etcd_tpu/batched/            # the gate
    python tools/jitlint.py --list-rules
    python tools/jitlint.py --format json etcd_tpu/batched/step.py

Exit code 0 iff there are zero unwaived findings. Pure AST — no jax,
no backend, safe anywhere (CI included). Waive a finding with an
inline comment reading `jitlint: waive(<rule>) -- <reason>`; see
etcd_tpu/analysis/jitlint.py for the rule catalog and README "Static
analysis & sentinels".
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from etcd_tpu.analysis import jitlint  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", default=[],
                    help="files or directories to lint")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-waived", action="store_true",
                    help="also print waived findings (audit mode)")
    args = ap.parse_args()

    if args.list_rules:
        for rule, doc in sorted(jitlint.RULES.items()):
            print(f"{rule:20s} {doc}")
        return 0
    if not args.paths:
        ap.error("no paths given (try: tools/jitlint.py etcd_tpu/batched/)")

    try:
        files = jitlint.collect_files(args.paths)
    except FileNotFoundError as e:
        print(e, file=sys.stderr)
        return 2
    if not files:
        print(f"jitlint: no .py files under {args.paths} — refusing to "
              "pass a vacuous gate", file=sys.stderr)
        return 2
    findings = jitlint.lint_paths(files)
    unwaived = [f for f in findings if not f.waived]
    waived = [f for f in findings if f.waived]

    if args.format == "json":
        print(json.dumps([f.__dict__ for f in findings], indent=1))
    else:
        for f in unwaived:
            print(f.format())
        if args.show_waived:
            for f in waived:
                print(f.format())
        print(f"jitlint: {len(unwaived)} finding(s), "
              f"{len(waived)} waived", file=sys.stderr)
    return 1 if unwaived else 0


if __name__ == "__main__":
    sys.exit(main())
