#!/usr/bin/env python
"""Fused-round smoke (ISSUE 14): tiny-G cross-check of every shipped
deliver shape under the transfer guard.

One engine per deliver shape (lanes | merged | vectorized) drives an
identical schedule — contested election, steady proposals, a
partition round, a ReadIndex batch — with every warm dispatch inside
``ETCD_TPU_TRANSFER_GUARD=disallow``. The three end states must agree
on every protocol field, commits must have advanced, and the ReadIndex
batch must have confirmed. This is the check.sh/CI face of the
equivalence contract; the full seeded suites live in
tests/batched/test_deliver_shapes.py and test_differential.py.

    python tools/fused_smoke.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("ETCD_TPU_TRANSFER_GUARD", "disallow")

G, R = 4, 3


def main() -> int:
    import jax.numpy as jnp
    import numpy as np

    from etcd_tpu.batched import BatchedConfig, MultiRaftEngine
    from etcd_tpu.batched.state import DELIVER_SHAPES

    engines = {}
    for shape in DELIVER_SHAPES:
        cfg = BatchedConfig(
            num_groups=G, num_replicas=R, window=32,
            max_ents_per_msg=4, max_props_per_round=2,
            election_timeout=1 << 20, heartbeat_timeout=1,
            deliver_shape=shape,
        )
        engines[shape] = MultiRaftEngine(cfg)

    n = G * R
    camp = np.zeros(n, bool)
    camp[[g * R + g % R for g in range(G)]] = True
    # Contested re-election in group 0: BOTH followers campaign in the
    # same round (split self-votes; the shared voter breaks the tie by
    # sender order) — the vote-lane tournament and the tally fold must
    # resolve it exactly like the sequential scans.
    camp2 = np.zeros(n, bool)
    camp2[[1, 2]] = True
    props = jnp.zeros((n,), jnp.int32)
    props = props.at[jnp.asarray([g * R + g % R for g in range(G)])].set(2)
    iso = np.zeros(n, bool)
    iso[0] = True
    read = np.zeros(n, bool)
    read[[g * R + g % R for g in range(G)]] = True

    def drive(eng):
        eng.step_round(campaign_mask=jnp.asarray(camp))
        for _ in range(3):
            eng.step_round()
        eng.step_round(propose_n=props)
        for _ in range(2):
            eng.step_round()
        eng.step_round(campaign_mask=jnp.asarray(camp2))
        for _ in range(3):
            eng.step_round()
        eng.step_round(propose_n=props, isolate=jnp.asarray(iso))
        for _ in range(2):
            eng.step_round()
        eng.step_round(read_req=jnp.asarray(read))
        for _ in range(3):
            eng.step_round()

    for shape, eng in engines.items():
        drive(eng)

    fields = ("term", "vote", "role", "lead", "commit", "last",
              "match", "next", "read_seq", "read_ready", "snap_index")
    ref = engines[DELIVER_SHAPES[0]]
    for shape, eng in engines.items():
        for f in fields:
            # jitlint: waive(sync-in-loop) -- end-of-smoke differential gate, not a hot path: one bulk gather per compared field
            a = np.asarray(getattr(ref.state, f))
            # jitlint: waive(sync-in-loop) -- same differential gate gather as above
            b = np.asarray(getattr(eng.state, f))
            assert (a == b).all(), (
                f"fused smoke: {shape} diverges from "
                f"{DELIVER_SHAPES[0]} on {f}:\n{a}\nvs\n{b}")
        commits = eng.commits()
        assert commits.min() >= 2, (shape, commits)
        # Group 0's contested re-election must have produced a new
        # leader at a higher term (sender-order tie-break: slot 1).
        # jitlint: waive(sync-in-loop) -- end-of-smoke assertion gather, not a hot path
        role = np.asarray(eng.state.role)
        # jitlint: waive(sync-in-loop) -- same end-of-smoke assertion gather
        assert role[1] == 2 and np.asarray(eng.state.term)[1] >= 2, (
            shape, role[:3])
        _seq, idx, ready = eng.read_states()
        # Groups 1.. kept their seeded leaders (group 0's read lands
        # on a deposed row and is a no-op — also identical per shape).
        lead_rows = [g * R + g % R for g in range(1, G)]
        assert all(ready[i] for i in lead_rows), (shape, ready)
        assert all(idx[i] >= 0 for i in lead_rows)

    print(json.dumps({
        "fused_smoke": "ok",
        "shapes": list(DELIVER_SHAPES),
        "groups": G,
        "commit_min": int(ref.commits().min()),
        "transfer_guard": os.environ["ETCD_TPU_TRANSFER_GUARD"],
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
