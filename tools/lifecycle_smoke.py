#!/usr/bin/env python
"""Log-lifecycle smoke for tools/check.sh (ISSUE 17): a tiny in-proc
cluster runs with aggressive lifecycle knobs (snapshot every 2 applied
entries, rotate the WAL tail past ~1 KiB), pumps writes until every
member has cut a segment, built a cadence file snapshot AND released a
sealed segment, checks retention (never more than snap_keep files per
group dir), then stops and cold-restarts: the replay must come back
through the file snapshots + the rotated tail with every acked write
served. One tiny compile (~seconds on CPU); a rotation, release-gating
or marker-replay regression fails the static gate, not a hosted run.

Writes artifacts/lifecycle_smoke.json (uploaded by lint.yml on
failure).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from etcd_tpu.batched.hosting import MultiRaftCluster  # noqa: E402
from etcd_tpu.batched.state import BatchedConfig  # noqa: E402

G, R = 4, 3
SNAP_CADENCE = 2
ROTATE_BYTES = 1024

OUT = os.path.join("artifacts", "lifecycle_smoke.json")


def _fail(report, msg: str) -> int:
    """Report the failure INTO the artifact too: lint.yml uploads it
    under if: failure(), so the forensics must reflect the failing
    run, not a stale prior success."""
    report["ok"] = False
    report["error"] = msg
    _write(report)
    print(f"lifecycle smoke: {msg}", file=sys.stderr)
    return 1


def _write(report) -> None:
    os.makedirs("artifacts", exist_ok=True)
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
        f.write("\n")


def main() -> int:
    cfg = BatchedConfig(
        num_groups=G, num_replicas=R, window=8, max_ents_per_msg=2,
        max_props_per_round=2, election_timeout=10,
        heartbeat_timeout=1, pre_vote=True, check_quorum=True,
        auto_compact=True,
    )
    data_dir = tempfile.mkdtemp(prefix="lifecycle-smoke-")
    report = {"groups": G, "members": R, "ok": False,
              "snap_cadence": SNAP_CADENCE,
              "wal_rotate_bytes": ROTATE_BYTES}
    written = {}
    c = MultiRaftCluster(data_dir, num_members=R, num_groups=G,
                         cfg=cfg, snap_cadence=SNAP_CADENCE,
                         wal_rotate_bytes=ROTATE_BYTES)
    try:
        c.wait_leaders(timeout=120.0)

        def lifecycle_done() -> bool:
            for m in c.members.values():
                lc = m.health()["lifecycle"]
                if not (lc["wal_cuts"] > 0
                        and lc["snapshots_built"] > 0
                        and lc["segments_released"] > 0):
                    return False
            return True

        # Pump acked writes until the full cut -> snapshot -> release
        # loop has turned over on every member.
        deadline = time.monotonic() + 120.0
        i = 0
        while not lifecycle_done():
            if time.monotonic() > deadline:
                return _fail(report, "lifecycle loop never completed: "
                             + json.dumps({
                                 str(m.id): m.health()["lifecycle"]
                                 for m in c.members.values()}))
            for g in range(G):
                k, v = b"k%d" % i, b"g%d-v%d" % (g, i)
                c.put(g, k, v, timeout=30.0)
                written[(g, k)] = v
            i += 1
        report["put_passes"] = i
        report["lifecycle"] = {
            str(m.id): m.health()["lifecycle"]
            for m in c.members.values()}

        # Retention: never more than snap_keep .snap files per group.
        for m in c.members.values():
            snap_root = os.path.join(m.dir, "snap")
            if not os.path.isdir(snap_root):
                return _fail(report,
                             f"member {m.id} built no snapshot dirs")
            for sub in sorted(os.listdir(snap_root)):
                files = [n for n in
                         os.listdir(os.path.join(snap_root, sub))
                         if n.endswith(".snap")]
                if len(files) > m.snap_keep:
                    return _fail(
                        report,
                        f"retention leak: member {m.id} {sub} holds "
                        f"{files}")
    finally:
        c.stop()

    # Cold restart: replay comes back through file snapshots + the
    # rotated tail; every acked write must be served again.
    c2 = MultiRaftCluster(data_dir, num_members=R, num_groups=G,
                          cfg=cfg, snap_cadence=SNAP_CADENCE,
                          wal_rotate_bytes=ROTATE_BYTES)
    try:
        for m in c2.members.values():
            if int(m._snap_file_idx.max()) <= 0:
                return _fail(
                    report,
                    f"member {m.id} replay found no file snapshots "
                    "despite fsync'd markers")
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(m.get(g, k) == v
                   for m in c2.members.values()
                   for (g, k), v in written.items()):
                break
            time.sleep(0.05)
        else:
            return _fail(report,
                         "acked writes lost across stop+replay")
        report["replay"] = {
            str(m.id): {
                "snap_file_idx_max": int(m._snap_file_idx.max()),
                "wal_segments":
                    m.health()["lifecycle"]["wal_segments"],
            } for m in c2.members.values()}
    finally:
        c2.stop()

    report["ok"] = True
    _write(report)
    rel = {k: v["segments_released"]
           for k, v in report["lifecycle"].items()}
    print(f"lifecycle smoke OK: released segments per member {rel}, "
          f"replay from snapshots clean ({OUT})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
