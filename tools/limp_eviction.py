#!/usr/bin/env python
"""Gray-failure eviction demo (ISSUE 15): seeded limp -> counted
``member_limping`` anomaly -> rebalancer drains leadership -> commit
p50 recovers. Detection to eviction as ONE measured loop, captured as
an artifact.

Method: a 3-member in-proc cluster (fleet observatory on) with every
leadership seeded onto one member. Phase A measures commit p50 healthy.
The victim's disk is then made to LIMP (an injected per-fsync delay at
the DiskFaultPlan seam — the member stays alive, correct, and slow: the
HotOS'17 gray-failure shape). Phase B measures the degraded p50 — every
commit now waits the limping leader's fsync. The member's own fleet hub
raises ``member_limping`` from the fsync-latency stream, the rebalancer
consumes it and drains every leadership off the victim, and phase C
measures p50 again — the limping member is a follower now, off every
commit's critical path, so the healthy quorum sets the pace.

Writes ``artifacts/limp_eviction_r15.json`` (phase p50/p99s, anomaly
counts, eviction report + wall time) — the BENCH_NOTES gray-failure
row cites it. ``--groups`` scales the cell (default 32).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

R = 3
VICTIM = 1
LIMP_S = 0.030  # 30ms injected fsync delay: cloud/HDD-class slow disk


def put_p50(cluster, groups, tag, n=60, timeout=30.0):
    """Commit latency distribution of n sequential puts round-robined
    over the groups (find-leader + propose + poll-apply, the
    MultiRaftCluster.put discipline, timed per put)."""
    lat = []
    for i in range(n):
        g = groups[i % len(groups)]
        t0 = time.perf_counter()
        cluster.put(g, b"%s-%d" % (tag.encode(), i),
                    b"v%d" % i, timeout=timeout)
        lat.append((time.perf_counter() - t0) * 1e3)
    lat.sort()
    return {
        "n": n,
        "p50_ms": round(lat[len(lat) // 2], 2),
        "p99_ms": round(lat[min(len(lat) - 1,
                                int(len(lat) * 0.99))], 2),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--groups", type=int, default=32)
    p.add_argument("--out", default="artifacts/limp_eviction_r15.json")
    args = p.parse_args(argv)
    g = args.groups

    from etcd_tpu.batched.faults import DiskFaultPlan
    from etcd_tpu.batched.hosting import MultiRaftCluster
    from etcd_tpu.batched.rebalance import (
        InProcActuator,
        RebalanceConfig,
        Rebalancer,
    )
    from etcd_tpu.batched.state import BatchedConfig

    cfg = BatchedConfig(
        num_groups=g, num_replicas=R, window=16, max_ents_per_msg=4,
        max_props_per_round=4, election_timeout=10,
        heartbeat_timeout=1, pre_vote=True, check_quorum=True,
        auto_compact=True, telemetry=True, fleet_summary=True,
    )
    plan = DiskFaultPlan(seed=15)
    tmp = tempfile.mkdtemp(prefix="limp_eviction_")
    c = MultiRaftCluster(tmp, num_members=R, num_groups=g, cfg=cfg,
                         disk_fault_hook_fn=plan.hook_for)
    artifact = {"groups": g, "members": R, "victim": VICTIM,
                "limp_fsync_s": LIMP_S, "ok": False}
    try:
        c.wait_leaders(timeout=180.0)
        victim = c.members[VICTIM]
        # Seed every leadership onto the victim: the worst case the
        # detector exists for — a limping member on EVERY commit path.
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            own = sum(1 for gi in range(g) if victim.is_leader(gi))
            if own == g:
                break
            for gi in range(g):
                for m in c.members.values():
                    if m.id != VICTIM and m.is_leader(gi):
                        m.transfer_leader(gi, VICTIM)
            time.sleep(0.2)
        if own != g:
            print(f"seeding incomplete ({own}/{g})", file=sys.stderr)
            return 1

        all_groups = list(range(g))
        artifact["phase_a_healthy"] = put_p50(c, all_groups, "a")

        # Limp the victim; sensitize the detector to the test cadence.
        for m in c.members.values():
            m.fleet.limp_ms = 10.0
            m.fleet.limp_ops = 4
        plan.set_limp(VICTIM, LIMP_S)
        artifact["phase_b_limping"] = put_p50(c, all_groups, "b")
        anom = victim.fleet.anomalies()
        artifact["anomalies_after_limp"] = anom
        artifact["limp_state"] = victim.fleet.limp_state()
        if anom.get("member_limping", 0) < 1:
            print(f"member_limping never raised: {anom}",
                  file=sys.stderr)
            return 1

        # Eviction: the rebalancer consumes the anomaly/level signal.
        t_evict = time.monotonic()
        reb = Rebalancer(
            InProcActuator(c.members),
            RebalanceConfig(skew_ratio=1.5, cooldown_s=1.0,
                            max_moves_per_pass=g, transfer_wait_s=10.0,
                            min_groups=8))
        reports = []
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            rep = reb.run_once()
            reports.append({k: rep[k] for k in (
                "triggered", "moved", "failed", "limping",
                "balance_after", "converged")})
            led = sum(1 for gi in range(g) if victim.is_leader(gi))
            if led == 0 and rep["converged"]:
                break
            time.sleep(0.5)
        artifact["evict_wall_s"] = round(time.monotonic() - t_evict, 3)
        artifact["evict_passes"] = reports
        led = sum(1 for gi in range(g) if victim.is_leader(gi))
        if led != 0:
            print(f"victim still leads {led} groups", file=sys.stderr)
            artifact["victim_still_leads"] = led
            _dump(args.out, artifact)
            return 1

        # Phase C: victim still LIMPING (fault not healed!) but off
        # the critical path — the healthy quorum sets the pace.
        artifact["phase_c_evicted"] = put_p50(c, all_groups, "c")
        artifact["invariant_trips"] = sum(
            m.hub.trips() for m in c.members.values()
            if m.hub is not None)
        a = artifact["phase_a_healthy"]["p50_ms"]
        b = artifact["phase_b_limping"]["p50_ms"]
        cc = artifact["phase_c_evicted"]["p50_ms"]
        # The loop is demonstrated when the limp visibly degraded p50
        # and eviction recovered most of it (midpoint bar: generous to
        # box noise, impossible to pass without a real recovery).
        artifact["ok"] = (b > a * 1.5 and cc < (a + b) / 2
                         and artifact["invariant_trips"] == 0)
        _dump(args.out, artifact)
        print(f"limp eviction: p50 healthy {a}ms -> limping {b}ms -> "
              f"evicted {cc}ms (victim still limping, off the commit "
              f"path); {artifact['evict_wall_s']}s detection-to-"
              f"eviction; trips={artifact['invariant_trips']} "
              f"({args.out})")
        return 0 if artifact["ok"] else 1
    finally:
        c.stop()


def _dump(path, artifact) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as fh:
        json.dump(artifact, fh, indent=1)
        fh.write("\n")


if __name__ == "__main__":
    sys.exit(main())
