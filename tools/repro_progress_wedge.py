"""Reproducer: restarted-member progress wedge on the TCP hosting path.

STATUS: FIXED (ISSUE 4). Root cause, found with the kernel telemetry
invariant sweep (etcd_tpu/batched/telemetry.py): a torn-tail follower
rejecting the leader's probe at ``next-1`` with a hint below the
leader's stale-high ``match`` drove ``next = hint+1 <= match`` — an
illegal progress state — after which every re-ack at-or-below
``match`` failed ``updated = match < m.index`` in
``step._leader_app_resp`` and was dropped wholesale: ``next`` froze,
``probe_sent`` pinned, the missing suffix was never re-sent. The
kernel now repairs ``match`` downward from the rejection evidence.
This script stays as the manual stochastic driver (the deterministic
kernel-level regression lives in
``tests/batched/test_progress_wedge.py``).

The wedge verdict is the on-device invariant sweep (the pre-fix wedge
trips ``next_le_match``/``probe_wedge`` persistently), plus
quorum-level hash parity. STRICT parity is not asserted: this
scenario tears fsync'd acked bytes, and a torn member that wins an
election can force a survivor to overwrite an entry it already
applied — an out-of-contract KV divergence no protocol heals (found
with the flight recorder; see faults.run_invariant_checks).

Original symptom notes below, kept for archaeology.

Found by the ISSUE 2 chaos harness. Symptom: after a chaos episode with
member restarts over TCP, one (group, follower) pair wedges — the
follower sits a suffix behind forever while the leader never re-sends.

Signature on the leader (observed via rn.state at stuck time):

* ``next[slot] == match[slot]`` — an ILLEGAL raft progress state
  (next must always be >= match + 1),
* ``pr_state[slot] == PROBE`` with ``probe_sent[slot]`` pinned True,
* zero object-path (T_APP/T_SNAP) messages emitted toward the lagger
  (verified by spying member._send), while block-path heartbeats flow
  both ways and the lagger's hb_resp + stale app_resp records verifiably
  arrive at the leader's deliver_block every tick.

Exonerated by instrumentation (see CHANGES.md PR 2):

* transport: sender-lane queues empty, frames delivered, the TCP
  self-connect bug is fixed and counted (stats()['self_connect']);
* host staging: records pass validate_block into the dense inbox;
* remediation: poke_append, a fresh write to the group, and
  transfer_leader all fail to unwedge; a SYNTHETIC object-path
  MsgHeartbeatResp injected straight into rn.step() also fails —
  the wedge is in the device round's resp->probe->emit interplay.

Run (fails with a diagnostic dump when the wedge reproduces; ~10-30%
of attempts on a loaded CPU):

    JAX_PLATFORMS=cpu python tools/repro_progress_wedge.py

--torn-acked (ISSUE 5): deterministic driver for the OTHER torn-tail
failure — the out-of-contract divergence itself. It commits writes on a
2/3 quorum with the third member partitioned, crashes that quorum,
tears one member's fsync'd acked entry mid-record, then lets the torn
member campaign against the stale third member. With the durability
fence DISABLED (the default for this mode — the point is keeping the
pre-fix failure demonstrable), the torn member wins and the strict
checkers report the divergence; with --fence the member boots fenced,
never wins, and the strict checkers pass:

    JAX_PLATFORMS=cpu python tools/repro_progress_wedge.py --torn-acked
    JAX_PLATFORMS=cpu python tools/repro_progress_wedge.py --torn-acked --fence
"""

import argparse
import sys
import tempfile
import time

sys.path.insert(0, ".")

import numpy as np  # noqa: E402

from etcd_tpu.batched.faults import ChaosHarness, FaultSpec  # noqa: E402
from etcd_tpu.functional import multiraft_hash_check  # noqa: E402
from etcd_tpu.functional.checker import committed_never_lost  # noqa: E402


def main(attempts: int = 10, base_seed: int = 424242) -> int:
    spec = FaultSpec(drop=0.06, dup=0.06, delay=0.1,
                     delay_max_s=0.05, reorder=0.25)
    for attempt in range(attempts):
        d = tempfile.mkdtemp(prefix="wedge-repro-")
        h = ChaosHarness(d, seed=base_seed + attempt, spec=spec,
                         num_members=3, num_groups=12, transport="tcp")
        try:
            h.wait_leaders()
            h.run_workload(15, prefix=b"vfy")
            h.crash_on_failpoint(2, "after_save")
            h.run_workload(6, prefix=b"mid", per_put_timeout=15.0)
            h.restart(2)
            h.wait_leaders()
            h.crash(3)
            h.torn_tail(3)
            h.restart(3)
            h.wait_leaders()
            h.touch_all_groups()
            h.plan.quiesce()
            try:
                multiraft_hash_check(h.alive(), timeout=25.0,
                                     allow_lag=1)
                trips = h.invariant_trips()
                assert trips == 0, (
                    f"{trips} illegal-progress invariant trips "
                    "(flight recorders dumped to artifacts/)")
                print(f"attempt {attempt}: converged, invariant "
                      "sweep clean")
            except AssertionError as e:
                h.dump_flight_recorders(reason="wedge-repro")
                print(f"attempt {attempt}: WEDGED -> {e}")
                applied = np.stack(
                    [m.applied_index for m in h.alive()])
                g = int(np.nonzero(
                    (applied != applied[0]).any(axis=0))[0][0])
                _t, _r, lead = h.members[1].rn.m_view
                leader = h.members[int(lead[g])]
                lagger = min(h.alive(),
                             key=lambda m: int(m.applied_index[g]))
                st = leader.rn.state
                print(f"  g{g} leader=m{leader.id} lagger=m{lagger.id}")
                print(f"  match={np.asarray(st.match[g]).tolist()} "
                      f"next={np.asarray(st.next[g]).tolist()} "
                      f"pr_state={np.asarray(st.pr_state[g]).tolist()} "
                      f"probe_sent="
                      f"{np.asarray(st.probe_sent[g]).tolist()} "
                      f"snap_index={int(np.asarray(st.snap_index[g]))}")
                return 1
        finally:
            h.stop()
    print("no repro — wedge is timing-dependent; re-run or raise "
          "attempts")
    return 0


def torn_acked(fence: bool, seed: int = 31337,
               groups: int = 4) -> int:
    """Reproduce (fence=False) or prove healed (fence=True) the
    torn-ACKED-bytes divergence. Exit 0 = the mode's expectation held:
    divergence demonstrated without the fence, strict parity with it."""
    d = tempfile.mkdtemp(prefix="torn-acked-")
    h = ChaosHarness(d, seed=seed, spec=FaultSpec(), num_members=3,
                     num_groups=groups, transport="inproc", fence=fence)
    try:
        h.wait_leaders()
        # Park leadership of every group on member 1, then cut member 3
        # off: the coming writes commit on the {1, 2} quorum only.
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            moved = 0
            for g in range(groups):
                lead = h.members[1].leader_of(g)
                if lead and lead != 1:
                    h.members[lead].transfer_leader(g, 1)
                    moved += 1
            if moved == 0 and all(
                    h.members[1].is_leader(g) for g in range(groups)):
                break
            time.sleep(0.2)
        h.plan.isolate_member(3, h.members.keys())
        for g in range(groups):
            assert h.put(g, b"acked-%d" % g, b"v-%d" % g, timeout=15.0), g
        # Crash the whole committing quorum; destroy member 2's fsync'd
        # acked tail mid-record. Member 3 never saw the writes, member 2
        # no longer durably holds them — only member 1 does.
        h.crash(1)
        h.crash(2)
        chop, torn_g = h.torn_acked_tail(2)
        assert chop > 0, "no acked entry record in member 2's tail"
        h.plan.heal_all()
        # Restart the torn member FIRST and let it campaign against the
        # stale member 3 (member 1 — the only intact holder — stays
        # down, so {2, 3} is the electing quorum).
        m2 = h.restart(2)
        fenced_at_boot = int(np.count_nonzero(m2._fenced))
        print(f"member 2 rebooted: tail={m2.health()['wal_tail']} "
              f"fenced_groups={fenced_at_boot} (torn group {torn_g})")
        deadline = time.monotonic() + 20.0
        won = 0
        while time.monotonic() < deadline:
            m2.campaign(np.arange(groups))
            won = sum(m2.is_leader(g) for g in range(groups))
            if fence and won == 0 and time.monotonic() > deadline - 15.0:
                break  # fenced: campaigns stay suppressed
            if not fence and won == groups:
                break
            time.sleep(0.2)
        print(f"member 2 leads {won}/{groups} group(s) "
              f"({'fence ON' if fence else 'fence OFF'})")
        h.restart(1)
        h.wait_leaders()
        h.touch_all_groups()
        h.plan.quiesce()
        try:
            multiraft_hash_check(h.alive(), timeout=30.0)
            committed_never_lost(h.alive(), h.acked, timeout=20.0,
                                 history=h.acked_history)
            diverged = False
        except AssertionError as e:
            diverged = True
            print(f"strict checkers FAILED: {e}")
        if fence:
            if diverged:
                print("UNEXPECTED: divergence despite the fence")
                return 1
            print("fence held: torn member never campaigned, strict "
                  "parity restored")
            return 0
        if not diverged:
            print("no divergence this run — the torn entries were "
                  "re-replicated before an election landed; re-run "
                  "or raise --groups")
            return 1
        print("pre-fix divergence reproduced: the torn member's "
              "shortened log displaced committed-and-applied state "
              "(run with --fence to see the ISSUE 5 fence close it)")
        return 0
    finally:
        h.stop()


def _cli(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--torn-acked", action="store_true",
                   help="drive the torn-ACKED-bytes divergence instead "
                        "of the (fixed) progress wedge")
    p.add_argument("--fence", action="store_true",
                   help="with --torn-acked: enable the durability "
                        "fence (expect strict parity instead of the "
                        "divergence)")
    p.add_argument("--attempts", type=int, default=10)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument("--groups", type=int, default=4)
    a = p.parse_args(argv)
    if a.torn_acked:
        return torn_acked(a.fence, seed=a.seed or 31337,
                          groups=a.groups)
    return main(attempts=a.attempts, base_seed=a.seed or 424242)


if __name__ == "__main__":
    sys.exit(_cli())
